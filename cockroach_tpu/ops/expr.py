"""Scalar expression evaluation — fused selection/projection kernels.

Replaces the reference's generated selection/projection operators
(pkg/sql/colexec/colexecsel, colexecproj, colexecprojconst — one .eg.go kernel
per (operator, left type, right type) combination) with a single expression
tree walked inside a traced function: XLA fuses the whole expression into one
elementwise kernel over the tile, which is exactly what execgen's codegen was
approximating on CPU.

NULL semantics follow SQL three-valued logic (reference: the generated kernels'
null-handling in colexecproj + tree.DNull semantics): every node evaluates to
(data, valid); AND/OR implement Kleene logic.

Dictionary-coded strings: all string predicates (equality, LIKE, range) are
pre-evaluated per dictionary code on the host at plan time and become a
CodeLookup gather on device (see coldata.Dictionary).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from ..coldata.types import BOOL, DATE, FLOAT64, INT64, Family, Schema, SQLType

# ---------------------------------------------------------------------------
# Expression tree


class Expr:
    pass


@dataclass(frozen=True)
class ColRef(Expr):
    idx: int


@dataclass(frozen=True)
class Const(Expr):
    value: Any
    type: SQLType


@dataclass(frozen=True)
class Param(Expr):
    """A runtime-bound literal slot (the prepared-statement placeholder).

    The prepared-plan cache (sql/plancache.py) rewrites numeric Consts in
    filter predicates into Params so the literal becomes a jit ARGUMENT
    read from the active ``param_scope`` at trace time — a repeat query
    with different literals reuses the cached executables with zero new
    traces. Values arrive pre-scaled for DECIMAL (host-side, at bind)."""

    slot: int
    type: SQLType


@dataclass(frozen=True)
class BinOp(Expr):
    op: str  # + - * /
    left: Expr
    right: Expr


@dataclass(frozen=True)
class Cmp(Expr):
    op: str  # lt le gt ge eq ne
    left: Expr
    right: Expr


@dataclass(frozen=True)
class BoolOp(Expr):
    op: str  # and / or
    args: tuple[Expr, ...]


@dataclass(frozen=True)
class Not(Expr):
    arg: Expr


@dataclass(frozen=True)
class IsNull(Expr):
    arg: Expr
    negate: bool = False


@dataclass(frozen=True, eq=False)
class CodeLookup(Expr):
    """Gather `table[code]` for a dictionary-coded column: the device half of a
    host-prepared string operation (predicate table, rank table, hash table)."""

    col: int
    table: np.ndarray = field(hash=False)
    out_type: SQLType = BOOL


@dataclass(frozen=True)
class Case(Expr):
    whens: tuple[tuple[Expr, Expr], ...]
    otherwise: Expr


@dataclass(frozen=True)
class Cast(Expr):
    arg: Expr
    to: SQLType


@dataclass(frozen=True)
class ExtractYear(Expr):
    arg: Expr  # DATE


@dataclass(frozen=True)
class Func1(Expr):
    """Unary scalar builtin over a numeric expr (sem/builtins surface,
    pkg/sql/sem/builtins/math_builtins.go): abs | ceil | floor | round |
    sign | sqrt | cbrt | exp | ln | log10 | trunc | degrees | radians |
    sin | cos | tan | cot | asin | acos | atan | sinh | cosh | tanh."""

    func: str
    arg: Expr


# the trig/analytic family: always FLOAT64-valued, with a domain mask
_FUNC1_FLOAT = {
    "sqrt": (jnp.sqrt, lambda x: x >= 0),
    "cbrt": (jnp.cbrt, None),
    "exp": (jnp.exp, None),
    "ln": (jnp.log, lambda x: x > 0),
    "log10": (jnp.log10, lambda x: x > 0),
    "degrees": (jnp.degrees, None),
    "radians": (jnp.radians, None),
    "sin": (jnp.sin, None),
    "cos": (jnp.cos, None),
    "tan": (jnp.tan, None),
    "cot": (lambda x: 1.0 / jnp.tan(x), lambda x: jnp.tan(x) != 0),
    "asin": (jnp.arcsin, lambda x: jnp.abs(x) <= 1),
    "acos": (jnp.arccos, lambda x: jnp.abs(x) <= 1),
    "atan": (jnp.arctan, None),
    "sinh": (jnp.sinh, None),
    "cosh": (jnp.cosh, None),
    "tanh": (jnp.tanh, None),
}


@dataclass(frozen=True)
class Func2(Expr):
    """Binary scalar builtin (pow | mod | div | atan2 | round2 — round2 is
    round(x, n) with literal n; see builtins.go round/pow/mod/div)."""

    func: str
    left: Expr
    right: Expr


@dataclass(frozen=True)
class ExtractPart(Expr):
    """EXTRACT(part FROM date) over DATE (days since epoch): year | month |
    day | quarter | dow | isodow | doy | epoch | decade | century |
    millennium (sem/tree's extractTimeSpanFromDate)."""

    part: str
    arg: Expr


EXTRACT_PARTS = ("year", "month", "day", "quarter", "dow", "isodow",
                 "doy", "epoch", "decade", "century", "millennium")


@dataclass(frozen=True)
class Greatest(Expr):
    """GREATEST/LEAST(a, b, ...): extreme of the NON-NULL arguments
    (NULL only when every argument is NULL — Postgres semantics)."""

    args: tuple[Expr, ...]
    is_least: bool = False


@dataclass(frozen=True)
class Coalesce(Expr):
    """COALESCE(a, b, ...): first non-NULL argument."""

    args: tuple[Expr, ...]


def lit(value: Any, t: SQLType | None = None) -> Const:
    if t is None:
        if isinstance(value, bool):
            t = BOOL
        elif isinstance(value, (int, np.integer)):
            t = INT64
        elif isinstance(value, float):
            t = FLOAT64
        else:
            raise TypeError(f"cannot infer literal type for {value!r}")
    return Const(value, t)


def and_(*args: Expr) -> Expr:
    return BoolOp("and", tuple(args))


def or_(*args: Expr) -> Expr:
    return BoolOp("or", tuple(args))


def between(e: Expr, lo: Expr, hi: Expr) -> Expr:
    return and_(Cmp("ge", e, lo), Cmp("le", e, hi))


# ---------------------------------------------------------------------------
# Parameter scope (prepared-plan literal rebinding)

_PARAM_SCOPE = threading.local()


class param_scope:
    """Context manager installing the positional parameter values a traced
    predicate's Param leaves read. Thread-local (concurrent sessions trace
    on their own threads) and re-entrant (inner scope shadows outer)."""

    def __init__(self, values):
        self._values = tuple(values)

    def __enter__(self):
        self._prev = getattr(_PARAM_SCOPE, "values", None)
        _PARAM_SCOPE.values = self._values
        return self

    def __exit__(self, *exc):
        _PARAM_SCOPE.values = self._prev
        return False


def param_value(slot: int):
    values = getattr(_PARAM_SCOPE, "values", None)
    if values is None:
        raise RuntimeError(
            "Param evaluated outside a param_scope — parameterized "
            "predicates only run through operators built with a ParamStore"
        )
    return values[slot]


# ---------------------------------------------------------------------------
# Type inference


def expr_type(e: Expr, schema: Schema) -> SQLType:
    if isinstance(e, ColRef):
        return schema.types[e.idx]
    if isinstance(e, Const):
        return e.type
    if isinstance(e, Param):
        return e.type
    if isinstance(e, (Cmp, BoolOp, Not, IsNull)):
        return BOOL
    if isinstance(e, CodeLookup):
        return e.out_type
    if isinstance(e, Cast):
        return e.to
    if isinstance(e, ExtractYear):
        return INT64
    if isinstance(e, Func1):
        at = expr_type(e.arg, schema)
        if e.func in _FUNC1_FLOAT:
            return FLOAT64
        if e.func in ("ceil", "floor", "round", "trunc"):
            return INT64 if at.family in (Family.INT,) else at
        if e.func == "sign":
            return INT64
        return at  # abs keeps the input type
    if isinstance(e, Func2):
        if e.func in ("pow", "atan2"):
            return FLOAT64
        if e.func in ("mod", "div"):
            lt = expr_type(e.left, schema)
            if lt.family is Family.FLOAT:
                return FLOAT64
            return INT64
        if e.func == "round2":
            return expr_type(e.left, schema)
        raise TypeError(f"unknown builtin {e.func}")
    if isinstance(e, ExtractPart):
        return INT64
    if isinstance(e, Greatest):
        ts = [expr_type(a, schema) for a in e.args]
        fams = {t.family for t in ts}
        # single-family INT/BOOL/DATE compare on their raw representation;
        # same-scale DECIMALs compare exactly as scaled ints
        if fams in ({Family.INT}, {Family.BOOL}, {Family.DATE}):
            return ts[0]
        if fams == {Family.DECIMAL} and len({t.scale for t in ts}) == 1:
            return ts[0]
        if fams <= {Family.INT, Family.FLOAT, Family.DECIMAL}:
            # mixed numeric representations: compare in float64 space
            return FLOAT64
        # BOOL/DATE mixed with numerics has no sane unification
        # (Postgres rejects it too)
        raise TypeError(
            f"greatest/least cannot unify argument families {fams}"
        )
    if isinstance(e, Coalesce):
        return expr_type(e.args[0], schema)
    if isinstance(e, Case):
        return expr_type(e.whens[0][1], schema)
    if isinstance(e, BinOp):
        lt, rt = expr_type(e.left, schema), expr_type(e.right, schema)
        return _binop_type(e.op, lt, rt)
    raise TypeError(f"unknown expr {e}")


def _binop_type(op: str, lt: SQLType, rt: SQLType) -> SQLType:
    fams = (lt.family, rt.family)
    if Family.FLOAT in fams or op == "/":
        return FLOAT64
    if Family.DECIMAL in fams:
        ls = lt.scale if lt.family is Family.DECIMAL else 0
        rs = rt.scale if rt.family is Family.DECIMAL else 0
        scale = ls + rs if op == "*" else max(ls, rs)
        return SQLType(Family.DECIMAL, precision=38, scale=scale)
    if Family.DATE in fams:
        return DATE
    return INT64


# ---------------------------------------------------------------------------
# Evaluation (inside trace)


def expr_bounds(e: Expr, schema: Schema, col_stats: dict) -> tuple | None:
    """(lo, hi) value bounds of an integer-family expression, derived from
    input column stats — the statistics-propagation analog of the
    reference's statisticsBuilder (opt/memo/statistics_builder.go) applied
    to scalar projections, so dense-key planning (aggregation slots, packed
    join keys, sort operands) survives computed columns like
    EXTRACT(YEAR FROM o_orderdate)."""
    if isinstance(e, ColRef):
        s = col_stats.get(e.idx)
        return None if s is None else (int(s[0]), int(s[1]))
    if isinstance(e, Const):
        try:
            v = int(e.value)
        except (TypeError, ValueError):
            return None
        return (v, v)
    if isinstance(e, ExtractYear):
        b = expr_bounds(e.arg, schema, col_stats)
        if b is None:
            return None
        return (_year_of_day(b[0]), _year_of_day(b[1]))
    if isinstance(e, BinOp) and e.op in ("+", "-", "*"):
        lt = expr_type(e.left, schema)
        rt = expr_type(e.right, schema)
        # DECIMAL arithmetic rescales operands (scale alignment /
        # multiplication scale growth) — raw bounds would be in the wrong
        # units; only plain integer/date arithmetic propagates
        if (lt.family in (Family.FLOAT, Family.DECIMAL)
                or rt.family in (Family.FLOAT, Family.DECIMAL)):
            return None
        lb = expr_bounds(e.left, schema, col_stats)
        rb = expr_bounds(e.right, schema, col_stats)
        if lb is None or rb is None:
            return None
        if e.op == "+":
            return (lb[0] + rb[0], lb[1] + rb[1])
        if e.op == "-":
            return (lb[0] - rb[1], lb[1] - rb[0])
        prods = [a * b for a in lb for b in rb]
        return (min(prods), max(prods))
    if isinstance(e, Cast):
        if e.to.family in (Family.FLOAT, Family.STRING, Family.BYTES):
            return None
        # int-to-int casts preserve value bounds (the cast matrix rounds
        # DECIMAL scale changes; bounds stay conservative by using both)
        b = expr_bounds(e.arg, schema, col_stats)
        ft = expr_type(e.arg, schema)
        if b is None or ft.family is Family.FLOAT:
            return None
        if ft.family is Family.DECIMAL or e.to.family is Family.DECIMAL:
            return None  # scale changes rescale values; skip
        return b
    return None


def _year_of_day(days: int) -> int:
    import datetime

    return (datetime.date(1970, 1, 1)
            + datetime.timedelta(days=int(days))).year


def eval_expr(e: Expr, cols, schema: Schema):
    """Evaluate e over a batch's columns -> (data, valid). `cols` is the tuple
    of Column; arrays are full-tile, mask applied by the caller."""
    if isinstance(e, ColRef):
        c = cols[e.idx]
        return c.data, c.valid

    if isinstance(e, Const):
        n = cols[0].data.shape[0]
        if e.value is None:
            from ..coldata.types import zeros_like_type

            return (
                zeros_like_type(e.type, n),  # BYTES needs [n, W]
                jnp.zeros((n,), jnp.bool_),
            )
        v = e.value
        if e.type.family is Family.DECIMAL:
            v = int(round(float(v) * 10**e.type.scale))
        return (
            jnp.full((n,), v, dtype=e.type.dtype),
            jnp.ones((n,), jnp.bool_),
        )

    if isinstance(e, Param):
        # the value is a traced argument (see param_scope), NOT a baked
        # constant — rebinding it later never invalidates the executable
        n = cols[0].data.shape[0]
        v = param_value(e.slot)
        data = jnp.broadcast_to(
            jnp.asarray(v).astype(e.type.dtype), (n,))
        return data, jnp.ones((n,), jnp.bool_)

    if isinstance(e, CodeLookup):
        c = cols[e.col]
        table = jnp.asarray(e.table)
        codes = jnp.clip(c.data, 0, table.shape[0] - 1)
        data = table[codes].astype(e.out_type.dtype)
        return data, c.valid

    if isinstance(e, Cast):
        d, v = eval_expr(e.arg, cols, schema)
        ft = expr_type(e.arg, schema)
        return _cast(d, ft, e.to), v

    if isinstance(e, ExtractYear):
        d, v = eval_expr(e.arg, cols, schema)
        if expr_type(e.arg, schema).family is Family.TIMESTAMP:
            d = d.astype(jnp.int64) // (86400 * 1000000)
        return _year_from_days(d), v

    if isinstance(e, Func1):
        d, v = eval_expr(e.arg, cols, schema)
        at = expr_type(e.arg, schema)
        scale = 10 ** at.scale if at.family is Family.DECIMAL else 1
        if e.func == "abs":
            return jnp.abs(d), v
        if e.func == "sign":
            return jnp.sign(d).astype(jnp.int64), v
        if e.func in ("ceil", "floor", "round"):
            if at.family is Family.FLOAT:
                f = {"ceil": jnp.ceil, "floor": jnp.floor,
                     "round": jnp.round}[e.func]
                return f(d), v
            if at.family is Family.DECIMAL:
                # stay in scaled-int space: exact, no float round-trip
                q, r = d // scale, d % scale
                if e.func == "ceil":
                    out = (q + (r > 0)) * scale
                elif e.func == "floor":
                    out = q * scale
                else:  # round half away from zero (SQL numeric rounding)
                    out = _div_half_away(d, scale) * scale
                return out, v
            return d, v  # ints are already integral
        if e.func == "trunc":
            if at.family is Family.FLOAT:
                return jnp.trunc(d), v
            if at.family is Family.DECIMAL:
                q = jnp.where(d >= 0, d // scale, -((-d) // scale))
                return q * scale, v
            return d, v
        f64 = d.astype(jnp.float64) / scale
        if e.func in _FUNC1_FLOAT:
            fn, domain = _FUNC1_FLOAT[e.func]
            ok = v if domain is None else v & domain(f64)
            return fn(jnp.where(ok, f64, 1.0)), ok
        raise ValueError(f"unknown builtin {e.func}")

    if isinstance(e, Func2):
        lt, rt = expr_type(e.left, schema), expr_type(e.right, schema)
        ld, lv = eval_expr(e.left, cols, schema)
        rd, rv = eval_expr(e.right, cols, schema)
        valid = lv & rv
        if e.func in ("pow", "atan2"):
            lf, rf = _to_float(ld, lt), _to_float(rd, rt)
            if e.func == "atan2":
                return jnp.arctan2(lf, rf), valid
            out = jnp.power(lf, rf)
            # pow(0, negative) and negative**fractional are SQL errors;
            # surface them as NULL (the engine's error-as-NULL policy for
            # value-dependent domain faults)
            return jnp.where(jnp.isfinite(out), out, 0.0), \
                valid & jnp.isfinite(out)
        if e.func in ("mod", "div"):
            if lt.family is Family.FLOAT or rt.family is Family.FLOAT:
                lf, rf = _to_float(ld, lt), _to_float(rd, rt)
                ok = valid & (rf != 0)
                rf = jnp.where(rf == 0, 1.0, rf)
                q = jnp.trunc(lf / rf)
                return (lf - q * rf if e.func == "mod" else q), ok
            li, ri = ld.astype(jnp.int64), rd.astype(jnp.int64)
            ok = valid & (ri != 0)
            ri = jnp.where(ri == 0, 1, ri)
            # SQL mod/div truncate toward zero; the remainder takes the
            # DIVIDEND's sign (Postgres mod(7,-3)=1, mod(-7,3)=-1).
            # floor-div + sign fixup keeps everything exact in int64
            qf = li // ri
            r = li - qf * ri
            q = qf + ((r != 0) & ((li < 0) != (ri < 0)))
            return (li - q * ri if e.func == "mod" else q), ok
        if e.func == "round2":
            n = int(e.right.value)  # binder guarantees a literal
            if lt.family is Family.FLOAT:
                p = 10.0 ** n
                return jnp.round(ld * p) / p, valid
            if lt.family is Family.DECIMAL:
                if n >= lt.scale:
                    return ld, valid
                p = 10 ** (lt.scale - n)
                return _div_half_away(ld, p) * p, valid
            if n >= 0:
                return ld, valid
            p = 10 ** (-n)
            return _div_half_away(ld, p) * p, valid
        raise ValueError(f"unknown builtin {e.func}")

    if isinstance(e, ExtractPart):
        d, v = eval_expr(e.arg, cols, schema)
        d = d.astype(jnp.int64)
        if expr_type(e.arg, schema).family is Family.TIMESTAMP:
            if e.part == "epoch":
                return d // 1000000, v
            d = d // (86400 * 1000000)
        return _extract_part(e.part, d), v

    if isinstance(e, Greatest):
        out_t = expr_type(e, schema)

        def as_out(arg):
            dd, vv = eval_expr(arg, cols, schema)
            at = expr_type(arg, schema)
            if out_t.family is Family.FLOAT:
                dd = _to_float(dd, at)  # DECIMAL scales divide out here
            elif dd.dtype != out_t.dtype:
                dd = _cast(dd, at, out_t)
            return dd, vv

        d, v = as_out(e.args[0])
        pick = jnp.minimum if e.is_least else jnp.maximum
        for a in e.args[1:]:
            d1, v1 = as_out(a)
            both = v & v1
            ext = pick(d, d1)
            d = jnp.where(both, ext, jnp.where(v, d, d1))
            v = v | v1
        return d, v

    if isinstance(e, Coalesce):
        d, v = eval_expr(e.args[0], cols, schema)
        for a in e.args[1:]:
            d1, v1 = eval_expr(a, cols, schema)
            d = jnp.where(v, d, d1.astype(d.dtype))
            v = v | v1
        return d, v

    if isinstance(e, IsNull):
        _, v = eval_expr(e.arg, cols, schema)
        out = v if e.negate else ~v
        return out, jnp.ones_like(v)

    if isinstance(e, Not):
        d, v = eval_expr(e.arg, cols, schema)
        return ~d, v

    if isinstance(e, BoolOp):
        d0, v0 = eval_expr(e.args[0], cols, schema)
        for a in e.args[1:]:
            d1, v1 = eval_expr(a, cols, schema)
            if e.op == "and":
                # Kleene AND: known-false if either side known-false;
                # known-true only if both sides known-true.
                t = (v0 & d0) & (v1 & d1)
                f = (v0 & ~d0) | (v1 & ~d1)
            else:
                t = (v0 & d0) | (v1 & d1)
                f = (v0 & ~d0) & (v1 & ~d1)
            d0, v0 = t, t | f
        return d0, v0

    if isinstance(e, Cmp):
        lt, rt = expr_type(e.left, schema), expr_type(e.right, schema)
        if e.op not in ("eq", "ne") and not (
            lt.comparable_on_device and rt.comparable_on_device
        ):
            # STRING range predicates must be planned as rank-table CodeLookups
            # (coldata.Dictionary.ranks); raw codes don't order by byte value.
            raise TypeError(
                f"range comparison on {lt}/{rt} requires a host-prepared rank "
                "table (plan a CodeLookup, not a raw Cmp)"
            )
        ld, lv = eval_expr(e.left, cols, schema)
        rd, rv = eval_expr(e.right, cols, schema)
        ld, rd = _align_numeric(ld, lt, rd, rt)
        fns = {
            "lt": jnp.less,
            "le": jnp.less_equal,
            "gt": jnp.greater,
            "ge": jnp.greater_equal,
            "eq": jnp.equal,
            "ne": jnp.not_equal,
        }
        return fns[e.op](ld, rd), lv & rv

    if isinstance(e, BinOp):
        lt, rt = expr_type(e.left, schema), expr_type(e.right, schema)
        ld, lv = eval_expr(e.left, cols, schema)
        rd, rv = eval_expr(e.right, cols, schema)
        out_t = _binop_type(e.op, lt, rt)
        valid = lv & rv
        if e.op == "/" or out_t.family is Family.FLOAT:
            lf = _to_float(ld, lt)
            rf = _to_float(rd, rt)
            if e.op == "/":
                valid = valid & (rf != 0)
                rf = jnp.where(rf == 0, 1.0, rf)
            fns = {
                "+": jnp.add,
                "-": jnp.subtract,
                "*": jnp.multiply,
                "/": jnp.divide,
            }
            return fns[e.op](lf, rf), valid
        if out_t.family is Family.DECIMAL:
            ls = lt.scale if lt.family is Family.DECIMAL else 0
            rs = rt.scale if rt.family is Family.DECIMAL else 0
            li, ri = ld.astype(jnp.int64), rd.astype(jnp.int64)
            if e.op == "*":
                return li * ri, valid
            s = max(ls, rs)
            li = li * (10 ** (s - ls))
            ri = ri * (10 ** (s - rs))
            return (li + ri if e.op == "+" else li - ri), valid
        fns = {"+": jnp.add, "-": jnp.subtract, "*": jnp.multiply}
        return fns[e.op](ld, rd).astype(out_t.dtype), valid

    if isinstance(e, Case):
        out_d, out_v = eval_expr(e.otherwise, cols, schema)
        # evaluate in reverse so earlier whens win
        for cond, val in reversed(e.whens):
            cd, cv = eval_expr(cond, cols, schema)
            vd, vv = eval_expr(val, cols, schema)
            take = cv & cd
            out_d = jnp.where(take, vd, out_d)
            out_v = jnp.where(take, vv, out_v)
        return out_d, out_v

    raise TypeError(f"cannot evaluate {e}")


def _align_numeric(ld, lt: SQLType, rd, rt: SQLType):
    """Bring two sides of a comparison to a common representation."""
    if Family.FLOAT in (lt.family, rt.family):
        return _to_float(ld, lt), _to_float(rd, rt)
    if Family.DECIMAL in (lt.family, rt.family):
        ls = lt.scale if lt.family is Family.DECIMAL else 0
        rs = rt.scale if rt.family is Family.DECIMAL else 0
        s = max(ls, rs)
        return (
            ld.astype(jnp.int64) * (10 ** (s - ls)),
            rd.astype(jnp.int64) * (10 ** (s - rs)),
        )
    return ld, rd


def _to_float(d, t: SQLType):
    if t.family is Family.DECIMAL:
        return d.astype(jnp.float64) / (10.0**t.scale)
    return d.astype(jnp.float64)


def _div_half_away(d, s: int):
    """Scaled-int division rounding half away from zero (SQL numeric
    rounding on precision reduction)."""
    pos = (d + s // 2) // s
    neg = -((-d + s // 2) // s)
    return jnp.where(d >= 0, pos, neg)


def _div_trunc(d, s: int):
    """Scaled-int division truncating toward zero (SQL cast to INT)."""
    return jnp.where(d >= 0, d // s, -((-d) // s))


def _cast(d, ft: SQLType, to: SQLType):
    if to.family is Family.FLOAT:
        return _to_float(d, ft)
    if to.family is Family.DECIMAL:
        if ft.family is Family.DECIMAL:
            diff = to.scale - ft.scale
            if diff >= 0:
                return d * (10**diff)
            return _div_half_away(d, 10**-diff)  # scale cut ROUNDS
        if ft.family is Family.FLOAT:
            return jnp.round(d * 10.0**to.scale).astype(jnp.int64)
        return d.astype(jnp.int64) * (10**to.scale)
    if to.family is Family.INT:
        if ft.family is Family.DECIMAL:
            # SQL casts numeric -> int by ROUNDING (Postgres semantics)
            return _div_half_away(d, 10**ft.scale).astype(to.dtype)
        if ft.family is Family.FLOAT:
            return jnp.round(d).astype(to.dtype)
        return d.astype(to.dtype)
    if to.family is Family.TIMESTAMP and ft.family is Family.DATE:
        return d.astype(jnp.int64) * (86400 * 1000000)
    if to.family is Family.DATE and ft.family is Family.TIMESTAMP:
        return (d // (86400 * 1000000)).astype(jnp.int32)
    if to.family is Family.BOOL:
        if ft.family is Family.DECIMAL:
            return d != 0
        return d.astype(jnp.bool_)
    return d.astype(to.dtype)


def _year_from_days(days):
    """Gregorian year from days-since-1970 (civil-from-days, integer only)."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    return jnp.where(m <= 2, y + 1, y)


def _civil_from_days(days):
    """(year, month, day, day-of-year) from days-since-1970 — Hinnant's
    civil_from_days, vectorized integer-only."""
    z = days.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy_mar = doe - (365 * yoe + yoe // 4 - yoe // 100)  # 0 = March 1
    mp = (5 * doy_mar + 2) // 153
    d = doy_mar - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    # calendar day-of-year (Jan 1 = 1)
    leap = ((y % 4 == 0) & (y % 100 != 0)) | (y % 400 == 0)
    jan_feb = jnp.where(m <= 2, 0, jnp.where(leap, 60, 59))
    doy = jnp.where(m <= 2,
                    d + jnp.where(m == 2, 31, 0),
                    doy_mar + 1 + jan_feb)
    return y, m, d, doy


def _extract_part(part: str, days):
    """EXTRACT(part FROM date) over days-since-epoch int64."""
    if part == "epoch":
        return days * 86400
    if part == "dow":  # 0 = Sunday (1970-01-01 was a Thursday)
        return (days + 4) % 7
    if part == "isodow":  # 1 = Monday .. 7 = Sunday
        return (days + 3) % 7 + 1
    y, m, d, doy = _civil_from_days(days)
    if part == "year":
        return y
    if part == "month":
        return m
    if part == "day":
        return d
    if part == "doy":
        return doy
    if part == "quarter":
        return (m - 1) // 3 + 1
    if part == "decade":
        return jnp.where(y >= 0, y, y - 9) // 10
    if part == "century":
        return jnp.where(y > 0, (y - 1) // 100 + 1, -((-y) // 100) - 1)
    if part == "millennium":
        return jnp.where(y > 0, (y - 1) // 1000 + 1, -((-y) // 1000) - 1)
    raise ValueError(f"unknown extract part {part}")


# ---------------------------------------------------------------------------
# Batch-level entry points


def filter_mask(batch, schema: Schema, predicate: Expr) -> jax.Array:
    """New liveness mask: old mask AND predicate is TRUE (not false/NULL)."""
    d, v = eval_expr(predicate, batch.cols, schema)
    return batch.mask & d & v
