"""Join kernels — the colexecjoin analog.

Reference: pkg/sql/colexec/colexecjoin/hashjoiner.go:165 builds a vectorized
chained hash table (colexechash.HashTable.FullBuild, hashtable.go:473) then
probes per batch. Pointer-chasing hash chains don't map to TPU, so the build
becomes *sort by 64-bit key hash* and the probe becomes *vectorized binary
search* (log2(n) gathers of the whole probe tile) + a short collision-advance
loop. Two probe paths:

- ``hash_join_unique``: build keys are unique (FK->PK joins — most TPC-H
  joins). Output is probe-aligned, fully static shapes: inner / left-outer /
  semi / anti.
- ``hash_join_general``: duplicate build keys; per-probe match counts + a
  bounded emission loop into a caller-sized output tile (capacity bucketing:
  the host re-invokes with the next power-of-two capacity on overflow —
  reported via the returned total). This mirrors how the reference's probe
  emits variable-size output batches per input batch.

SQL semantics: NULL join keys never match (NULL != NULL); anti-join keeps
NULL-key probe rows (NOT EXISTS semantics, matching CRDB's anti join).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..coldata.batch import Batch, Column
from ..coldata.types import Family, Schema
from .hashing import hash_columns

_SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)


@dataclass(frozen=True)
class JoinSpec:
    join_type: str = "inner"  # inner | left | semi | anti
    build_unique: bool = True


# ---------------------------------------------------------------------------
# Exact packed join keys
#
# When every join-key column has known bounds (catalog stats for ints/dates/
# decimals; dictionary size for strings), the multi-column key bit-packs
# EXACTLY into one uint64. Key equality then IS packed-word equality: the
# probe needs no hash, no collision-advance while_loop and no per-column
# key-verification gathers — on TPU that turns the probe into straight-line
# gathers, an order of magnitude cheaper to XLA-compile than control flow.
# The hash path below remains the fallback for unbounded keys.


@dataclass(frozen=True)
class ExactKeyLayout:
    """Per key position: (kind, lo, bits). kind 'int' encodes (x - lo);
    kind 'str' uses probe dictionary codes (build codes remapped host-side,
    absent values -> the never-matching code 2**bits - 1)."""

    segs: tuple[tuple[str, int, int], ...]
    total_bits: int


def plan_exact_key(
    probe_schema: Schema,
    probe_keys: tuple[int, ...],
    build_schema: Schema,
    build_keys: tuple[int, ...],
    probe_stats: dict | None,
    build_stats: dict | None,
    probe_dict_sizes: dict | None,
    have_remaps: bool,
) -> ExactKeyLayout | None:
    """Try to plan an exact packed key; None when any column is unbounded."""
    from .keys import bits_for_count

    probe_stats = probe_stats or {}
    build_stats = build_stats or {}
    probe_dict_sizes = probe_dict_sizes or {}
    segs = []
    total = 0
    for pk, bk in zip(probe_keys, build_keys):
        t = probe_schema.types[pk]
        if t.family is Family.STRING:
            if not have_remaps or pk not in probe_dict_sizes:
                return None
            n = probe_dict_sizes[pk]
            bits = bits_for_count(n + 2)  # probe codes + absent sentinel
            segs.append(("str", 0, bits))
        elif t.family in (Family.FLOAT, Family.BYTES, Family.JSON):
            return None
        elif t.family is Family.BOOL:
            segs.append(("int", 0, 1))
            bits = 1
        else:
            ps = probe_stats.get(pk)
            bs = build_stats.get(bk)
            if ps is None or bs is None:
                return None
            lo = min(int(ps[0]), int(bs[0]))
            hi = max(int(ps[1]), int(bs[1]))
            bits = bits_for_count(hi - lo + 1)
            segs.append(("int", lo, bits))
        total += segs[-1][2]
    if total > 63:
        return None
    return ExactKeyLayout(tuple(segs), total)


def exact_keys(
    batch: Batch,
    keys: tuple[int, ...],
    layout: ExactKeyLayout,
    code_remaps: dict | None = None,
) -> tuple[jax.Array, jax.Array]:
    """(packed u64 key, active) — NULL-key and dead rows get the sentinel
    (which no packed key can equal: total_bits <= 63)."""
    k = jnp.zeros((batch.capacity,), jnp.uint64)
    active = batch.mask
    for pos, (ki, (kind, lo, bits)) in enumerate(zip(keys, layout.segs)):
        c = batch.cols[ki]
        active = active & c.valid
        if kind == "str":
            v = c.data.astype(jnp.int64)
            if code_remaps is not None and pos in code_remaps:
                remap = jnp.asarray(code_remaps[pos]).astype(jnp.int64)
                v = remap[jnp.clip(v, 0, remap.shape[0] - 1)]
            # absent-in-probe-dict (-1) -> the never-matching top code
            v = jnp.where(v < 0, jnp.int64((1 << bits) - 1), v)
        else:
            v = c.data.astype(jnp.int64) - lo
        k = (k << np.uint64(bits)) | (
            v.astype(jnp.uint64) & jnp.uint64((1 << bits) - 1)
        )
    return jnp.where(active, k, _SENTINEL), active


# ---------------------------------------------------------------------------
# Dense direct addressing
#
# The reference's hash table (colexechash) exists because Go can chase
# pointers; the first TPU design replaced it with sort + unrolled binary
# search (log2(n) dependent gathers per probe — ~20 x 7.5ms per 1M-row tile
# on v5e, the measured join bottleneck). When the build key's VALUE RANGE is
# dense, addressing is direct instead:
#
# - 'analytic': the build side is a position-preserving chain over a resident
#   table whose key column IS (an offset of) the row index — true for every
#   TPC-H PK (o_orderkey = 1..N, p_partkey = 1..N, ...) and for clustered
#   child tables (partsupp: 4 rows per part, contiguous). Probe cost: ONE
#   gather of the build liveness mask (+ fanout-1 verification gathers).
#   Build cost: ZERO — no sort, no spool sync, no hash table at all.
# - 'lut': the packed exact key (plan_exact_key) fits in few bits; a dense
#   int32 position table is scatter-built ONCE from the (compacted, usually
#   small) build spool. Probe cost: one gather. Build cost: one scatter of
#   build-side size.
#
# Both paths are exact (no hash, no collision handling): key equality is
# index equality by construction.


@dataclass(frozen=True)
class DenseAnalytic:
    """Probe row index = (first_key - key_lo) * fanout + j, j in [0, fanout).
    verify: remaining key positions needing equality checks (all but the
    first when fanout > 1 or multi-column keys)."""

    key_lo: int
    fanout: int
    build_rows: int  # fanout * number-of-distinct-first-keys (live prefix)


def dense_analytic_probe(
    probe: Batch,
    probe_keys: tuple[int, ...],
    build: Batch,
    build_keys: tuple[int, ...],
    info: DenseAnalytic,
    build_code_remaps=None,
):
    """(found_idx, found) for unique-build joins via direct addressing."""
    k0 = probe.cols[probe_keys[0]]
    base = (k0.data.astype(jnp.int64) - info.key_lo) * info.fanout
    active = probe.mask & k0.valid
    in_range = active & (base >= 0) & (base < info.build_rows)
    base_c = jnp.clip(base, 0, build.capacity - 1).astype(jnp.int32)
    rest_p = probe_keys[1:]
    rest_b = build_keys[1:]
    rest_remaps = None
    if build_code_remaps:
        rest_remaps = {
            pos - 1: r for pos, r in build_code_remaps.items() if pos >= 1
        }
    found = jnp.zeros((probe.capacity,), jnp.bool_)
    found_idx = jnp.zeros((probe.capacity,), jnp.int32)
    for j in range(info.fanout):
        idx = jnp.minimum(base_c + j, build.capacity - 1)
        ok = in_range & build.mask[idx]
        if rest_p:
            ok = ok & _keys_equal(
                probe, rest_p, build, rest_b, idx, rest_remaps
            )
        found_idx = jnp.where(ok & ~found, idx, found_idx)
        found = found | ok
    return found_idx, found


def build_dense_lut(
    build: Batch,
    build_keys: tuple[int, ...],
    layout: ExactKeyLayout,
    exact_remaps=None,
) -> jax.Array:
    """[2**total_bits] int32 build positions (-1 absent). Dead/NULL rows
    carry the u64 sentinel key and drop out of the scatter."""
    bk, _ = exact_keys(build, build_keys, layout, exact_remaps)
    lut = jnp.full((1 << layout.total_bits,), -1, jnp.int32)
    pos = jnp.arange(build.capacity, dtype=jnp.int32)
    return lut.at[bk].set(pos, mode="drop")


def dense_lut_probe(
    probe: Batch,
    probe_keys: tuple[int, ...],
    layout: ExactKeyLayout,
    lut: jax.Array,
):
    """(found_idx, found): one gather; packed-key equality IS key equality."""
    ph, p_active = exact_keys(probe, probe_keys, layout)
    size = lut.shape[0]
    phc = jnp.clip(ph, jnp.uint64(0), jnp.uint64(size - 1)).astype(jnp.int32)
    idx = lut[phc]
    found = p_active & (ph < size) & (idx >= 0)
    return jnp.maximum(idx, 0), found


def emit_unique(probe: Batch, build: Batch, spec: JoinSpec,
                found_idx, found) -> Batch:
    """Probe-aligned emission shared by every unique-build probe strategy
    (dense analytic / dense LUT / sorted bsearch)."""
    if spec.join_type == "semi":
        return probe.with_mask(probe.mask & found)
    if spec.join_type == "anti":
        return probe.with_mask(probe.mask & ~found)
    bcols = tuple(
        Column(data=c.data[found_idx], valid=c.valid[found_idx] & found)
        for c in build.cols
    )
    cols = probe.cols + bcols
    if spec.join_type == "inner":
        mask = probe.mask & found
    elif spec.join_type == "left":
        mask = probe.mask
    else:
        raise ValueError(f"unsupported join type {spec.join_type}")
    return Batch(cols=cols, mask=mask)


def bsearch(sorted_u64: jax.Array, queries: jax.Array,
            side: str = "left") -> jax.Array:
    """Branchless UNROLLED binary search (log2(n) static gather+select
    steps). Replaces jnp.searchsorted, whose lax.scan lowering is far more
    expensive for XLA:TPU to compile inside fused query kernels."""
    n = sorted_u64.shape[0]
    # n.bit_length() (not n-1): the insertion point ranges over [0, n]
    # INCLUSIVE, and a power-of-two n needs the extra step to reach n when
    # the query is >= the last element (otherwise the final matching build
    # row of a fully-live power-of-two batch is silently dropped)
    bits = max(1, int(n).bit_length())
    pos = jnp.zeros(queries.shape, jnp.int32)
    for sb in range(bits - 1, -1, -1):
        cand = pos + (1 << sb)
        v = sorted_u64[jnp.clip(cand - 1, 0, n - 1)]
        if side == "left":
            ok = (cand <= n) & (v < queries)
        else:
            ok = (cand <= n) & (v <= queries)
        pos = jnp.where(ok, cand, pos)
    return pos


def _key_hashes(batch: Batch, keys: tuple[int, ...], schema: Schema, hash_tables):
    cols = [batch.cols[i] for i in keys]
    types = [schema.types[i] for i in keys]
    h = hash_columns(cols, types, hash_tables)
    all_valid = batch.mask
    for c in cols:
        all_valid = all_valid & c.valid
    # rows that can never match: dead, or any NULL key
    return jnp.where(all_valid, h, _SENTINEL), all_valid


def _keys_equal(probe: Batch, pkeys, build: Batch, bkeys, bidx, build_remaps=None):
    """Exact key equality probe[i] == build[bidx[i]] per row.

    build_remaps: {key position -> np.ndarray} host-prepared remap of build
    dictionary codes into the probe column's dictionary code space (-1 when
    the value is absent there), so STRING equality is exact across tables
    with different dictionaries."""
    build_remaps = build_remaps or {}
    eq = jnp.ones((probe.capacity,), jnp.bool_)
    for pos, (pk, bk) in enumerate(zip(pkeys, bkeys)):
        pc = probe.cols[pk]
        bc = build.cols[bk]
        bdata = bc.data[bidx]
        if pos in build_remaps:
            remap = jnp.asarray(build_remaps[pos])
            bdata = remap[jnp.clip(bdata, 0, remap.shape[0] - 1)]
        eq = eq & (pc.data == bdata) & pc.valid & bc.valid[bidx]
    return eq


def build_index(
    build: Batch, schema: Schema, keys: tuple[int, ...], hash_tables=None,
    exact_layout: ExactKeyLayout | None = None, exact_remaps=None,
):
    """Sort build rows by key (exact packed key when the layout allows, else
    64-bit hash) -> (sorted_keys, orig_index). NULL-key and dead rows get
    the max sentinel and sort to the end."""
    if exact_layout is not None:
        if (exact_remaps is None
                and any(k == "str" for k, _, _ in exact_layout.segs)):
            raise ValueError(
                "exact STRING join keys need build-code remaps (pass "
                "exact_remaps or a precomputed index)"
            )
        bh, _ = exact_keys(build, keys, exact_layout, exact_remaps)
    else:
        bh, _ = _key_hashes(build, keys, schema, hash_tables)
    perm = jnp.arange(build.capacity, dtype=jnp.int32)
    sh, order = jax.lax.sort([bh, perm], num_keys=1)
    return sh, order


def _probe_positions(sh, ph):
    return bsearch(sh, ph, side="left")


def hash_join_unique(
    probe: Batch,
    probe_schema: Schema,
    probe_keys: tuple[int, ...],
    build: Batch,
    build_schema: Schema,
    build_keys: tuple[int, ...],
    spec: JoinSpec,
    probe_hash_tables=None,
    build_hash_tables=None,
    build_code_remaps=None,
    index=None,
    exact_layout: ExactKeyLayout | None = None,
    exact_remaps=None,
) -> Batch:
    """Join with unique build keys. Output tile is probe-capacity:
    probe columns followed by build columns (semi/anti: probe columns only).
    `index` is an optional precomputed build_index() result so the build-side
    sort runs once per build batch, not once per probe tile.

    With an exact_layout the probe is control-flow-free: one unrolled binary
    search + one equality compare (packed-key equality IS key equality).
    The hash path verifies columns and advances past 64-bit collisions."""
    cap = probe.capacity
    bcap = build.capacity
    sh, order = index if index is not None else build_index(
        build, build_schema, build_keys, build_hash_tables,
        exact_layout=exact_layout, exact_remaps=exact_remaps,
    )
    if exact_layout is not None:
        ph, p_active = exact_keys(probe, probe_keys, exact_layout)
        pos = _probe_positions(sh, ph)
        posc = jnp.clip(pos, 0, bcap - 1)
        found_idx = order[posc]
        found = (pos < bcap) & (sh[posc] == ph) & p_active
        found = found & build.mask[found_idx]
    else:
        ph, p_active = _key_hashes(
            probe, probe_keys, probe_schema, probe_hash_tables
        )
        pos = _probe_positions(sh, jnp.where(p_active, ph, _SENTINEL))

        def cond(state):
            _, _, active, _ = state
            return jnp.any(active)

        def body(state):
            pos, found_idx, active, found = state
            inb = pos < bcap
            posc = jnp.clip(pos, 0, bcap - 1)
            bidx = order[posc]
            hash_eq = inb & (sh[posc] == ph) & active
            key_eq = _keys_equal(
                probe, probe_keys, build, build_keys, bidx, build_code_remaps
            )
            hit = hash_eq & key_eq
            found_idx = jnp.where(hit, bidx, found_idx)
            found = found | hit
            # advance only on hash collision with key mismatch
            advance = hash_eq & ~key_eq
            return pos + advance, found_idx, advance, found

        init = (
            pos,
            jnp.zeros((cap,), jnp.int32),
            p_active,
            jnp.zeros((cap,), jnp.bool_),
        )
        _, found_idx, _, found = jax.lax.while_loop(cond, body, init)
        # guard against sentinel-hash self-matches
        found = found & p_active & build.mask[found_idx]

    return emit_unique(probe, build, spec, found_idx, found)


def hash_join_general(
    probe: Batch,
    probe_schema: Schema,
    probe_keys: tuple[int, ...],
    build: Batch,
    build_schema: Schema,
    build_keys: tuple[int, ...],
    spec: JoinSpec,
    out_capacity: int,
    probe_hash_tables=None,
    build_hash_tables=None,
    build_code_remaps=None,
    index=None,
    exact_layout: ExactKeyLayout | None = None,
    exact_remaps=None,
):
    """General join (duplicate build keys). Returns (out_batch, total_rows);
    if total_rows > out_capacity the caller must retry with a larger tile
    (capacity bucketing keeps shapes static per bucket)."""
    cap = probe.capacity
    bcap = build.capacity
    sh, order = index if index is not None else build_index(
        build, build_schema, build_keys, build_hash_tables,
        exact_layout=exact_layout, exact_remaps=exact_remaps,
    )
    if exact_layout is not None:
        ph, p_active = exact_keys(probe, probe_keys, exact_layout)
        phs = ph
    else:
        ph, p_active = _key_hashes(
            probe, probe_keys, probe_schema, probe_hash_tables
        )
        phs = jnp.where(p_active, ph, _SENTINEL)
    lo = bsearch(sh, phs, side="left")
    hi = bsearch(sh, phs, side="right")
    run = jnp.where(p_active, hi - lo, 0)
    max_run = jnp.max(run)

    def key_eq_at(k):
        posc = jnp.clip(lo + k, 0, bcap - 1)
        bidx = order[posc]
        valid_k = (k < run) & p_active & build.mask[bidx]
        if exact_layout is not None:
            # packed-key equality is exact: the [lo, hi) run IS the match set
            return bidx, valid_k
        return bidx, valid_k & _keys_equal(
            probe, probe_keys, build, build_keys, bidx, build_code_remaps
        )

    # phase 1: count real key matches per probe row
    def count_body(state):
        k, cnt = state
        _, eq = key_eq_at(k)
        return k + 1, cnt + eq.astype(jnp.int32)

    _, cnt = jax.lax.while_loop(
        lambda s: s[0] < max_run,
        count_body,
        (jnp.int32(0), jnp.zeros((cap,), jnp.int32)),
    )

    left = spec.join_type == "left"
    if spec.join_type == "semi":
        return probe.with_mask(probe.mask & (cnt > 0)), jnp.sum(cnt > 0)
    if spec.join_type == "anti":
        return probe.with_mask(probe.mask & (cnt == 0)), jnp.sum(cnt == 0)

    out_rows = jnp.where(left & probe.mask, jnp.maximum(cnt, 1), cnt)
    base = jnp.cumsum(out_rows) - out_rows  # exclusive prefix
    total = jnp.sum(out_rows)

    OC = out_capacity
    out_pidx = jnp.zeros((OC,), jnp.int32)
    out_bidx = jnp.zeros((OC,), jnp.int32)
    out_found = jnp.zeros((OC,), jnp.bool_)
    out_live = jnp.zeros((OC,), jnp.bool_)

    if left:
        # unmatched probe rows emit one null-extended row at their base slot
        unmatched = probe.mask & (cnt == 0)
        dest0 = jnp.where(unmatched, base.astype(jnp.int32), OC)
        out_pidx = out_pidx.at[dest0].set(jnp.arange(cap, dtype=jnp.int32), mode="drop")
        out_live = out_live.at[dest0].set(True, mode="drop")

    # phase 2: emit the m-th key match of probe i at slot base[i] + m
    def emit_body(state):
        k, m, op, ob, of, ol = state
        bidx, eq = key_eq_at(k)
        dest = jnp.where(eq, (base + m).astype(jnp.int32), OC)
        op = op.at[dest].set(jnp.arange(cap, dtype=jnp.int32), mode="drop")
        ob = ob.at[dest].set(bidx, mode="drop")
        of = of.at[dest].set(True, mode="drop")
        ol = ol.at[dest].set(True, mode="drop")
        return k + 1, m + eq.astype(jnp.int32), op, ob, of, ol

    _, _, out_pidx, out_bidx, out_found, out_live = jax.lax.while_loop(
        lambda s: s[0] < max_run,
        emit_body,
        (jnp.int32(0), jnp.zeros((cap,), jnp.int32), out_pidx, out_bidx, out_found, out_live),
    )

    pcols = tuple(
        Column(data=c.data[out_pidx], valid=c.valid[out_pidx] & out_live)
        for c in probe.cols
    )
    bcols = tuple(
        Column(data=c.data[out_bidx], valid=c.valid[out_bidx] & out_found)
        for c in build.cols
    )
    return Batch(cols=pcols + bcols, mask=out_live), total


def join_output_schema(
    probe_schema: Schema, build_schema: Schema, spec: JoinSpec
) -> Schema:
    if spec.join_type in ("semi", "anti"):
        return probe_schema
    return probe_schema.concat(build_schema)
