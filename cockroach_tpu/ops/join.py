"""Join kernels — the colexecjoin analog.

Reference: pkg/sql/colexec/colexecjoin/hashjoiner.go:165 builds a vectorized
chained hash table (colexechash.HashTable.FullBuild, hashtable.go:473) then
probes per batch. Pointer-chasing hash chains don't map to TPU, so the build
becomes *sort by 64-bit key hash* and the probe becomes *vectorized binary
search* (log2(n) gathers of the whole probe tile) + a short collision-advance
loop. Two probe paths:

- ``hash_join_unique``: build keys are unique (FK->PK joins — most TPC-H
  joins). Output is probe-aligned, fully static shapes: inner / left-outer /
  semi / anti.
- ``hash_join_general``: duplicate build keys; per-probe match counts + a
  bounded emission loop into a caller-sized output tile (capacity bucketing:
  the host re-invokes with the next power-of-two capacity on overflow —
  reported via the returned total). This mirrors how the reference's probe
  emits variable-size output batches per input batch.

SQL semantics: NULL join keys never match (NULL != NULL); anti-join keeps
NULL-key probe rows (NOT EXISTS semantics, matching CRDB's anti join).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..coldata.batch import Batch, Column
from ..coldata.types import Schema
from .hashing import hash_columns

_SENTINEL = np.uint64(0xFFFFFFFFFFFFFFFF)


@dataclass(frozen=True)
class JoinSpec:
    join_type: str = "inner"  # inner | left | semi | anti
    build_unique: bool = True


def _key_hashes(batch: Batch, keys: tuple[int, ...], schema: Schema, hash_tables):
    cols = [batch.cols[i] for i in keys]
    types = [schema.types[i] for i in keys]
    h = hash_columns(cols, types, hash_tables)
    all_valid = batch.mask
    for c in cols:
        all_valid = all_valid & c.valid
    # rows that can never match: dead, or any NULL key
    return jnp.where(all_valid, h, _SENTINEL), all_valid


def _keys_equal(probe: Batch, pkeys, build: Batch, bkeys, bidx, build_remaps=None):
    """Exact key equality probe[i] == build[bidx[i]] per row.

    build_remaps: {key position -> np.ndarray} host-prepared remap of build
    dictionary codes into the probe column's dictionary code space (-1 when
    the value is absent there), so STRING equality is exact across tables
    with different dictionaries."""
    build_remaps = build_remaps or {}
    eq = jnp.ones((probe.capacity,), jnp.bool_)
    for pos, (pk, bk) in enumerate(zip(pkeys, bkeys)):
        pc = probe.cols[pk]
        bc = build.cols[bk]
        bdata = bc.data[bidx]
        if pos in build_remaps:
            remap = jnp.asarray(build_remaps[pos])
            bdata = remap[jnp.clip(bdata, 0, remap.shape[0] - 1)]
        eq = eq & (pc.data == bdata) & pc.valid & bc.valid[bidx]
    return eq


def build_index(
    build: Batch, schema: Schema, keys: tuple[int, ...], hash_tables=None
):
    """Sort build rows by key hash -> (sorted_hashes, orig_index). NULL-key and
    dead rows hash to the max sentinel and sort to the end."""
    bh, _ = _key_hashes(build, keys, schema, hash_tables)
    perm = jnp.arange(build.capacity, dtype=jnp.int32)
    sh, order = jax.lax.sort([bh, perm], num_keys=1)
    return sh, order


def _probe_positions(sh, ph):
    return jnp.searchsorted(sh, ph, side="left").astype(jnp.int32)


def hash_join_unique(
    probe: Batch,
    probe_schema: Schema,
    probe_keys: tuple[int, ...],
    build: Batch,
    build_schema: Schema,
    build_keys: tuple[int, ...],
    spec: JoinSpec,
    probe_hash_tables=None,
    build_hash_tables=None,
    build_code_remaps=None,
    index=None,
) -> Batch:
    """Join with unique build keys. Output tile is probe-capacity:
    probe columns followed by build columns (semi/anti: probe columns only).
    `index` is an optional precomputed build_index() result so the build-side
    sort runs once per build batch, not once per probe tile."""
    cap = probe.capacity
    bcap = build.capacity
    sh, order = index if index is not None else build_index(
        build, build_schema, build_keys, build_hash_tables
    )
    ph, p_active = _key_hashes(probe, probe_keys, probe_schema, probe_hash_tables)
    pos = _probe_positions(sh, jnp.where(p_active, ph, _SENTINEL))

    def cond(state):
        _, _, active, _ = state
        return jnp.any(active)

    def body(state):
        pos, found_idx, active, found = state
        inb = pos < bcap
        posc = jnp.clip(pos, 0, bcap - 1)
        bidx = order[posc]
        hash_eq = inb & (sh[posc] == ph) & active
        key_eq = _keys_equal(
            probe, probe_keys, build, build_keys, bidx, build_code_remaps
        )
        hit = hash_eq & key_eq
        found_idx = jnp.where(hit, bidx, found_idx)
        found = found | hit
        # advance only on hash collision with key mismatch
        advance = hash_eq & ~key_eq
        return pos + advance, found_idx, advance, found

    init = (
        pos,
        jnp.zeros((cap,), jnp.int32),
        p_active,
        jnp.zeros((cap,), jnp.bool_),
    )
    _, found_idx, _, found = jax.lax.while_loop(cond, body, init)
    # guard against sentinel-hash self-matches
    found = found & p_active & build.mask[found_idx]

    if spec.join_type == "semi":
        return probe.with_mask(probe.mask & found)
    if spec.join_type == "anti":
        return probe.with_mask(probe.mask & ~found)

    bcols = tuple(
        Column(data=c.data[found_idx], valid=c.valid[found_idx] & found)
        for c in build.cols
    )
    cols = probe.cols + bcols
    if spec.join_type == "inner":
        mask = probe.mask & found
    elif spec.join_type == "left":
        mask = probe.mask
    else:
        raise ValueError(f"unsupported join type {spec.join_type}")
    return Batch(cols=cols, mask=mask)


def hash_join_general(
    probe: Batch,
    probe_schema: Schema,
    probe_keys: tuple[int, ...],
    build: Batch,
    build_schema: Schema,
    build_keys: tuple[int, ...],
    spec: JoinSpec,
    out_capacity: int,
    probe_hash_tables=None,
    build_hash_tables=None,
    build_code_remaps=None,
    index=None,
):
    """General join (duplicate build keys). Returns (out_batch, total_rows);
    if total_rows > out_capacity the caller must retry with a larger tile
    (capacity bucketing keeps shapes static per bucket)."""
    cap = probe.capacity
    bcap = build.capacity
    sh, order = index if index is not None else build_index(
        build, build_schema, build_keys, build_hash_tables
    )
    ph, p_active = _key_hashes(probe, probe_keys, probe_schema, probe_hash_tables)
    phs = jnp.where(p_active, ph, _SENTINEL)
    lo = jnp.searchsorted(sh, phs, side="left").astype(jnp.int32)
    hi = jnp.searchsorted(sh, phs, side="right").astype(jnp.int32)
    run = jnp.where(p_active, hi - lo, 0)
    max_run = jnp.max(run)

    def key_eq_at(k):
        posc = jnp.clip(lo + k, 0, bcap - 1)
        bidx = order[posc]
        valid_k = (k < run) & p_active & build.mask[bidx]
        return bidx, valid_k & _keys_equal(
            probe, probe_keys, build, build_keys, bidx, build_code_remaps
        )

    # phase 1: count real key matches per probe row
    def count_body(state):
        k, cnt = state
        _, eq = key_eq_at(k)
        return k + 1, cnt + eq.astype(jnp.int32)

    _, cnt = jax.lax.while_loop(
        lambda s: s[0] < max_run,
        count_body,
        (jnp.int32(0), jnp.zeros((cap,), jnp.int32)),
    )

    left = spec.join_type == "left"
    if spec.join_type == "semi":
        return probe.with_mask(probe.mask & (cnt > 0)), jnp.sum(cnt > 0)
    if spec.join_type == "anti":
        return probe.with_mask(probe.mask & (cnt == 0)), jnp.sum(cnt == 0)

    out_rows = jnp.where(left & probe.mask, jnp.maximum(cnt, 1), cnt)
    base = jnp.cumsum(out_rows) - out_rows  # exclusive prefix
    total = jnp.sum(out_rows)

    OC = out_capacity
    out_pidx = jnp.zeros((OC,), jnp.int32)
    out_bidx = jnp.zeros((OC,), jnp.int32)
    out_found = jnp.zeros((OC,), jnp.bool_)
    out_live = jnp.zeros((OC,), jnp.bool_)

    if left:
        # unmatched probe rows emit one null-extended row at their base slot
        unmatched = probe.mask & (cnt == 0)
        dest0 = jnp.where(unmatched, base.astype(jnp.int32), OC)
        out_pidx = out_pidx.at[dest0].set(jnp.arange(cap, dtype=jnp.int32), mode="drop")
        out_live = out_live.at[dest0].set(True, mode="drop")

    # phase 2: emit the m-th key match of probe i at slot base[i] + m
    def emit_body(state):
        k, m, op, ob, of, ol = state
        bidx, eq = key_eq_at(k)
        dest = jnp.where(eq, (base + m).astype(jnp.int32), OC)
        op = op.at[dest].set(jnp.arange(cap, dtype=jnp.int32), mode="drop")
        ob = ob.at[dest].set(bidx, mode="drop")
        of = of.at[dest].set(True, mode="drop")
        ol = ol.at[dest].set(True, mode="drop")
        return k + 1, m + eq.astype(jnp.int32), op, ob, of, ol

    _, _, out_pidx, out_bidx, out_found, out_live = jax.lax.while_loop(
        lambda s: s[0] < max_run,
        emit_body,
        (jnp.int32(0), jnp.zeros((cap,), jnp.int32), out_pidx, out_bidx, out_found, out_live),
    )

    pcols = tuple(
        Column(data=c.data[out_pidx], valid=c.valid[out_pidx] & out_live)
        for c in probe.cols
    )
    bcols = tuple(
        Column(data=c.data[out_bidx], valid=c.valid[out_bidx] & out_found)
        for c in build.cols
    )
    return Batch(cols=pcols + bcols, mask=out_live), total


def join_output_schema(
    probe_schema: Schema, build_schema: Schema, spec: JoinSpec
) -> Schema:
    if spec.join_type in ("semi", "anti"):
        return probe_schema
    return probe_schema.concat(build_schema)
