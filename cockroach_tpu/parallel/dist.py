"""Distributed query pipelines — the DistSQL physical planner analog.

Reference: pkg/sql/distsql_physical_planner.go plans partitioned TableReaders
per node, local (partial) aggregation, a hash-router shuffle, and a final
aggregation stage (aggregation planning around OutputRouterSpec); joins
shuffle both sides on the join key so each consumer joins co-located
partitions. Here each of those multi-node flow graphs compiles into ONE SPMD
program over the mesh:

    partial sort_groupby (local)  ->  all_to_all shuffle by key hash
        ->  merge sort_groupby (local)  ->  finalize

The whole pipeline is a single jit: XLA sees the collective and overlaps it
with local compute — there is no flow registry, no outbox goroutines, no
Arrow serialization (SURVEY §2.3 TPU-native equivalent row).
"""

from __future__ import annotations

import jax
import numpy as np
from ._compat import shard_map
from jax.sharding import NamedSharding, PartitionSpec as P

from ..coldata.batch import Batch
from ..coldata.types import Schema
from ..flow import dispatch
from ..ops import aggregation as agg_ops
from ..ops import join as join_ops
from .mesh import AXIS
from .shuffle import _local_shuffle


def shard_batch(batch: Batch, mesh) -> Batch:
    """Place a host-built global batch row-sharded across the mesh
    (partitioned-scan placement; capacity must divide the mesh size)."""
    sh = NamedSharding(mesh, P(AXIS))
    return jax.tree_util.tree_map(
        lambda x: jax.device_put(x, sh), batch
    )


def make_distributed_groupby(
    mesh,
    schema: Schema,
    group_cols: tuple[int, ...],
    aggs: tuple[agg_ops.AggSpec, ...],
    local_capacity: int,
    hash_tables: dict[int, np.ndarray] | None = None,
    send_factor: float = 2.0,
):
    """Build (jitted_fn, output_schema). jitted_fn: row-sharded Batch ->
    (row-sharded final Batch, [D] shuffle overflow counts). Every group lands
    on exactly one device (hash placement), so results are globally correct
    without a gather."""
    D = mesh.shape[AXIS]
    partial_specs, state_schema, final_map = agg_ops.partial_layout(
        schema, group_cols, aggs
    )
    k = len(group_cols)
    merge_specs = agg_ops.merge_specs_for(partial_specs, k)
    state_keys = tuple(range(k))
    key_types = [state_schema.types[i] for i in state_keys]
    # final schema: keys + finalized aggs
    names = list(state_schema.names[:k])
    types = list(state_schema.types[:k])
    for spec, fm in zip(aggs, final_map):
        names.append(spec.name or spec.func)
        if fm[0] == "avg":
            from ..coldata.types import FLOAT64

            types.append(FLOAT64)
        else:
            types.append(agg_ops.agg_output_type(spec, schema))
    final_schema = Schema(tuple(names), tuple(types))

    lcap = local_capacity
    send_cap = max(128, int(lcap / D * send_factor) // 128 * 128)

    def local_pipeline(b: Batch):
        part, _ = agg_ops.sort_groupby(b, schema, group_cols, partial_specs)
        shuffled, overflow = _local_shuffle(
            part, state_keys, key_types, hash_tables, D, send_cap, lcap
        )
        merged, _ = agg_ops.sort_groupby(
            shuffled, state_schema, state_keys, merge_specs
        )
        return agg_ops.finalize_states(merged, final_map, k), overflow

    fn = shard_map(
        local_pipeline,
        mesh=mesh,
        in_specs=(P(AXIS),),
        out_specs=(P(AXIS), P(AXIS)),
        check_vma=False,
    )
    return dispatch.jit(fn), final_schema


def make_distributed_join(
    mesh,
    probe_schema: Schema,
    probe_keys: tuple[int, ...],
    build_schema: Schema,
    build_keys: tuple[int, ...],
    spec: join_ops.JoinSpec,
    probe_capacity: int,
    build_capacity: int,
    probe_hash_tables=None,
    build_hash_tables=None,
    build_code_remaps=None,
    send_factor: float = 2.0,
):
    """Shuffle-join: repartition both sides by key hash over ICI, then join
    co-located partitions locally (the reference's both-sides-hash-routed
    hash join). Returns (jitted_fn, output_schema); fn maps row-sharded
    (probe, build) -> (row-sharded joined Batch, [D] overflow counts)."""
    D = mesh.shape[AXIS]
    p_types = [probe_schema.types[i] for i in probe_keys]
    b_types = [build_schema.types[i] for i in build_keys]
    p_send = max(128, int(probe_capacity / D * send_factor) // 128 * 128)
    b_send = max(128, int(build_capacity / D * send_factor) // 128 * 128)

    def local_pipeline(p: Batch, b: Batch):
        ps, pov = _local_shuffle(
            p, probe_keys, p_types, probe_hash_tables, D, p_send, probe_capacity
        )
        bs, bov = _local_shuffle(
            b, build_keys, b_types, build_hash_tables, D, b_send, build_capacity
        )
        out = join_ops.hash_join_unique(
            ps, probe_schema, probe_keys, bs, build_schema, build_keys, spec,
            probe_hash_tables, build_hash_tables, build_code_remaps,
        )
        return out, pov + bov

    fn = shard_map(
        local_pipeline,
        mesh=mesh,
        in_specs=(P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS)),
        check_vma=False,
    )
    return dispatch.jit(fn), join_ops.join_output_schema(
        probe_schema, build_schema, spec)
