"""SPMD plan lowering — distributed plans compile to ONE mesh program.

Reference: the DistSQL flow machinery (vectorizedFlowCreator building an
operator DAG per node, colrpc Outbox/Inbox streams between them —
pkg/sql/colflow/vectorized_flow.go:219, distsql_running.go:710). The TPU
redesign collapses the entire distributed flow graph into a single jitted
shard_map: every per-node local pipeline is ordinary traced compute, every
router/stream edge is a collective (Exchange -> lax.all_to_all via
parallel/shuffle.py; Broadcast/Gather -> lax.all_gather; dense/scalar
aggregation states -> psum/pmin/pmax). XLA schedules the collectives and
overlaps them with local compute; there is no flow registry and no
serialization.

Capacity contract: every stage has a static output capacity derived from its
inputs (scaled by a host-controlled `factor`). Stages that can overflow —
Exchange send buckets and general (duplicate-key) join outputs — report
overflow counts; `DistributedQuery.run()` retries with a doubled factor
until clean (the host-side retry loop the shuffle contract promises,
parallel/shuffle.py:12-16).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from ._compat import shard_map
from jax.sharding import PartitionSpec as P

from ..catalog import Catalog
from ..coldata.batch import Batch, Column, Dictionary, from_host, to_host
from ..coldata.types import FLOAT64, Family, Schema
from ..flow import dispatch
from ..ops import aggregation as agg_ops
from ..ops import expr as ex
from ..ops import join as join_ops
from ..ops import sort as sort_ops
from ..plan import spec as S
from ..plan.distribute import distribute
from .mesh import AXIS
from .shuffle import _local_shuffle


def _pow2(n: int) -> int:
    p = 1024
    while p < n:
        p *= 2
    return p


@dataclass
class _LNode:
    """One lowered plan node: `emit(env)` returns the node's per-device
    Batch when traced inside the shard_map."""

    emit: Callable
    schema: Schema
    dicts: dict[int, Dictionary]
    replicated: bool
    cap: int  # per-device output capacity (static)


class _Lowering:
    def __init__(self, catalog: Catalog, D: int, factor: int):
        self.catalog = catalog
        self.D = D
        self.factor = factor
        self.scan_specs: list[tuple[str, tuple[str, ...], int]] = []
        self.overflows: list[jax.Array] = []  # collected during tracing
        self.emit_cache: dict = {}  # per-trace shared-subtree results

    # -- helpers ------------------------------------------------------------

    def _all_gather(self, ln: _LNode) -> _LNode:
        """Replicate a sharded batch on every device (Gather/Broadcast)."""
        if ln.replicated:
            return ln
        inner = ln.emit

        def emit(env):
            b = inner(env)
            return jax.tree_util.tree_map(
                lambda x: jax.lax.all_gather(x, AXIS, axis=0, tiled=True), b
            )

        return _LNode(emit, ln.schema, ln.dicts, True, ln.cap * self.D)

    def _exchange(self, ln: _LNode, keys: tuple[int, ...]) -> _LNode:
        types = [ln.schema.types[i] for i in keys]
        hash_tables = {
            pos: ln.dicts[i].hashes
            for pos, i in enumerate(keys) if i in ln.dicts
        } or None
        # key positions are passed positionally to hash_columns via the
        # extracted column list, so hash tables index by position
        out_cap = _pow2(ln.cap * 2 * self.factor)
        send_cap = max(
            128, (ln.cap * 2 * self.factor // self.D) // 128 * 128
        )
        D = self.D
        inner = ln.emit

        def emit(env):
            b = inner(env)
            out, ovf = _local_shuffle(
                b, keys, types, hash_tables, D, send_cap, out_cap
            )
            self.overflows.append(ovf[0])
            return out

        return _LNode(emit, ln.schema, ln.dicts, False, out_cap)

    # -- node dispatch ------------------------------------------------------

    def lower(self, plan: S.PlanNode) -> _LNode:
        # memoize by plan-node identity: DAG-shaped plans (a shared subtree
        # feeding two consumers, e.g. q15's max-revenue branch) lower — and
        # therefore trace and COMPUTE — once inside the single SPMD program
        memo = getattr(self, "_memo", None)
        if memo is None:
            memo = self._memo = {}
        ln = memo.get(id(plan))
        if ln is not None:
            return ln
        m = getattr(self, f"_lower_{type(plan).__name__.lower()}", None)
        if m is None:
            raise TypeError(f"cannot lower {type(plan).__name__}")
        ln = m(plan)
        # cache emit RESULTS per trace as well: two consumers of a shared
        # subtree reuse the same traced value instead of emitting the whole
        # subgraph twice (emit_cache is cleared by local_fn per trace)
        orig_emit = ln.emit
        lowering = self

        def cached_emit(env, _key=id(plan)):
            r = lowering.emit_cache.get(_key)
            if r is None:
                r = orig_emit(env)
                lowering.emit_cache[_key] = r
            return r

        ln = _LNode(cached_emit, ln.schema, ln.dicts, ln.replicated, ln.cap)
        memo[id(plan)] = ln
        return ln

    def _lower_tablescan(self, plan: S.TableScan) -> _LNode:
        table = self.catalog.get(plan.table)
        names = plan.columns or table.schema.names
        idxs = tuple(table.schema.index(n) for n in names)
        schema = table.schema.select(idxs)
        full = table.dict_by_index()
        dicts = {i: full[ci] for i, ci in enumerate(idxs) if ci in full}
        # size from the SNAPSHOT's live count where the table distinguishes
        # it: num_rows is the newest-visible count at now(), but a KV table
        # pinned to an older read_ts (or reading as a txn) can hold more
        # live rows — sizing from num_rows would drop the tail at compact
        snap_fn = getattr(table, "snapshot_live_rows", None)
        rows = snap_fn() if callable(snap_fn) else table.num_rows
        local_cap = max(
            1024, -(-rows // (self.D * 1024)) * 1024
        )
        slot = len(self.scan_specs)
        self.scan_specs.append((plan.table, tuple(names), local_cap))
        return _LNode(lambda env: env[slot], schema, dicts, False, local_cap)

    def _lower_filter(self, plan: S.Filter) -> _LNode:
        ln = self.lower(plan.input)
        schema, pred, inner = ln.schema, plan.predicate, ln.emit

        def emit(env):
            b = inner(env)
            return b.with_mask(ex.filter_mask(b, schema, pred))

        return _LNode(emit, schema, ln.dicts, ln.replicated, ln.cap)

    def _lower_project(self, plan: S.Project) -> _LNode:
        ln = self.lower(plan.input)
        schema = ln.schema
        types = tuple(ex.expr_type(e, schema) for e in plan.exprs)
        out_schema = Schema(tuple(plan.names), types)
        dicts = {
            i: ln.dicts[e.idx]
            for i, e in enumerate(plan.exprs)
            if isinstance(e, ex.ColRef) and e.idx in ln.dicts
        }
        for i, d in plan.dict_overrides:
            dicts[i] = d
        inner = ln.emit

        def emit(env):
            b = inner(env)
            cols = []
            for e in plan.exprs:
                d, v = ex.eval_expr(e, b.cols, schema)
                cols.append(Column(data=d, valid=v))
            return Batch(cols=tuple(cols), mask=b.mask)

        return _LNode(emit, out_schema, dicts, ln.replicated, ln.cap)

    def _lower_exchange(self, plan: S.Exchange) -> _LNode:
        return self._exchange(self.lower(plan.input), plan.keys)

    def _lower_broadcast(self, plan: S.Broadcast) -> _LNode:
        return self._all_gather(self.lower(plan.input))

    def _lower_gather(self, plan: S.Gather) -> _LNode:
        return self._all_gather(self.lower(plan.input))

    # -- aggregation --------------------------------------------------------

    def _agg_final_schema(self, base, group_cols, aggs, state_schema, mode):
        return agg_ops.agg_output_schema(base, group_cols, aggs, mode)

    def _lower_aggregate(self, plan: S.Aggregate) -> _LNode:
        ln = self.lower(plan.input)
        if plan.key_sizes is not None:
            return self._lower_dense_agg(plan, ln)
        if plan.mode == "partial":
            base = ln.schema
            pspecs, state_schema, _ = agg_ops.partial_layout(
                base, plan.group_cols, plan.aggs
            )
            gcols, cap, inner = plan.group_cols, ln.cap, ln.emit
            # a contiguous device shard of a clustered table keeps equal
            # keys adjacent: the per-shard grouping can skip its key sort
            # (orderedAggregator role; plan/builder._clustered_input)
            from ..plan.builder import _clustered_input

            ordered, prefix_live = _clustered_input(
                plan.input, plan.group_cols, self.catalog
            )

            def emit(env):
                b = inner(env)
                part, _ = agg_ops.sort_groupby(
                    b, base, gcols, pspecs, out_capacity=cap,
                    presorted=ordered, compact=not prefix_live,
                )  # num_groups <= live rows <= cap: no overflow possible
                return part

            dicts = {
                plan.group_cols.index(gi): d
                for gi, d in ln.dicts.items() if gi in plan.group_cols
            }
            return _LNode(emit, state_schema, dicts, ln.replicated, cap)

        if plan.mode == "final":
            base = plan.base_schema
            pspecs, state_schema, final_map = agg_ops.partial_layout(
                base, plan.group_cols, plan.aggs
            )
            k = len(plan.group_cols)
            merge_specs = agg_ops.merge_specs_for(pspecs, k)
            out_schema = self._agg_final_schema(
                base, plan.group_cols, plan.aggs, state_schema, "final"
            )
            cap, inner = ln.cap, ln.emit

            def emit(env):
                b = inner(env)
                merged, _ = agg_ops.sort_groupby(
                    b, state_schema, tuple(range(k)), merge_specs,
                    out_capacity=cap,
                )
                return agg_ops.finalize_states(merged, final_map, k)

            dicts = {i: d for i, d in ln.dicts.items() if i < k}
            return _LNode(emit, out_schema, dicts, ln.replicated, cap)

        # complete (replicated input): partial + finalize in one pass
        base = ln.schema
        pspecs, state_schema, final_map = agg_ops.partial_layout(
            base, plan.group_cols, plan.aggs
        )
        k = len(plan.group_cols)
        out_schema = self._agg_final_schema(
            base, plan.group_cols, plan.aggs, state_schema, "complete"
        )
        gcols, cap, inner = plan.group_cols, ln.cap, ln.emit

        def emit(env):
            b = inner(env)
            part, _ = agg_ops.sort_groupby(
                b, base, gcols, pspecs, out_capacity=cap
            )
            return agg_ops.finalize_states(part, final_map, k)

        dicts = {
            plan.group_cols.index(gi): d
            for gi, d in ln.dicts.items() if gi in plan.group_cols
        }
        return _LNode(emit, out_schema, dicts, ln.replicated, cap)

    def _lower_dense_agg(self, plan: S.Aggregate, ln: _LNode) -> _LNode:
        """Dense-code aggregation: [G] states merge across the mesh with
        psum/pmin/pmax — Q1's path has zero all-to-all traffic."""
        base = ln.schema
        pspecs, _, final_map = agg_ops.partial_layout(
            base, plan.group_cols, plan.aggs
        )
        G, strides = agg_ops.dense_layout(plan.key_sizes)
        gcols, sizes, inner = plan.group_cols, plan.key_sizes, ln.emit
        replicated = ln.replicated
        out_schema = self._agg_final_schema(
            base, gcols, plan.aggs, None, "complete"
        )

        def emit(env):
            b = inner(env)
            code, _ = agg_ops.dense_group_codes(b, gcols, strides, sizes)
            from ..ops import segscan

            states, rows = (
                agg_ops.dense_onehot_states(b, base, code, G, pspecs)
                if G <= 64 and segscan.use_scans()
                else agg_ops.dense_scatter_states(b, base, code, G, pspecs)
            )
            if not replicated:
                states = agg_ops.psum_dense_states(pspecs, states, AXIS)
                rows = jax.lax.psum(rows, AXIS)
            return agg_ops.dense_finalize(
                base, gcols, strides, sizes, G, final_map, states, rows
            )

        dicts = {
            gcols.index(gi): d for gi, d in ln.dicts.items() if gi in gcols
        }
        return _LNode(emit, out_schema, dicts, True, G)

    def _lower_scalaraggregate(self, plan: S.ScalarAggregate) -> _LNode:
        ln = self.lower(plan.input)
        base = ln.schema
        names, types = [], []
        for spec in plan.aggs:
            names.append(spec.name or spec.func)
            types.append(FLOAT64 if spec.func == "avg"
                         else agg_ops.agg_output_type(spec, base))
        out_schema = Schema(tuple(names), tuple(types))
        aggs, inner, replicated = plan.aggs, ln.emit, ln.replicated

        def emit(env):
            b = inner(env)
            st = agg_ops.scalar_tile_states(b, aggs, base)
            if not replicated:
                st = agg_ops.psum_dense_states(aggs, st, AXIS)
            return agg_ops.scalar_result_batch(aggs, base, out_schema, st)

        return _LNode(emit, out_schema, {}, True, 1)

    def _lower_distinct(self, plan: S.Distinct) -> _LNode:
        ln = self.lower(plan.input)
        cols = plan.cols or tuple(range(len(ln.schema)))
        out_schema = ln.schema.select(cols)
        dicts = {
            cols.index(i): d for i, d in ln.dicts.items() if i in cols
        }
        pspecs, state_schema, _ = agg_ops.partial_layout(ln.schema, cols, ())
        cap, inner = ln.cap, ln.emit

        def emit(env):
            b = inner(env)
            out, _ = agg_ops.sort_groupby(
                b, ln.schema, cols, pspecs, out_capacity=cap
            )
            return out

        return _LNode(emit, out_schema, dicts, ln.replicated, cap)

    # -- joins --------------------------------------------------------------

    def _join_bridges(self, pl: _LNode, bl: _LNode, probe_keys, build_keys):
        """Host-side string-key bridges (HashJoinOp's dictionary glue)."""
        pht, bht, remaps = {}, {}, {}
        for pos, (pk, bk) in enumerate(zip(probe_keys, build_keys)):
            if pl.schema.types[pk].family is Family.STRING:
                pd, bd = pl.dicts[pk], bl.dicts[bk]
                pht[pk] = pd.hashes
                bht[bk] = bd.hashes
                remaps[pos] = np.array(
                    [pd.code_of(str(v)) for v in bd.values], dtype=np.int32
                )
        return pht or None, bht or None, remaps or None

    def _join_dicts(self, pl: _LNode, bl: _LNode, spec) -> dict:
        dicts = dict(pl.dicts)
        if spec.join_type not in ("semi", "anti"):
            off = len(pl.schema)
            for i, d in bl.dicts.items():
                dicts[off + i] = d
        return dicts

    def _lower_hashjoin(self, plan: S.HashJoin) -> _LNode:
        pl = self.lower(plan.probe)
        bl = self.lower(plan.build)
        pht, bht, remaps = self._join_bridges(
            pl, bl, plan.probe_keys, plan.build_keys
        )
        out_schema = join_ops.join_output_schema(pl.schema, bl.schema,
                                                 plan.spec)
        dicts = self._join_dicts(pl, bl, plan.spec)
        pemit, bemit = pl.emit, bl.emit
        pschema, bschema = pl.schema, bl.schema
        pkeys, bkeys, spec = plan.probe_keys, plan.build_keys, plan.spec
        replicated = pl.replicated and bl.replicated

        if spec.build_unique:
            def emit(env):
                p, b = pemit(env), bemit(env)
                return join_ops.hash_join_unique(
                    p, pschema, pkeys, b, bschema, bkeys, spec,
                    pht, bht, remaps,
                )

            return _LNode(emit, out_schema, dicts, replicated, pl.cap)

        out_cap = _pow2(pl.cap * 2 * self.factor)

        def emit(env):
            p, b = pemit(env), bemit(env)
            out, total = join_ops.hash_join_general(
                p, pschema, pkeys, b, bschema, bkeys, spec, out_cap,
                pht, bht, remaps,
            )
            self.overflows.append(
                jnp.maximum(total - out_cap, 0).astype(jnp.int32)
            )
            return out

        return _LNode(emit, out_schema, dicts, replicated, out_cap)

    def _lower_mergejoin(self, plan: S.MergeJoin) -> _LNode:
        from ..ops import merge_join as mj_ops

        pl = self.lower(plan.probe)
        bl = self.lower(plan.build)
        out_schema = join_ops.join_output_schema(pl.schema, bl.schema,
                                                 plan.spec)
        dicts = self._join_dicts(pl, bl, plan.spec)
        # STRING keys share the probe dictionary's rank space, per key
        # position (shared helper with MergeJoinOp; composite keys included)
        probe_rank, build_rank = mj_ops.rank_tables_for(
            pl.schema, plan.probe_key, pl.dicts, plan.build_key, bl.dicts,
        )
        out_cap = _pow2(pl.cap * 2 * self.factor)
        pemit, bemit = pl.emit, bl.emit
        pschema, bschema = pl.schema, bl.schema
        pk, bk, spec = plan.probe_key, plan.build_key, plan.spec

        def emit(env):
            p, b = pemit(env), bemit(env)
            out, total = mj_ops.merge_join(
                p, pschema, pk, b, bschema, bk, spec, out_cap,
                probe_rank, build_rank,
            )
            self.overflows.append(
                jnp.maximum(total - out_cap, 0).astype(jnp.int32)
            )
            return out

        return _LNode(emit, out_schema, dicts,
                      pl.replicated and bl.replicated, out_cap)

    # -- order / limit / window --------------------------------------------

    def _lower_sort(self, plan: S.Sort) -> _LNode:
        ln = self.lower(plan.input)
        rank_tables = {
            k.col: ln.dicts[k.col].ranks
            for k in plan.keys if k.col in ln.dicts
        }
        schema, keys, inner = ln.schema, plan.keys, ln.emit

        def emit(env):
            return sort_ops.sort_batch(inner(env), schema, keys, rank_tables)

        return _LNode(emit, schema, ln.dicts, ln.replicated, ln.cap)

    def _lower_limit(self, plan: S.Limit) -> _LNode:
        from ..coldata.batch import compact

        ln = self.lower(plan.input)
        limit, offset, inner = plan.limit, plan.offset, ln.emit
        # shrink the tile to the limit: a top-k feeding a Gather then moves
        # D*pow2(k) rows over ICI, not the whole per-device result
        out_cap = min(ln.cap, _pow2(limit + offset))

        def emit(env):
            b = sort_ops.limit_mask(inner(env), limit, offset)
            if out_cap < b.capacity:
                b = compact(b, capacity=out_cap)  # order-preserving
            return b

        return _LNode(emit, ln.schema, ln.dicts, ln.replicated, out_cap)

    def _lower_union(self, plan: S.Union) -> _LNode:
        from ..coldata.batch import concat

        lns = [self.lower(p) for p in plan.inputs]
        assert all(ln.replicated == lns[0].replicated for ln in lns), \
            "distribute() must make Union children uniformly placed"
        cap = _pow2(sum(ln.cap for ln in lns))
        emits = [ln.emit for ln in lns]

        def emit(env):
            return concat([e(env) for e in emits], capacity=cap)

        return _LNode(emit, lns[0].schema, dict(lns[0].dicts),
                      lns[0].replicated, cap)

    def _lower_window(self, plan: S.Window) -> _LNode:
        from ..ops import window as win_ops

        ln = self.lower(plan.input)
        out_schema = win_ops.window_output_schema(ln.schema, plan.specs)
        dicts = dict(ln.dicts)
        base_len = len(ln.schema)
        for i, sp in enumerate(plan.specs):
            if (sp.col is not None and sp.col in ln.dicts
                    and sp.func in ("lag", "lead", "min", "max",
                                    "first_value", "last_value")):
                dicts[base_len + i] = ln.dicts[sp.col]
        need = {k.col for k in plan.order_keys}
        need.update(plan.partition_cols)
        need.update(sp.col for sp in plan.specs
                    if sp.col is not None and sp.func in ("min", "max"))
        rank_tables = {
            c: ln.dicts[c].ranks for c in need if c in ln.dicts
        }
        schema, inner = ln.schema, ln.emit
        pcols, okeys, specs = plan.partition_cols, plan.order_keys, plan.specs

        def emit(env):
            return win_ops.compute_windows(
                inner(env), schema, pcols, okeys, specs, rank_tables
            )

        return _LNode(emit, out_schema, dicts, ln.replicated, ln.cap)


def _needs_local(plan) -> bool:
    """True when the plan contains a construct the SPMD lowering cannot
    express (today: string_agg's host-side concatenation)."""
    stack = [plan]
    while stack:
        n = stack.pop()
        aggs = getattr(n, "aggs", None)
        if aggs and any(getattr(s, "func", "") == "string_agg"
                        for s in aggs):
            return True
        for f in getattr(n, "__dataclass_fields__", {}):
            v = getattr(n, f)
            if isinstance(v, S.PlanNode):
                stack.append(v)
            elif isinstance(v, tuple):
                stack.extend(x for x in v if isinstance(x, S.PlanNode))
    return False


class DistributedQuery:
    """One distributed query: plan rewrite + SPMD lowering + retry loop.

    The reference analog of DistSQLPlanner.PlanAndRunAll + the flow runtime
    (distsql_running.go:1751,:710), collapsed into build-jit-run."""

    def __init__(self, plan: S.PlanNode, catalog: Catalog, mesh,
                 broadcast_rows: int | None = None,
                 already_distributed: bool = False):
        self.catalog = catalog
        self.mesh = mesh
        self.D = mesh.shape[AXIS]
        # unsupported-for-distribution constructs fall back to local
        # operator execution — the reference's checkSupportForPlanNode
        # discipline (distsql_physical_planner.go:541): distribute what we
        # can, never fail a query for being non-distributable
        self._local_fallback = _needs_local(plan)
        if self._local_fallback:
            self.plan = plan
            self.dplan = plan  # explain() shows the (local) plan
            return
        self.dplan = plan if already_distributed else distribute(
            plan, catalog, broadcast_rows
        )
        self._build(factor=1)

    def _build(self, factor: int):
        self.factor = factor
        low = _Lowering(self.catalog, self.D, factor)
        root = low.lower(self.dplan)
        self.root = root
        nscans = len(low.scan_specs)

        def local_fn(*scan_batches):
            low.overflows = []
            low.emit_cache = {}
            out = root.emit(list(scan_batches))
            low.emit_cache = {}
            if low.overflows:
                ovf = sum(jnp.asarray(o, jnp.int32) for o in low.overflows)
            else:
                ovf = jnp.int32(0)
            return out, ovf[None]

        in_specs = tuple(P(AXIS) for _ in range(nscans))
        out_specs = (P() if root.replicated else P(AXIS), P(AXIS))
        # dispatch.jit so the whole-pipeline SPMD program counts into
        # sql_kernel_dispatches (one dispatch per run_batch attempt)
        self._fn = dispatch.jit(shard_map(
            local_fn, mesh=self.mesh, in_specs=in_specs,
            out_specs=out_specs, check_vma=False,
        ))
        # global sharded scan inputs (partitioned-scan placement), cached:
        # scan shapes don't depend on `factor`, so overflow retries reuse
        # the already-uploaded shards instead of re-sharding every table
        from .dist import shard_batch

        if not hasattr(self, "_scan_cache"):
            self._scan_cache = {}
        self._scan_batches = []
        for spec in low.scan_specs:
            if spec not in self._scan_cache:
                tname, names, local_cap = spec
                t = self.catalog.get(tname)
                if hasattr(t, "columns"):
                    sub = t.schema.select(
                        tuple(t.schema.index(n) for n in names))
                    arrays = {n: np.asarray(t.columns[n]) for n in names}
                    valids = {n: t.valids[n]
                              for n in names if n in t.valids}
                    gb = from_host(sub, arrays, valids=valids,
                                   capacity=local_cap * self.D)
                else:
                    # KV-engine-backed table: snapshot the newest-visible
                    # rows through the direct columnar scan, then row-shard
                    # the snapshot like any other input (the
                    # range/leaseholder placement model would instead read
                    # per-device spans; one-snapshot-then-shard keeps the
                    # same SPMD program shape meanwhile)
                    from ..coldata.batch import compact

                    gb = t.device_batch(tuple(names))
                    # backstop for the snapshot/now() divergence (sizing
                    # uses snapshot_live_rows): compacting more live rows
                    # than planned would silently DROP the tail — fail
                    # loudly instead (one live-count sync at scan setup)
                    live = int(np.asarray(
                        jnp.sum(gb.mask, dtype=jnp.int32)))
                    if live > local_cap * self.D:
                        raise RuntimeError(
                            f"snapshot of {tname} holds {live} live rows "
                            f"but the plan sized {local_cap * self.D}; "
                            "re-plan after the snapshot moved"
                        )
                    gb = compact(gb, capacity=local_cap * self.D)
                self._scan_cache[spec] = shard_batch(gb, self.mesh)
            self._scan_batches.append(self._scan_cache[spec])

    def run_batch(self, max_retries: int = 4) -> tuple[Batch, Schema, dict]:
        """Execute with the overflow-retry loop; returns the global output
        batch (+ schema and dictionaries for host decode)."""
        for _ in range(max_retries):
            out, ovf = self._fn(*self._scan_batches)
            if int(np.asarray(ovf).sum()) == 0:
                return out, self.root.schema, self.root.dicts
            # a shuffle bucket or join output overflowed its static
            # capacity: double every stage capacity and re-lower
            self._build(factor=self.factor * 2)
        raise RuntimeError(
            f"distributed query still overflows at factor {self.factor}"
        )

    def run(self) -> dict[str, np.ndarray]:
        from ..utils.errors import query_boundary

        if self._local_fallback:
            from ..flow.runtime import run_operator
            from ..plan import builder as plan_builder

            return run_operator(plan_builder.build(self.plan, self.catalog))

        @query_boundary("distributed flow")
        def _go():
            out, schema, dicts = self.run_batch()
            return to_host(out, schema, dicts)

        return _go()

    def explain(self) -> str:
        from ..plan.explain import explain_plan

        if self._local_fallback:
            # checkSupportForPlanNode said no: the plan runs locally
            return ("distribution: local (plan not distributable)\n"
                    + explain_plan(self.dplan))
        return explain_plan(self.dplan)
