"""jax version shim for the parallel plane.

The shard_map entry point moved (jax.experimental.shard_map -> jax.shard_map)
and renamed its replication-check kwarg (check_rep -> check_vma) across jax
releases; the baked-in toolchain may carry either side of the move. Callers
here always use the NEW spelling and this module adapts downward.
"""

from __future__ import annotations

try:  # jax >= 0.5: top-level export, check_vma kwarg
    from jax import shard_map  # crlint: allow-unused-import(re-export shim: callers import shard_map from here)
except ImportError:  # older jax: experimental module, check_rep kwarg
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, **kw):
        if "check_vma" in kw:
            kw["check_rep"] = kw.pop("check_vma")
        return _shard_map(f, **kw)
