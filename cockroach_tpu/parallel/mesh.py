"""Device mesh helpers — the cluster topology analog.

The reference's "cluster" is N symmetric nodes connected by gRPC
(pkg/rpc, pkg/gossip); here it is a jax.sharding.Mesh over TPU chips
connected by ICI. One mesh axis ("d") plays the role of DistSQL's node set:
table rows shard across it (partitioned scans, SURVEY §2.2) and hash
repartitioning rides all_to_all over it (HashRouter analog).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS = "d"


def make_mesh(n_devices: int | None = None, devices=None) -> Mesh:
    devs = devices if devices is not None else jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (AXIS,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Rows sharded across the mesh axis (partitioned-scan placement)."""
    return NamedSharding(mesh, P(AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
