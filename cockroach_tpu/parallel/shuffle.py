"""Hash repartition over the mesh — the HashRouter + Outbox/Inbox shuffle.

Reference: colflow/routers.go:420 (HashRouter) hash-partitions each producer's
batches into one stream per consumer; colrpc/outbox.go:44 / inbox.go:48 carry
those streams over gRPC FlowStream with Arrow-serialized batches. On TPU the
entire mechanism becomes ONE collective: inside shard_map each device buckets
its rows by key hash, scatters them into per-destination send buffers, and a
single ``lax.all_to_all`` over the ICI mesh axis delivers every bucket to its
owner. No serialization, no streams, no flow registry — the interconnect is
the router.

Static-shape contract: send buffers are [D, send_cap]; rows that overflow
their destination bucket are counted and reported so the host can retry with
a larger factor (same capacity-bucketing pattern as the join/groupby kernels).
With a balanced 64-bit hash, overflow at send_cap = 2x fair share is
vanishingly rare at real tile sizes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from ._compat import shard_map
from jax.sharding import PartitionSpec as P

from ..coldata.batch import Batch, Column
from ..coldata.types import Schema
from ..flow import dispatch
from ..ops.hashing import hash_columns
from .mesh import AXIS


def _local_shuffle(batch: Batch, keys, types, hash_tables, D, send_cap,  # crlint: allow-mem-accounting(shard_map kernel: send/recv buffers are [D, send_cap] statics from make_shuffle capacities the planner budgets)
                   out_cap, hot=None):
    """Per-device half of the shuffle (runs inside shard_map)."""
    cap = batch.capacity
    cols = [batch.cols[i] for i in keys]
    h = hash_columns(cols, types, hash_tables)
    bucket = (h % np.uint64(D)).astype(jnp.int32)
    keep = None
    if hot is not None:
        # heavy-hitter keys keep their rows LOCAL instead of funneling the
        # key's entire row mass through one destination device — the skew
        # escape hatch of the hash router. Kept rows never enter the send
        # buffers (zero interconnect cost, no send-cap pressure); they
        # merge into the output tile after the all_to_all. The caller must
        # pair this with a REPLICATED build table for the hot keys (every
        # device holds their build rows), which keeps local joins exact.
        pos = jnp.clip(jnp.searchsorted(hot, h), 0, hot.shape[0] - 1)
        keep = batch.mask & (hot[pos] == h)
        bucket = jnp.where(keep, D, bucket)
    bucket = jnp.where(batch.mask, bucket, D)  # dead rows sort last

    # slot within destination bucket, via sort (stable rank-in-bucket)
    iota = jnp.arange(cap, dtype=jnp.int32)
    sb, si = jax.lax.sort([bucket, iota], num_keys=1, is_stable=True)
    first = jnp.searchsorted(sb, sb, side="left").astype(jnp.int32)
    pos_sorted = iota - first
    slot = jnp.zeros((cap,), jnp.int32).at[si].set(pos_sorted)

    send_live = batch.mask if keep is None else (batch.mask & ~keep)
    live = send_live & (slot < send_cap)
    overflow = jnp.sum(send_live & (slot >= send_cap), dtype=jnp.int32)
    dest = jnp.where(live, bucket * send_cap + slot, D * send_cap)

    def scatter_col(c: Column) -> Column:
        if c.data.ndim == 2:
            data = jnp.zeros((D * send_cap, c.data.shape[1]), c.data.dtype)
        else:
            data = jnp.zeros((D * send_cap,), c.data.dtype)
        data = data.at[dest].set(c.data, mode="drop")
        valid = jnp.zeros((D * send_cap,), jnp.bool_).at[dest].set(
            c.valid, mode="drop"
        )
        return Column(data=data, valid=valid)

    send_mask = jnp.zeros((D * send_cap,), jnp.bool_).at[dest].set(
        batch.mask, mode="drop"
    )
    send = Batch(
        cols=tuple(scatter_col(c) for c in batch.cols), mask=send_mask
    )
    # [D*send_cap] -> [D, send_cap] -> all_to_all -> received from each peer
    send = jax.tree_util.tree_map(
        lambda x: x.reshape((D, send_cap) + x.shape[1:]), send
    )
    recv = jax.tree_util.tree_map(
        lambda x: jax.lax.all_to_all(x, AXIS, split_axis=0, concat_axis=0),
        send,
    )
    flat = jax.tree_util.tree_map(
        lambda x: x.reshape((D * send_cap,) + x.shape[2:]), recv
    )
    # compact received rows (plus locally-kept hot rows) into the output
    if keep is None:
        m = flat.mask
        srcs = flat.cols
    else:
        m = jnp.concatenate([flat.mask, keep])
        srcs = tuple(
            Column(data=jnp.concatenate([fc.data, bc.data]),
                   valid=jnp.concatenate([fc.valid, bc.valid]))
            for fc, bc in zip(flat.cols, batch.cols)
        )
    rdest = jnp.cumsum(m.astype(jnp.int32)) - 1
    rdest = jnp.where(m, rdest, out_cap)
    received = jnp.sum(m, dtype=jnp.int32)

    def compact_col(c: Column) -> Column:
        if c.data.ndim == 2:
            data = jnp.zeros((out_cap, c.data.shape[1]), c.data.dtype)
        else:
            data = jnp.zeros((out_cap,), c.data.dtype)
        data = data.at[rdest].set(c.data, mode="drop")
        valid = jnp.zeros((out_cap,), jnp.bool_).at[rdest].set(c.valid, mode="drop")
        return Column(data=data, valid=valid)

    out_mask = jnp.arange(out_cap, dtype=jnp.int32) < jnp.minimum(received, out_cap)
    out = Batch(cols=tuple(compact_col(c) for c in srcs), mask=out_mask)
    dropped = jnp.maximum(received - out_cap, 0)
    return out, (overflow + dropped)[None]  # [1] per device -> [D] global


def make_shuffle(
    mesh,
    schema: Schema,
    keys: tuple[int, ...],
    local_capacity: int,
    hash_tables: dict[int, np.ndarray] | None = None,
    send_factor: float = 2.0,
    out_capacity: int | None = None,
    hot_hashes: np.ndarray | None = None,
):
    """Build a jitted shuffle: (row-sharded Batch) -> (row-sharded Batch
    repartitioned by key hash, plus per-device overflow counts).

    After the shuffle, every row whose keys hash equal lives on the same
    device — the precondition for local final aggregation / joins, exactly
    what the reference's hash router guarantees per consumer flow.

    ``hot_hashes`` (sorted or not; 64-bit key hashes) marks heavy-hitter
    keys whose rows stay on their producing device instead of shuffling to
    ``hash % D`` — the planner supplies them from build-side sampling
    (GraceHashJoinOp's reservoir) and replicates those keys' build rows so
    device-local joins stay exact. Every other row routes normally."""
    D = mesh.shape[AXIS]
    types = [schema.types[i] for i in keys]
    send_cap = max(128, int(local_capacity / D * send_factor) // 128 * 128)
    out_cap = out_capacity or local_capacity
    hot = None
    if hot_hashes is not None and len(hot_hashes) > 0:
        hot = jnp.asarray(np.sort(np.asarray(hot_hashes, dtype=np.uint64)))

    fn = functools.partial(
        _local_shuffle,
        keys=keys,
        types=types,
        hash_tables=hash_tables,
        D=D,
        send_cap=send_cap,
        out_cap=out_cap,
        hot=hot,
    )
    sharded = shard_map(
        fn,
        mesh=mesh,
        in_specs=(P(AXIS),),
        out_specs=(P(AXIS), P(AXIS)),
        check_vma=False,
    )
    # dispatch.jit, not jax.jit: an SPMD shuffle is one XLA dispatch like
    # any flow kernel — it must count into sql_kernel_dispatches
    return dispatch.jit(sharded)
