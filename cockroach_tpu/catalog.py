"""Table catalog — host-side table storage feeding device scans.

The reference reads tables from the KV layer through cFetcher
(pkg/sql/colfetcher/cfetcher.go:230); here a Table holds canonical-typed host
columns (strings already dictionary-encoded) plus per-column Dictionaries, and
materializes a device-resident padded Batch once (the "table is in HBM" model
— the TPU analog of a warmed block cache). The storage layer (cockroach_tpu/
storage) layers MVCC versions and SST-style runs beneath this.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np

from .coldata.batch import Batch, Dictionary, from_host
from .coldata.types import Family, Schema



TILE_ALIGN = 1024  # pad device tables to a multiple of this (8x128 lanes)

# canonical tile-shape menu (L0 of the cache hierarchy — see README):
# sub-tile tables pad UP to the next rung instead of to their own 1024-
# aligned cardinality, so every kernel over a small table compiles at one
# of ~5 shapes shared process-wide rather than one shape per table size.
# Tables larger than a rung keep tile-multiple padding: their downstream
# kernels already see tile-shaped slices, and padding further would add
# tiles (= dispatches) for zero compile benefit.
SHAPE_BUCKETS = (1 << 10, 1 << 13, 1 << 16, 1 << 19, 1 << 21)


def _bucket_cap(n: int) -> int:
    for b in SHAPE_BUCKETS:
        if n <= b:
            return b
    top = SHAPE_BUCKETS[-1]
    return ((n + top - 1) // top) * top


def _pad_cap(n: int, tile: int | None = None) -> int:
    """Padded device capacity: a multiple of the scan tile (so bounded-tile
    resident scans slice evenly — no full-table kernel shapes), min one tile.
    With shape bucketing (default), sub-tile tables round up the pow2 rung
    ladder; with it off, they align to 1024 lanes only (the pre-bucketing
    behavior the bit-identity sweep compares against)."""
    from .utils import settings

    if settings.get("sql.distsql.shape_buckets.enabled"):
        cap = _bucket_cap(n)
        if tile is None or tile <= 0 or cap <= tile:
            return cap
        # above one tile: tile-multiple padding (never MORE tiles than the
        # unbucketed shape — the dispatch budget must hold with padding on)
        return max(tile, ((n + tile - 1) // tile) * tile)
    align = TILE_ALIGN
    if tile is not None and n > tile:
        align = tile
    return max(align, ((n + align - 1) // align) * align)


@dataclass
class Table:
    name: str
    schema: Schema
    columns: dict[str, np.ndarray]
    valids: dict[str, np.ndarray] = field(default_factory=dict)
    dictionaries: dict[str, Dictionary] = field(default_factory=dict)
    _device: dict | None = None
    _stats: dict | None = None
    # physical clustering: host rows are stored grouped (equal values
    # adjacent) by this column prefix — e.g. TPC-H lineitem by l_orderkey,
    # KV tables by primary key. Enables the sort-free ordered aggregation
    # (colexec orderedAggregator role, ordered_aggregator.go)
    ordering: tuple[str, ...] = ()

    @property
    def num_rows(self) -> int:
        return len(next(iter(self.columns.values())))

    def dict_by_index(self) -> dict[int, Dictionary]:
        return {
            self.schema.index(name): d for name, d in self.dictionaries.items()
        }

    def set_stats(self, st) -> None:
        """Install ANALYZE-collected statistics (sql/stats.TableStats).
        Planner consumers (join order, broadcast threshold, exact-key bit
        widths) read the SNAPSHOT — deliberately stale-able, like the
        reference's optimizer stats."""
        self.table_stats = st
        # exact-key/sort-key consumers read col_stats(): refresh the (lo,
        # hi) view from the analyzed snapshot
        self._stats = {
            n: (c.lo, c.hi)
            for n, c in st.cols.items()
            if c.lo is not None and c.hi is not None
        } if st is not None else None

    def estimated_rows(self) -> int:
        """Planner cardinality: the ANALYZE snapshot when present, else the
        physical count."""
        st = getattr(self, "table_stats", None)
        return st.row_count if st is not None else self.num_rows

    def col_stats(self) -> dict[str, tuple]:
        """Per-column (lo, hi) bounds over valid rows for integer-represented
        columns (the table-statistics analog of pkg/sql/stats, reduced to
        what the kernel layer consumes: sort-key bit widths). Computed once
        on the host, cached; ANALYZE (set_stats) replaces the snapshot."""
        if getattr(self, "_stats", None) is None:
            stats: dict[str, tuple] = {}
            for name, t in zip(self.schema.names, self.schema.types):
                if t.family in (Family.FLOAT, Family.BYTES, Family.BOOL,
                                Family.JSON):
                    continue
                a = np.asarray(self.columns[name])
                if name in self.valids:
                    a = a[np.asarray(self.valids[name])]
                if len(a) == 0:
                    continue
                stats[name] = (int(a.min()), int(a.max()))
            self._stats = stats
        return self._stats

    def dense_key_info(self) -> dict[str, tuple[int, int]]:
        """{column: (lo, fanout)} for integer columns whose value IS an
        affine function of the row index: col == repeat(arange(lo, lo+n/f), f).

        fanout 1 covers surrogate primary keys (TPC-H o_orderkey = 1..N and
        friends — the reference reads the same structure out of its index
        key prefix, pkg/sql/colfetcher/cfetcher.go:230); fanout f covers
        clustered child tables (partsupp: exactly 4 contiguous rows per
        part). Joins against such a column need no hash table and no sorted
        index: the matching row index is arithmetic (ops/join.py
        DenseAnalytic). Host-verified once, cached."""
        cached = getattr(self, "_dense_keys", None)
        if cached is not None:
            return cached
        info: dict[str, tuple[int, int]] = {}
        n = self.num_rows
        for name, t in zip(self.schema.names, self.schema.types):
            if t.family not in (Family.INT, Family.DECIMAL, Family.DATE,
                                Family.TIMESTAMP, Family.INTERVAL):
                continue
            if name in self.valids or n == 0:
                continue  # NULLs break the bijection
            a = np.asarray(self.columns[name])
            if a.ndim != 1 or a.dtype.kind not in ("i", "u"):
                continue
            lo = int(a[0])
            hi = int(a[-1])
            distinct = hi - lo + 1
            if distinct <= 0 or n % distinct != 0:
                continue
            fanout = n // distinct
            if np.array_equal(
                a, np.repeat(np.arange(lo, lo + distinct, dtype=a.dtype),
                             fanout)
            ):
                info[name] = (lo, fanout)
        self._dense_keys = info
        return info

    def device_batch(self, names: tuple[str, ...] | None = None) -> Batch:
        """Device-resident batch of the requested columns. Cached per column,
        so a query never uploads columns it does not scan.

        The host source dicts are snapshotted into the cache when it is
        created: a concurrent re-host that swaps ``columns``/``valids``
        wholesale (matview materialize) leaves an in-flight reader
        uploading from the generation its cache was built over — one
        consistent snapshot, never a torn mix of old and new columns."""
        from .utils import settings

        names = names or self.schema.names
        dev = self._device
        if dev is None:
            dev = self._device = {}
        host = dev.setdefault("__host__", self.columns)
        valids = dev.setdefault("__valids__", self.valids)
        n = len(next(iter(host.values()))) if host else 0
        # pin the padded capacity when the cache is created: tile_size is a
        # live setting, and per-column uploads after a change must match the
        # capacity of already-cached columns
        cap = dev.get("__cap__")
        if cap is None:
            cap = _pad_cap(n, settings.get("sql.distsql.tile_size"))
            dev["__cap__"] = cap
        if "__mask__" not in dev:
            m = np.zeros((cap,), dtype=np.bool_)
            m[:n] = True
            dev["__mask__"] = jnp.asarray(m)
        cols = []
        for cname in names:
            if cname not in dev:
                t = self.schema.type_of(cname)
                one = Schema((cname,), (t,))
                v = {cname: valids[cname]} if cname in valids else None
                b = from_host(
                    one, {cname: np.asarray(host[cname])},
                    valids=v, capacity=cap,
                )
                dev[cname] = b.cols[0]
            cols.append(dev[cname])
        return Batch(cols=tuple(cols), mask=dev["__mask__"])

    @staticmethod
    def from_strings(
        name: str,
        schema: Schema,
        raw: dict[str, np.ndarray],
        valids: dict[str, np.ndarray] | None = None,
        ordering: tuple[str, ...] = (),
    ) -> "Table":
        """Build a table from raw host columns, dictionary-encoding STRING
        columns (object/str arrays -> int32 codes + Dictionary)."""
        cols: dict[str, np.ndarray] = {}
        dicts: dict[str, Dictionary] = {}
        for cname, t in zip(schema.names, schema.types):
            a = raw[cname]
            if t.family is Family.STRING and a.dtype.kind in ("O", "U", "S"):
                values, codes = np.unique(a.astype(str), return_inverse=True)
                dicts[cname] = Dictionary(values.astype(object))
                cols[cname] = codes.astype(np.int32)
            else:
                cols[cname] = a
        return Table(
            name=name,
            schema=schema,
            columns=cols,
            valids=valids or {},
            dictionaries=dicts,
            ordering=ordering,
        )


class Catalog:
    """Table namespace plus a monotonically increasing schema version.

    Every DDL that can invalidate a compiled plan — CREATE/DROP TABLE,
    CREATE/DROP INDEX, ALTER — bumps ``version``; the prepared-plan cache
    (sql/plancache.py) keys entries on it, so a stale plan (e.g. one built
    against a since-dropped index) can never serve another statement."""

    def __init__(self):
        self.tables: dict[str, Table] = {}
        self.version = 0

    def bump_version(self) -> int:
        self.version += 1
        return self.version

    def add(self, table: Table) -> Table:
        self.tables[table.name] = table
        self.bump_version()
        return table

    def get(self, name: str) -> Table:
        t = self.tables.get(name)
        if t is None and name.startswith("crdb_internal."):
            # virtual introspection tables materialize on read from the
            # process registries (sql/crdb_internal.py); lazy import — the
            # sql layer imports this module
            from .sql import crdb_internal as _ci

            return _ci.build(self, name)
        if t is None:
            return self.tables[name]  # KeyError with the usual shape
        return t
