"""Flow operators — the colexec operator set over the Operator contract.

Two execution paths per operator:

- **Fused streaming segments** (the TPU-first hot path): every streaming
  operator exposes ``stream_parts()`` — a pure per-tile device function plus
  its device arguments. Buffering consumers (aggregation, sort, join build)
  compose the whole streaming chain beneath them (scan slice -> filter ->
  project -> unique/semi/anti join probes -> their own per-tile work) into
  ONE jitted function, so a TPC-H probe pipeline costs one XLA dispatch per
  tile instead of one per operator. This matters doubly on TPU: XLA fuses
  elementwise work into single HBM passes, and dispatch+sync latency
  (~70ms measured over the v5e tunnel) stops scaling with plan depth.
  The reference gets pipelining from goroutine-per-processor batch pulls
  (flowinfra); here the pipeline is a traced program.
- **Per-operator jits** (fallback): general joins (dynamic output capacity),
  exchanges, and any non-fusible child keep the classic pull loop, one jit
  per operator, mirroring colexecop.Operator Next() semantics.

Buffering operators size their spools by LIVE row count (one host sync per
spool, not per tile), so downstream kernels compile at the smallest pow2
capacity that fits the data, and capacity-bucketing keeps the set of compiled
shapes tiny. Aggregation decomposes into partial/merge/finalize exactly like
CRDB's local/final aggregation around a shuffle (distsql_physical_planner.go).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..catalog import Table
from ..coldata.batch import Batch, Column, Dictionary, concat
from ..coldata.types import FLOAT64, Family, Schema
from ..ops import aggregation as agg_ops
from ..ops.aggregation import partial_layout
from ..ops import expr as ex
from ..ops import join as join_ops
from ..ops import sort as sort_ops
from . import dispatch
from .operator import OneInputOperator, Operator, SourceOperator


def _next_pow2(n: int) -> int:
    p = 1024
    while p < n:
        p *= 2
    return p


def _canonical_cap(n: int) -> int:
    """Canonical capacity for DATA-DEPENDENT intermediates (spools, learned
    emission caps, join output growth). With shape bucketing on, snaps to
    the catalog.SHAPE_BUCKETS rung ladder so a repeat run whose literals
    select a somewhat different row count still lands on the kernel shapes
    the first run compiled — pure pow2 would mint a fresh specialization at
    every doubling boundary. Stays pow2 (spool consumers assume it): rungs
    are pow2, and above the top rung pow2 growth IS the coarse ladder.
    Falls back to plain pow2 with bucketing off."""
    from ..catalog import SHAPE_BUCKETS
    from ..utils import settings

    if settings.get("sql.distsql.shape_buckets.enabled"):
        for b in SHAPE_BUCKETS:
            if n <= b:
                return b
    return _next_pow2(n)


def _live_total(tiles: list[Batch]) -> int:
    """Total live rows across spooled tiles — ONE host sync for the spool."""
    if not tiles:
        return 0
    # crlint: allow-host-sync(one stacked sync per spool finalize, not per tile)
    return int(sum(jnp.sum(t.mask, dtype=jnp.int64) for t in tiles))


def _spool_cap(tiles: list[Batch]) -> int:
    """Canonical capacity fitting the spool's LIVE rows (concat compacts)."""
    return _canonical_cap(max(1, _live_total(tiles)))


class _FusedPull:
    """Drives a fused streaming chain: one jit over (consumer tile fn o
    chain fn), pulled from the chain's source. Cached on the consumer so the
    composition traces once per operator instance."""

    def __init__(self, parts, tile_fn):
        src, chain_fn, _ = parts
        self.src = src
        self.chain = chain_fn
        self._fn = dispatch.jit(
            lambda t, *a: tile_fn(chain_fn(t, *a))
        )

    def pull(self, parts):
        _, _, args = parts
        for t in self.src.stream_tiles():
            yield self._fn(t, *args)


def _fusion_enabled() -> bool:
    # sql.distsql.fusion.enabled=off degrades EVERY fusion path (the
    # plan-build pass in flow/fuse.py AND these consumer-driven spool
    # compositions) to classic one-jit-per-operator pulls — the unfused
    # oracle the fusion-equivalence sweep compares against
    from ..utils import settings

    return settings.get("sql.distsql.fusion.enabled")


def _consume(op: OneInputOperator, tile_fn_name: str, tile_fn,
             fallback_fn=None):
    """Iterate tile_fn over the child's tiles, fused into one jit with the
    child's streaming chain when possible. fallback_fn (a jitted version of
    tile_fn) serves the classic per-operator pull path.

    tile_fn_name keys the cached composition on the consumer instance.

    Stats collection (EXPLAIN ANALYZE) forces the per-operator path so every
    operator's batch/row counts stay observable — the reference equivalently
    pays for its stats wrappers (colflow/stats.go)."""
    parts = (None if (op._collect or not _fusion_enabled())
             else op.child.stream_parts())
    if parts is None:
        fn = fallback_fn if fallback_fn is not None else tile_fn
        while True:
            b = op.child.next_batch()
            if b is None:
                return
            yield fn(b)
        return
    attr = f"_fused_{tile_fn_name}"
    cached = getattr(op, attr, None)
    if cached is None or cached.chain is not parts[1]:
        cached = _FusedPull(parts, tile_fn)
        setattr(op, attr, cached)
    yield from cached.pull(parts)


def _fold(op: OneInputOperator, tag: str, tile_raw, tile_jit, merge_raw,
          merge_jit):
    """Reduce tile_raw over the child's tiles, merging into an accumulator
    with merge_raw. The fused path composes (merge o tile o chain) into ONE
    step kernel carrying the accumulator — folding consumers (scalar/dense
    aggregation) then pay exactly one dispatch per tile instead of
    tile + merge. Returns the final accumulator (None on empty input)."""
    parts = (None if (op._collect or not _fusion_enabled())
             else op.child.stream_parts())
    if parts is None:
        acc = None
        while True:
            b = op.child.next_batch()
            if b is None:
                return acc
            st = tile_jit(b)
            acc = st if acc is None else merge_jit(acc, st)
    src, cfn, args = parts
    attr = f"_fold_{tag}"
    cached = getattr(op, attr, None)
    if cached is None or cached[0] is not cfn:
        nc = len(args)
        seed = dispatch.jit(lambda t, *a: tile_raw(cfn(t, *a[:nc])))
        step = dispatch.jit(
            lambda acc, t, *a: merge_raw(acc, tile_raw(cfn(t, *a[:nc]))),
            donate_argnums=0,
        )
        cached = (cfn, seed, step)
        setattr(op, attr, cached)
    _, seed, step = cached
    acc = None
    for t in src.stream_tiles():
        acc = seed(t, *args) if acc is None else step(acc, t, *args)
    return acc


# ---------------------------------------------------------------------------
# Scan


def _wire_source_metadata(op, table, names: tuple[str, ...]) -> None:
    """Install the plan-static metadata every table source carries:
    output_schema, per-column dictionaries, and (lo, hi) column stats —
    shared by ScanOp and IndexScanOp so the downstream contract has one
    definition."""
    idxs = tuple(table.schema.index(n) for n in names)
    op.col_idxs = idxs
    op.output_schema = table.schema.select(idxs)
    full_dicts = table.dict_by_index()
    op.dictionaries = {
        i: full_dicts[ci] for i, ci in enumerate(idxs) if ci in full_dicts
    }
    stats_fn = getattr(table, "col_stats", None)
    if callable(stats_fn):
        by_name = stats_fn()
        op.col_stats = {
            i: by_name[n]
            for i, n in enumerate(op.output_schema.names)
            if n in by_name
        }


class ScanOp(SourceOperator):
    """Tile-granular scan (cFetcher analog). Two modes:

    - resident: the table materializes once in HBM (warm block-cache model;
      KV decode happened at load) and BOUNDED tiles slice from it — the
      table capacity is padded to a multiple of the tile (catalog._pad_cap),
      so no downstream kernel ever compiles at full-table shape.
    - streaming: tables over `sql.distsql.scan_stream_rows` never fully
      occupy HBM — tiles upload host->device with DOUBLE BUFFERING (the
      next tile's async transfer is issued before the current one is
      consumed, so transfer overlaps downstream compute — SURVEY §7's
      pipelining host<->device hard part).

    In fused mode the slice itself is traced into the consumer's kernel
    (stream_tiles yields (resident_batch, offset) tokens), so a probe
    pipeline's scan costs zero extra dispatches.
    """

    def __init__(self, table: Table, columns: tuple[str, ...] | None = None,
                 tile: int | None = None,
                 shard: tuple[int, int] | None = None):
        super().__init__()
        self.table = table
        self.shard = shard  # (i, n): emit only rows [i*rows//n, (i+1)*rows//n)
        _wire_source_metadata(self, table, columns or table.schema.names)
        self._batch = None
        self.tile = tile
        self._offset = 0
        self.streaming = False
        self._shared = None

    def init(self):
        from ..utils import settings

        stream_rows = settings.get("sql.distsql.scan_stream_rows")
        self.streaming = (
            hasattr(self.table, "columns")  # KV-backed tables decode whole
            and self.table.num_rows > stream_rows
        )
        if self.streaming:
            self._init_streaming()
        else:
            self._init_resident()
            # concurrent scans of the same resident table share one tile
            # stream (flow/sharedscan.py): attach returns None for solo
            if self._res_tile < self._batch.capacity:
                from . import sharedscan

                if self._shared is not None:  # re-init (capacity retry)
                    sharedscan.detach(self, self._shared)
                self._shared = sharedscan.attach(self)
        self._offset = 0
        super().init()

    def close(self):
        if self._shared is not None:
            from . import sharedscan

            sharedscan.detach(self, self._shared)
            self._shared = None
        super().close()

    # -- resident mode ------------------------------------------------------

    def _shard_bounds(self) -> tuple[int, int | None] | None:
        """Rank range [lo, hi) for this shard; the LAST shard is unbounded
        (hi None): num_rows is the newest-visible count at now(), but the
        scan's snapshot can hold MORE live rows (older snapshot before
        deletes, or a txn's own inserts) — trailing ranks must still land
        in some shard or a distributed scan silently drops them."""
        if self.shard is None:
            return None
        i, n = self.shard
        rows = self.table.num_rows
        return (i * rows // n,
                None if i == n - 1 else (i + 1) * rows // n)

    def _init_resident(self):
        # snapshot token bracketing the decode: valid only when nothing
        # wrote between the two reads (sharedscan's adopt-batch guard)
        tok_fn = getattr(self.table, "snapshot_token", None)
        tok0 = tok_fn() if callable(tok_fn) else None
        self._batch = self.table.device_batch(self.output_schema.names)
        self._snap = (tok0 if tok0 is not None and tok0 == tok_fn()
                      else None)
        bounds = self._shard_bounds()
        if bounds is not None:
            # shard by LIVE-ROW RANK, not raw position: KV-backed tables'
            # live rows sit at scattered merged-view positions (often past
            # num_rows), so a positional mask would silently drop rows.
            # For host tables live rows are a prefix, so rank == position.
            # Positions stay stable either way (dense-key addressing holds).
            lo, hi = bounds
            rank = jnp.cumsum(self._batch.mask.astype(jnp.int32)) - 1
            keep = self._batch.mask & (rank >= lo)
            if hi is not None:
                keep = keep & (rank < hi)
            self._batch = self._batch.with_mask(keep)
        cap = self._batch.capacity
        tile = self.tile
        if tile is None or tile <= 0 or cap % tile != 0:
            tile = cap  # small tables: one tile
        self._res_tile = min(tile, cap)
        if getattr(self, "_slice_tile", None) != self._res_tile:
            res_tile = self._res_tile
            # the slice kernel takes (batch, offset) as arguments, so one
            # wrapper per tile size serves EVERY resident table
            self._slice = dispatch.jit(
                functools.partial(_slice_tile, res_tile),
                key=("slice_tile", res_tile))
            self._slice_tile = res_tile

    # -- streaming mode -----------------------------------------------------

    def _init_streaming(self):
        t = self.table
        names = self.output_schema.names
        # crlint: allow-host-sync(catalog columns are host-resident numpy)
        self._host_cols = {n: np.asarray(t.columns[n]) for n in names}
        self._host_valids = {n: t.valids[n] for n in names if n in t.valids}
        self._nrows = t.num_rows
        bounds = self._shard_bounds()
        if bounds is not None:
            lo, hi = bounds
            self._host_cols = {n: a[lo:hi] for n, a in self._host_cols.items()}
            self._host_valids = {
                n: v[lo:hi] for n, v in self._host_valids.items()
            }
            self._nrows = (hi if hi is not None else self._nrows) - lo
        # big tiles amortize dispatch (bounded so two in-flight double-
        # buffered tiles stay far under HBM); ~64 tiles per table keeps the
        # pipeline busy at any scale
        auto = _next_pow2(max(1 << 12, min(1 << 20, self._nrows // 64)))
        self._stream_tile = max(self.tile or 0, auto)
        self._prefetched = None

    def _upload(self, off: int) -> Batch:
        """Async host->device transfer of one tile (device_put returns
        before the copy completes — that is the overlap)."""
        from ..coldata.batch import from_host

        hi = min(off + self._stream_tile, self._nrows)
        arrays = {n: a[off:hi] for n, a in self._host_cols.items()}
        valids = {n: v[off:hi] for n, v in self._host_valids.items()}
        return from_host(self.output_schema, arrays, valids=valids,
                         capacity=self._stream_tile)

    def stream_parts(self):
        if not self._initialized:
            self.init()
        if self.streaming:
            self._parts_key = ("scan_stream",)
            return self, _identity_fn, ()
        self._parts_key = ("scan_slice", self._res_tile)
        # one chain head per tile size, shared by every resident scan:
        # stable identity keeps consumer compositions cached across runs
        # AND across queries (the closure is immutable, so a re-init with
        # a different tile gets a different fn, never a stale one)
        return self, _slice_parts_for(self._res_tile), ()

    def stream_tiles(self):
        """Yield raw tile tokens for the fused path (reset scan position)."""
        self._offset = 0
        if self.streaming:
            self._prefetched = None
            while True:
                t = self._next_streaming()
                if t is None:
                    return
                yield t
            return
        cap = self._batch.capacity
        # advance the shared scan position so a consumer that stops mid-way
        # and falls back to next_batch() (e.g. SortOp's spill handoff)
        # resumes after the tiles already delivered instead of re-reading
        while self._offset < cap:
            off = self._offset
            self._offset += self._res_tile
            yield (self._batch, jnp.int32(off))

    def _next_streaming(self):
        if self._offset >= self._nrows:
            return None
        cur = self._prefetched
        if cur is None:
            cur = self._upload(self._offset)
        nxt = self._offset + self._stream_tile
        # issue the next transfer BEFORE handing the current tile to
        # the consumer: its device work overlaps this upload
        self._prefetched = self._upload(nxt) if nxt < self._nrows else None
        self._offset = nxt
        return cur

    def _next(self):
        if self.streaming:
            return self._next_streaming()
        cap = self._batch.capacity
        if self._offset >= cap:
            return None
        if self._res_tile == cap:
            self._offset = cap
            return self._batch
        if self._shared is not None:
            kind, t = self._shared.next_tile(
                self, self._offset // self._res_tile)
            if kind == "tile":
                self._offset += self._res_tile
                return t
            # window trimmed past us: slice this tile solo (catch-up)
        out = self._slice(self._batch, jnp.int32(self._offset))
        self._offset += self._res_tile
        return out


class IndexScanOp(SourceOperator):
    """Index-backed read (plan/spec.IndexScan): resolve matching primary
    keys from the secondary-index keyspace, then fetch the rows in one
    Streamer pass (joinreader.go + kvstreamer/streamer.go:517 roles). The
    output batch's capacity is sized by the MATCH COUNT — downstream
    kernels compile at lookup-result shape, not table shape."""

    def __init__(self, table, index_name: str, lo: int | None,
                 hi: int | None, columns: tuple[str, ...] | None = None):
        super().__init__()
        self.table = table
        self.ix = next(i for i in table.indexes if i.name == index_name)
        self.lo, self.hi = lo, hi
        self.names = tuple(columns or table.schema.names)
        _wire_source_metadata(self, table, self.names)
        self._batch = None

    def init(self):
        from ..kv import index as ixm

        pks = ixm.scan_pks(self.table, self.ix, self.lo, self.hi)
        self._batch = ixm.Streamer(self.table).fetch(pks, self.names)
        super().init()

    def _next(self):
        b, self._batch = self._batch, None
        return b


def _identity_fn(b):
    return b


_slice_parts_fns: dict[int, object] = {}


def _slice_parts_for(res_tile: int):
    fn = _slice_parts_fns.get(res_tile)
    if fn is None:
        def fn(token):
            b, off = token
            return _slice_tile(res_tile, b, off)

        fn = _slice_parts_fns.setdefault(res_tile, fn)
    return fn


def _slice_tile(tile: int, b: Batch, off) -> Batch:
    return jax.tree_util.tree_map(
        lambda x: jax.lax.dynamic_slice_in_dim(x, off, tile, axis=0), b
    )


# ---------------------------------------------------------------------------
# Streaming ops


class HashBucketOp(OneInputOperator):
    """One outgoing stream of a HashRouter (colflow/routers.go:420): mask
    away rows whose key-hash bucket is not `part` of `n_parts`. A producer
    runs one HashBucketOp per consumer over the same scan — together they
    partition the input exactly (same splitmix64 the join/agg hash paths
    use, so co-partitioned sides land on the same peer)."""

    def __init__(self, child: Operator, keys: tuple[int, ...],
                 n_parts: int, part: int):
        super().__init__(child)
        self.output_schema = child.output_schema
        from ..coldata.types import Family
        from ..ops import hashing

        schema = child.output_schema
        for k in keys:
            if schema.types[k].family is Family.STRING:
                raise TypeError(
                    "cross-host repartition on STRING keys is not "
                    "supported (dictionary codes are per-process)"
                )

        def raw(b: Batch) -> Batch:
            h = hashing.hash_columns(
                [b.cols[k] for k in keys],
                [schema.types[k] for k in keys],
            )
            return b.with_mask(
                b.mask & (hashing.bucket(h, n_parts) == part))

        self._key = dispatch.kernel_key(
            "hashbucket", schema, keys, n_parts, part)
        self._raw = raw
        self._fn = dispatch.jit(raw, key=self._key)

    def stream_parts(self):
        return _compose_parts(self, self.child, self._raw, key=self._key)

    def _next(self):
        b = self.child.next_batch()
        return None if b is None else self._fn(b)


class RemoteStreamOp(SourceOperator):
    """Leaf that attaches to a peer host's registered flow stream at init
    and pulls its batches — the Inbox half of a host-to-host stream
    (colrpc/inbox.go:48; plan/spec.RemoteStream)."""

    def __init__(self, addr, flow_id: str, stream_id: int, schema):
        super().__init__()
        self.addr = tuple(addr)
        self.flow_id = flow_id
        self.stream_id = stream_id
        self.output_schema = schema
        self._inbox = None

    def init(self):
        from .disthost import attach_stream

        self._inbox = attach_stream(self.addr, self.flow_id,
                                    self.stream_id, self.output_schema)
        super().init()

    def _next(self):
        return self._inbox.next_batch()

    def close(self):
        if self._inbox is not None:
            self._inbox.close()


class FilterOp(OneInputOperator):
    """Predicate mask. With ``params`` (a plancache.ParamStore), the
    predicate's ex.Param leaves read their values from jit ARGUMENTS
    instead of baked constants, so a cached plan rebinds literals with
    zero new traces (the prepared-plan fast path)."""

    def __init__(self, child: Operator, predicate: ex.Expr, params=None):
        super().__init__(child)
        self.output_schema = child.output_schema
        schema = child.output_schema
        self.predicate = predicate
        self._params = params
        if params is None:
            def raw(b: Batch) -> Batch:
                return b.with_mask(ex.filter_mask(b, schema, predicate))
        else:
            def raw(b: Batch, *pv) -> Batch:
                with ex.param_scope(pv):
                    return b.with_mask(ex.filter_mask(b, schema, predicate))

        self._key = dispatch.kernel_key(
            "filter", schema, predicate, params is not None)
        self._raw = raw
        self._fn = dispatch.jit(raw, key=self._key)

    def stream_parts(self):
        extra = () if self._params is None else self._params.args()
        return _compose_parts(self, self.child, self._raw, key=self._key,
                              extra=extra)

    def _next(self):
        b = self.child.next_batch()
        if b is None:
            return None
        if self._params is None:
            return self._fn(b)
        return self._fn(b, *self._params.args())


_chain_cache: dict = {}


def _compose_parts(op, child, raw_fn, key=None, extra=()):
    """Chain raw_fn onto the child's fused streaming function (args
    pass-through; composition cached per operator instance).

    When both the child's chain and this op carry structural kernel keys,
    the composed chain function is ALSO shared process-globally (keyed on
    the key pair), so two queries with identical fused prefixes reuse one
    traced chain — the cross-query half of the kernel cache. ``extra``
    appends this op's runtime arguments (param values) after the child's;
    the chain splits them back out positionally, so values stay jit
    ARGUMENTS (re-read every run) rather than baked constants."""
    parts = child.stream_parts()
    if parts is None:
        return None
    src, cfn, cargs = parts
    ckey = getattr(child, "_parts_key", None)
    chain_key = (("chain", ckey, key, len(cargs))
                 if ckey is not None and key is not None else None)
    chain = getattr(op, "_chain_fn", None)
    if chain is None or getattr(op, "_chain_base", None) is not cfn:
        chain = (_chain_cache.get(chain_key)
                 if chain_key is not None else None)
        if chain is None:
            nc = len(cargs)

            def chain(t, *a):
                return raw_fn(cfn(t, *a[:nc]), *a[nc:])

            if chain_key is not None:
                chain = _chain_cache.setdefault(chain_key, chain)
        op._chain_fn = chain
        op._chain_base = cfn
    op._parts_key = chain_key
    return src, op._chain_fn, tuple(cargs) + tuple(extra)


class ProjectOp(OneInputOperator):
    def __init__(self, child: Operator, exprs: tuple[ex.Expr, ...],
                 names: tuple[str, ...], dict_overrides: tuple = ()):
        super().__init__(child)
        self.exprs = exprs  # JoinOp's dense-build walk maps keys through these
        schema = child.output_schema
        types = tuple(ex.expr_type(e, schema) for e in exprs)
        self.output_schema = Schema(tuple(names), types)
        # dictionaries survive through bare column references; host-side
        # string transforms attach theirs via dict_overrides
        self.dictionaries = {
            i: self.child.dictionaries[e.idx]
            for i, e in enumerate(exprs)
            if isinstance(e, ex.ColRef) and e.idx in self.child.dictionaries
        }
        for i, d in dict_overrides:
            self.dictionaries[i] = d
        # bounds propagate through computed columns (EXTRACT/arithmetic),
        # not just bare references — keeps dense-key planning alive
        self.col_stats = {}
        for i, e in enumerate(exprs):
            b = ex.expr_bounds(e, schema, self.child.col_stats)
            if b is not None:
                self.col_stats[i] = b

        def raw(b: Batch) -> Batch:
            cols = []
            for e in exprs:
                d, v = ex.eval_expr(e, b.cols, schema)
                cols.append(Column(data=d, valid=v))
            return Batch(cols=tuple(cols), mask=b.mask)

        self._key = dispatch.kernel_key("project", schema, exprs)
        self._raw = raw
        self._fn = dispatch.jit(raw, key=self._key)

    def stream_parts(self):
        return _compose_parts(self, self.child, self._raw, key=self._key)

    def _next(self):
        b = self.child.next_batch()
        return None if b is None else self._fn(b)


class LimitOp(OneInputOperator):
    def __init__(self, child: Operator, limit: int, offset: int = 0):
        super().__init__(child)
        self.output_schema = child.output_schema
        self.limit = limit
        self.offset = offset
        self._seen = 0

        def fn(b: Batch, seen):
            pos = seen + jnp.cumsum(b.mask.astype(jnp.int32)) - 1
            keep = b.mask & (pos >= offset) & (pos < offset + limit)
            return b.with_mask(keep), seen + jnp.sum(b.mask, dtype=jnp.int32)

        self._fn = dispatch.jit(
            fn, key=dispatch.kernel_key("limit", offset, limit))

    def init(self):
        super().init()
        self._seen = jnp.int32(0)
        self._done = False

    def _next(self):
        if self._done:
            return None
        b = self.child.next_batch()
        if b is None:
            return None
        out, self._seen = self._fn(b, self._seen)
        if int(self._seen) >= self.offset + self.limit:
            self._done = True
        return out


# ---------------------------------------------------------------------------
# Aggregation


class AggregateOp(OneInputOperator):
    """GROUP BY aggregation (hashAggregator analog). mode:
    - complete: input rows -> final results
    - partial:  input rows -> state columns (feeds an Exchange)
    - final:    state columns (partial layout) -> final results
    """

    def __init__(
        self,
        child: Operator,
        group_cols: tuple[int, ...],
        aggs: tuple[agg_ops.AggSpec, ...],
        mode: str = "complete",
        input_schema: Schema | None = None,
        ordered: bool = False,
        prefix_live: bool = False,
    ):
        super().__init__(child)
        self.mode = mode
        self.group_cols = group_cols
        self.aggs = aggs
        # ordered: equal group keys arrive adjacent (clustered scan —
        # Table.ordering); the per-tile grouping skips its key sort
        # (orderedAggregator role). prefix_live additionally asserts tiles
        # are live-prefix (no filters in the fused chain below), dropping
        # the dead-row compaction sort too.
        self.ordered = ordered
        self.prefix_live = prefix_live
        # string_agg runs OUTSIDE the device state pipeline: per-row
        # (group key, string code) pairs are collected host-side during
        # the spool and concatenated at finalize (the reference's concat
        # agg accumulates variable-width bytes, which has no fixed-tile
        # device representation). The device pipeline runs a count
        # placeholder in its slot; _attach_saggs overwrites the column.
        self._sagg = [(j, s) for j, s in enumerate(aggs)
                      if s.func == "string_agg"]
        if self._sagg:
            if mode != "complete":
                raise ValueError(
                    "string_agg runs in complete mode only (distributed "
                    "plans fall back to local execution, parallel/"
                    "planner.py _needs_local)"
                )
            aggs = tuple(
                agg_ops.AggSpec("count", s.col, s.name)
                if s.func == "string_agg" else s
                for s in aggs
            )
        # the schema over which aggs/group_cols were written
        base = input_schema if input_schema is not None else child.output_schema
        self.base_schema = base
        self.partial_specs, self.state_schema, self.final_map = partial_layout(
            base, group_cols, aggs
        )
        k = len(group_cols)
        self.num_keys = k
        # merge aggregation over the state layout
        self.merge_group_cols = tuple(range(k))
        self.merge_specs = agg_ops.merge_specs_for(self.partial_specs, k)
        final_schema = self._final_schema(base)
        self.output_schema = (
            self.state_schema if mode == "partial" else final_schema
        )
        keep = {
            gi: self.child.dictionaries[gi]
            for gi in group_cols
            if gi in self.child.dictionaries
        }
        if mode == "final":
            # child emits state layout; group keys are 0..k-1 already
            keep = {
                i: self.child.dictionaries[i]
                for i in range(k)
                if i in self.child.dictionaries
            }
            self.dictionaries = keep
            self.key_stats = {
                i: self.child.col_stats[i]
                for i in range(k)
                if i in self.child.col_stats
            }
        else:
            self.dictionaries = {
                group_cols.index(gi): d for gi, d in keep.items()
            }
            self.key_stats = {
                group_cols.index(gi): s
                for gi, s in self.child.col_stats.items()
                if gi in group_cols
            }
        # group keys (and their stats) survive to the output positions
        self.col_stats = dict(self.key_stats)
        # STRING group keys without numeric stats still pack tight: the
        # dictionary size bounds the code range
        for pos, d in self.dictionaries.items():
            self.col_stats.setdefault(pos, (0, max(0, len(d) - 1)))
            self.key_stats.setdefault(pos, (0, max(0, len(d) - 1)))
        # string_agg outputs get an empty Dictionary NOW (parents copy the
        # reference at construction) and fill it in place at finalize.
        # _runtime marks it: consumers whose PLAN depends on dictionary
        # contents (sort ranks, dense-agg sizing) must refuse it — at init
        # time it is still empty and would silently produce garbage
        for j, _ in self._sagg:
            d = Dictionary(np.array([], dtype=object))
            d._runtime = True
            self.dictionaries[len(group_cols) + j] = d
        # conversely, grouping BY a runtime-filled string column cannot
        # work: the group codes would be computed against an empty dict
        for gi in group_cols:
            if getattr(self.child.dictionaries.get(gi), "_runtime", False):
                raise ValueError(
                    "grouping by a string_agg result is not supported"
                )
        self._acc = None
        self._emitted = False
        self._spool_alloc = None

    def _close_spool(self) -> None:
        if self._spool_alloc is not None:
            self._spool_alloc.close()
            self._spool_alloc = None

    def _final_schema(self, base: Schema) -> Schema:
        return agg_ops.agg_output_schema(
            base, self.group_cols, self.aggs,
            "final" if self.mode == "final" else "complete",
        )

    def init(self):
        super().init()
        self._tiles: list[Batch] = []
        self._emitted = False
        self._external = None
        self._close_spool()  # cached-plan re-run: prior account is dead
        self._sagg_rows = {j: {} for j, _ in self._sagg}
        if hasattr(self, "_partial_fn"):
            return
        schema = self.base_schema
        gcols = self.group_cols
        pspecs = self.partial_specs
        sschema = self.state_schema
        mcols = self.merge_group_cols
        mspecs = self.merge_specs
        in_stats = {
            gi: s for gi, s in self.child.col_stats.items() if gi in gcols
        } if self.mode != "final" else {}
        for gi in gcols:
            if gi in self.child.dictionaries:
                in_stats.setdefault(
                    gi, (0, max(0, len(self.child.dictionaries[gi]) - 1))
                )
        merge_stats = {
            i: s for i, s in self.key_stats.items() if i < len(mcols)
        }

        ordered = self.ordered
        prefix_live = self.prefix_live

        def partial_fn(b):
            # out_capacity == input capacity: groups <= live rows, so this
            # CANNOT overflow — no device->host sync on the hot tile loop
            part, _ = agg_ops.sort_groupby(
                b, schema, gcols, pspecs, out_capacity=b.capacity,
                col_stats=in_stats,
                presorted=ordered, compact=not prefix_live,
            )
            return part

        @functools.partial(dispatch.jit, static_argnames=("cap",))
        def merge_fn(tiles, cap):
            both = concat(list(tiles), capacity=cap)
            # ordered partials stay in scan order per tile, so their
            # concatenation is still clustered; only dead pad rows between
            # tiles need compacting (the cheap single-operand sort)
            return agg_ops.sort_groupby(both, sschema, mcols, mspecs,
                                        out_capacity=cap,
                                        col_stats=merge_stats,
                                        presorted=ordered, compact=True)

        self._partial_raw = partial_fn
        self._partial_fn = dispatch.jit(partial_fn)
        self._merge_fn = merge_fn
        self._finalize_fn = dispatch.jit(self._finalize)

    def _finalize(self, state: Batch) -> Batch:
        return agg_ops.finalize_states(state, self.final_map, self.num_keys)

    def _spool(self):
        """Spool per-tile partial states (fused with the streaming chain
        beneath); merge down only when the spool exceeds workmem (rows or
        the monitor-tree byte account — the colmem.Allocator discipline)."""
        from ..utils import settings
        from .memory import Allocator, batch_bytes, note_spill

        budget = settings.get("sql.distsql.workmem_rows")
        alloc = Allocator("aggregation spool", stats=self.stats)
        self._spool_alloc = alloc
        if self.mode == "final":
            tile_raw, tile_jit = _identity_fn, _identity_fn
        else:
            tile_raw, tile_jit = self._partial_raw, self._partial_fn
        spooled = 0
        if self._sagg:
            # plain pull (no fused chain): every input tile materializes
            # its (group key, string code) pairs host-side before the
            # device partial — the host collect cannot live inside a jit
            def gen():
                while True:
                    b = self.child.next_batch()
                    if b is None:
                        return
                    self._collect_sagg(b)
                    yield tile_jit(b)

            source = gen()
        else:
            source = _consume(self, "partial", tile_raw, tile_jit)
        source_it = iter(source)
        for part in source_it:
            self._tiles.append(part)
            spooled += part.capacity
            nb = batch_bytes(part)
            over = alloc.would_exceed(nb)
            # the tile is resident whether or not the budget likes it, so
            # account it truthfully (forcing past the refusal): a spilling
            # operator's max-mem must show the footprint that tripped the
            # budget, and string_agg (which cannot spill — host-side
            # state) keeps over-budget accounting rather than none
            alloc.reserve(nb, force=over)
            if spooled > budget or over:
                self._tiles = [self._merge_down()]
                spooled = self._tiles[0].capacity
                alloc.release()
                mb = batch_bytes(self._tiles[0])
                over = alloc.would_exceed(mb)
                alloc.reserve(mb, force=over)
                if (spooled > budget or over) and not self._sagg:
                    # merge-down didn't shrink below budget: the GROUP
                    # COUNT itself exceeds memory. Hand the spooled state
                    # tiles + the rest of the partial stream to the Grace
                    # external aggregator (disk_spiller.go's swap;
                    # external_hash_aggregator.go role), attributed to the
                    # owning query's monitor
                    from .external import ChainOp, GraceAggregateOp

                    note_spill("agg")
                    self.stats.spilled = True
                    alloc.close()
                    self._spool_alloc = None

                    class _Rest:
                        def next_batch(_self):
                            return next(source_it, None)

                        def close(_self):
                            pass

                    chain = ChainOp(self._tiles, self.state_schema,
                                    self.dictionaries, _Rest())
                    chain.init()
                    self._external = GraceAggregateOp(chain, self)
                    self._external.init()
                    self._tiles = []
                    return

    # -- string_agg host path ------------------------------------------------

    # crlint: allow-host-sync(string_agg host path: object-dtype strings cannot live on device)
    def _collect_sagg(self, b: Batch) -> None:
        """Append (group key tuple -> string values) for every live row of
        one input tile, in row order."""
        mask = np.asarray(b.mask)
        idx = np.nonzero(mask)[0]
        if not len(idx):
            return
        keys = self._host_group_keys(b, idx)
        for j, spec in self._sagg:
            col = b.cols[spec.col]
            data = np.asarray(col.data)[idx]
            valid = np.asarray(col.valid)[idx]
            d = self.child.dictionaries.get(spec.col)
            store = self._sagg_rows[j]
            for key, code, ok in zip(keys, data, valid):
                if not ok:
                    continue
                v = (str(d.values[int(code)]) if d is not None
                     else str(code))
                store.setdefault(key, []).append(v)

    # crlint: allow-host-sync(string_agg host path: hashable python keys)
    def _host_group_keys(self, b: Batch, idx: np.ndarray) -> list[tuple]:
        """Hashable per-row group keys (None for NULL key columns) over the
        rows at `idx` — for SOURCE-schema batches (complete mode)."""
        parts = []
        for gi in self.group_cols:
            c = b.cols[gi]
            data = np.asarray(c.data)[idx]
            valid = np.asarray(c.valid)[idx]
            parts.append([
                (None if not ok else data[i].item())
                for i, ok in enumerate(valid)
            ])
        return list(zip(*parts)) if parts else [()] * len(idx)

    # crlint: allow-host-sync(string_agg host path: runs once at finalize)
    def _attach_saggs(self, final: Batch) -> Batch:
        """Overwrite each string_agg placeholder column with codes into a
        runtime-built Dictionary of per-group concatenations."""
        k = self.num_keys
        mask = np.asarray(final.mask)
        idx = np.nonzero(mask)[0]
        # final batch group keys are at positions 0..k-1 (output schema)
        gcols_saved = self.group_cols
        try:
            self.group_cols = tuple(range(k))
            keys = self._host_group_keys(final, idx)
        finally:
            self.group_cols = gcols_saved
        cols = list(final.cols)
        for j, spec in self._sagg:
            store = self._sagg_rows[j]
            joined = [
                spec.sep.join(store[key]) if store.get(key) else None
                for key in keys
            ]
            uniq = sorted({v for v in joined if v is not None})
            self.dictionaries[k + j].reset(np.array(uniq, dtype=object))
            code_of = {v: c for c, v in enumerate(uniq)}
            codes = np.zeros(final.capacity, np.int32)
            valid = np.zeros(final.capacity, bool)
            for row, v in zip(idx, joined):
                if v is not None:
                    codes[row] = code_of[v]
                    valid[row] = True
            cols[k + j] = Column(
                data=jnp.asarray(codes),
                valid=jnp.asarray(valid) & final.mask,
            )
        return Batch(cols=tuple(cols), mask=final.mask)

    def _merge_down(self) -> Batch:
        cap = _spool_cap(self._tiles)
        merged, ng = self._merge_fn(tuple(self._tiles), cap=cap)
        # one bounded retry loop per merge-down, not per tile
        while int(ng) > cap:
            cap = _canonical_cap(int(ng))
            merged, ng = self._merge_fn(tuple(self._tiles), cap=cap)
        return merged

    def _next(self):
        if self._external is not None:
            return self._external.next_batch()  # spilled: stream partitions
        if self._emitted:
            return None
        self._spool()
        if self._external is not None:
            return self._external.next_batch()
        self._emitted = True
        if not self._tiles:
            self._close_spool()
            return None
        # a single tile is already fully grouped UNLESS it came from a
        # "final"-mode child (exchanged state rows may repeat group keys)
        if len(self._tiles) == 1 and self.mode != "final":
            acc = self._tiles[0]
        else:
            acc = self._merge_down()
        self._tiles = []
        self._close_spool()  # spool tiles are dead; the account drains
        if self.mode == "partial":
            return acc
        out = self._finalize_fn(acc)
        if self._sagg:
            out = self._attach_saggs(out)
        return out

    def close(self):
        super().close()
        self._close_spool()


class ScalarAggregateOp(OneInputOperator):
    """Aggregation without GROUP BY — exactly one output row, even on empty
    input (SQL scalar aggregate semantics)."""

    def __init__(self, child: Operator, aggs: tuple[agg_ops.AggSpec, ...]):
        super().__init__(child)
        self.aggs = aggs
        base = child.output_schema
        self.base_schema = base
        names, types = [], []
        for spec in aggs:
            names.append(spec.name or spec.func)
            types.append(
                FLOAT64 if spec.func == "avg"
                else agg_ops.agg_output_type(spec, base)
            )
        self.output_schema = Schema(tuple(names), tuple(types))
        self.dictionaries = {}
        self.col_stats = {}
        self._tile_raw = lambda b: agg_ops.scalar_tile_states(b, aggs, base)
        self._tile_fn = dispatch.jit(self._tile_raw)
        self._merge_raw = (
            lambda acc, new: agg_ops.scalar_merge_states(aggs, acc, new)
        )
        self._merge_fn = dispatch.jit(self._merge_raw)
        self._emitted = False

    def init(self):
        super().init()
        self._emitted = False

    def _next(self):
        if self._emitted:
            return None
        acc = _fold(self, "scalar", self._tile_raw, self._tile_fn,
                    self._merge_raw, self._merge_fn)
        self._emitted = True
        return agg_ops.scalar_result_batch(
            self.aggs, self.base_schema, self.output_schema, acc
        )


# ---------------------------------------------------------------------------
# Sort / Distinct


class SortOp(OneInputOperator):
    """Buffering sorter (NewSorter analog): spool all tiles, one device sort
    at the pow2 capacity fitting the spool's LIVE rows."""

    def __init__(self, child: Operator, keys: tuple[sort_ops.SortKey, ...]):
        super().__init__(child)
        self.output_schema = child.output_schema
        self.keys = keys
        self._emitted = False
        self._spool_alloc = None

    def close(self):
        super().close()
        if self._spool_alloc is not None:
            self._spool_alloc.close()
            self._spool_alloc = None

    def init(self):
        super().init()
        self._emitted = False
        self._external = None
        if self._spool_alloc is not None:  # cached-plan re-run
            self._spool_alloc.close()
            self._spool_alloc = None
        if hasattr(self, "_fn"):
            return
        rank_tables = {
            k.col: self.child.dictionaries[k.col].ranks
            for k in self.keys
            if k.col in self.child.dictionaries
        }
        for k in self.keys:
            if getattr(self.child.dictionaries.get(k.col), "_runtime",
                       False):
                # the dict fills at the child's finalize — its ranks here
                # are empty and would sort garbage
                raise ValueError(
                    "ORDER BY a string_agg result is not supported"
                )
        schema = self.output_schema
        keys = self.keys
        col_stats = dict(self.child.col_stats)

        @functools.partial(dispatch.jit, static_argnames=("cap",))
        def fn(batches, cap):
            big = concat(list(batches), capacity=cap)
            return sort_ops.sort_batch(big, schema, keys, rank_tables,
                                       col_stats)

        self._fn = fn

    def _next(self):
        from ..utils import settings
        from .memory import Allocator, batch_bytes, note_spill

        if self._emitted:
            return None
        if getattr(self, "_external", None) is not None:
            return self._external.next_batch()
        tiles = []
        total = 0
        budget = settings.get("sql.distsql.workmem_rows")
        alloc = self._spool_alloc = Allocator("sort spool", stats=self.stats)
        for b in _consume(self, "spool", _identity_fn):
            nb = batch_bytes(b)
            tiles.append(b)
            total += b.capacity
            over = alloc.would_exceed(nb)
            # account the tile even past the budget (it is resident, and
            # the spilling operator's max-mem must reflect it)
            alloc.reserve(nb, force=over)
            if total > budget or over:
                # spill: hand the spooled tiles + the rest of the input to
                # the external range-partitioned sort (disk_spiller swap) —
                # triggered by the ROW budget or the byte ACCOUNT,
                # attributed to the owning query's monitor
                from .external import ChainOp, ExternalSortOp

                note_spill("sort")
                self.stats.spilled = True
                alloc.close()
                self._spool_alloc = None
                chain = ChainOp(tiles, self.output_schema,
                                self.child.dictionaries, self.child)
                self._external = ExternalSortOp(
                    chain, self.keys, budget_rows=budget
                )
                self._external.init()
                return self._external.next_batch()
        self._emitted = True
        alloc.close()  # the one-shot device sort consumes the spool
        self._spool_alloc = None
        if not tiles:
            return None
        return self._fn(tuple(tiles), cap=_spool_cap(tiles))


class TopKOp(OneInputOperator):
    """Device top-k (sorttopk.go analog): fold a per-tile stable
    k-selection over the input — each step keeps the first k rows of the
    stable sort order at a static accumulator capacity — so ORDER BY ...
    LIMIT k neither spools the input nor sorts more than O(k) rows per
    tile. The accumulator merge rides inside the fused step kernel
    (_fold), so a fused chain still pays ONE dispatch per tile. Output is
    the single sorted top-k tile, bit-identical to SortOp + LimitOp (the
    oracle plan/topkopt.py rewrites away)."""

    def __init__(self, child: Operator, keys: tuple[sort_ops.SortKey, ...],
                 k: int):
        super().__init__(child)
        self.output_schema = child.output_schema
        self.keys = keys
        self.k = int(k)
        self._emitted = False

    def init(self):
        super().init()
        self._emitted = False
        if hasattr(self, "_tile_raw"):
            return
        rank_tables = {
            k.col: self.child.dictionaries[k.col].ranks
            for k in self.keys
            if k.col in self.child.dictionaries
        }
        for k in self.keys:
            if getattr(self.child.dictionaries.get(k.col), "_runtime",
                       False):
                raise ValueError(
                    "ORDER BY a string_agg result is not supported"
                )
        schema = self.output_schema
        keys = self.keys
        col_stats = dict(self.child.col_stats)
        kk = self.k
        cap = self._acc_cap = _canonical_cap(kk)

        def tile_raw(b):
            return sort_ops.topk_batch(b, schema, keys, kk, cap,
                                       rank_tables, col_stats)

        def merge_raw(acc, new):
            # concat compacts acc's live rows BEFORE new's, so the stable
            # re-selection keeps earlier-tile rows first among equal keys
            # — global stable order survives the fold
            big = concat([acc, new], capacity=2 * cap)
            return sort_ops.topk_batch(big, schema, keys, kk, cap,
                                       rank_tables, col_stats)

        self._tile_raw = tile_raw
        self._tile_fn = dispatch.jit(tile_raw)
        self._merge_raw = merge_raw
        self._merge_fn = dispatch.jit(merge_raw)

    def _next(self):
        from .memory import Allocator, batch_bytes

        if self._emitted:
            return None
        acc = _fold(self, "topk", self._tile_raw, self._tile_fn,
                    self._merge_raw, self._merge_fn)
        self._emitted = True
        if acc is None:
            return None
        # the accumulator is the operator's whole resident state — O(k),
        # but account it so EXPLAIN ANALYZE max-mem tells the truth
        alloc = Allocator("topk accumulator", stats=self.stats)
        alloc.reserve(batch_bytes(acc), force=True)
        alloc.close()
        return acc


class DistinctOp(OneInputOperator):
    """DISTINCT via grouped aggregation with no aggregates."""

    def __init__(self, child: Operator, cols: tuple[int, ...] | None = None):
        super().__init__(child)
        self.cols = cols or tuple(range(len(child.output_schema)))
        self.output_schema = child.output_schema.select(self.cols)
        self.dictionaries = {
            self.cols.index(i): d
            for i, d in child.dictionaries.items()
            if i in self.cols
        }
        self.col_stats = {
            self.cols.index(i): s
            for i, s in child.col_stats.items()
            if i in self.cols
        }
        self._inner = AggregateOp(child, self.cols, (), mode="complete")

    def init(self):
        self._inner.init()
        self._initialized = True

    def _next(self):
        return self._inner._next()


# ---------------------------------------------------------------------------
# Join


class HashJoinOp(OneInputOperator):
    """hashJoiner analog: spool+index the build side once, stream probe tiles.

    Unique-build and semi/anti probes have static output shapes and fuse into
    the consumer's streaming segment (the build batch + sorted hash index ride
    along as device arguments). General duplicate-key joins keep the
    capacity-bucketing retry loop and act as a fusion barrier."""

    def __init__(
        self,
        probe: Operator,
        build: Operator,
        probe_keys: tuple[int, ...],
        build_keys: tuple[int, ...],
        spec: join_ops.JoinSpec,
    ):
        super().__init__(probe)
        self.build = build
        self.probe_keys = probe_keys
        self.build_keys = build_keys
        self.spec = spec
        self.output_schema = join_ops.join_output_schema(
            probe.output_schema, build.output_schema, spec
        )
        self.dictionaries = dict(probe.dictionaries)
        self.col_stats = dict(probe.col_stats)
        if spec.join_type not in ("semi", "anti"):
            off = len(probe.output_schema)
            for i, d in build.dictionaries.items():
                self.dictionaries[off + i] = d
            for i, s in build.col_stats.items():
                self.col_stats[off + i] = s
        # host-side string-key bridges
        self.probe_hash_tables = {}
        self.build_hash_tables = {}
        self.build_code_remaps = {}
        for pos, (pk, bk) in enumerate(zip(probe_keys, build_keys)):
            pt = probe.output_schema.types[pk]
            if pt.family is Family.STRING:
                pd = probe.dictionaries[pk]
                bd = build.dictionaries[bk]
                if (getattr(pd, "_runtime", False)
                        or getattr(bd, "_runtime", False)):
                    # its hashes/values fill at the child's finalize —
                    # captured here they are empty and every probe misses
                    raise ValueError(
                        "joining on a string_agg result is not supported "
                        "(its dictionary fills at runtime)"
                    )
                self.probe_hash_tables[pk] = pd.hashes
                self.build_hash_tables[bk] = bd.hashes
                self.build_code_remaps[pos] = np.array(
                    [pd.code_of(str(v)) for v in bd.values], dtype=np.int32
                )
        # exact packed keys when every key column is bounded (catalog stats /
        # dictionary sizes): probes become control-flow-free — no hash, no
        # collision loop, no per-column verification gathers
        self.exact_layout = join_ops.plan_exact_key(
            probe.output_schema, probe_keys,
            build.output_schema, build_keys,
            probe.col_stats, build.col_stats,
            {pk: len(probe.dictionaries[pk]) for pk in probe_keys
             if pk in probe.dictionaries},
            have_remaps=True,
        )
        self._built = False
        # existence probes (semi/anti) and unique-build probes have static
        # probe-aligned output shapes: fusable, and eligible for the dense
        # direct-addressing strategies picked in _ensure_built
        self._fusable = (
            spec.build_unique or spec.join_type in ("semi", "anti")
        )
        self._analytic = None
        # Adaptive compact emission. A selective probe (e.g. TPC-H Q18's
        # lineitem against 14 surviving orders) emits probe-aligned tiles
        # that are almost entirely dead; every downstream kernel then pays
        # O(tile x ncols) for a handful of rows. Sticky modes:
        #   learn       first run: probe output materializes with a live
        #               count per tile (device futures, fetched ONCE at
        #               query end in post_run_update)
        #   compact     output compacts in-kernel to _emit_cap; counts keep
        #               recording so an overflow (count > cap: results
        #               truncated) is detected at query end and the runtime
        #               re-runs with a corrected cap
        #   transparent dense probes: fully fused into the consumer (no
        #               materialization, no counts)
        from ..utils import settings as _settings

        # general duplicate-key inner/left probes fuse too, as speculative
        # streaming emitters: the probe runs at a learned static out-capacity
        # inside the (chain o probe) kernel, per-tile totals record as device
        # futures, and post_run_update validates them once per query — an
        # overflow (truncated rows) grows the capacity and re-runs. Replaces
        # the per-tile int(total) host-sync retry loop as the streaming path.
        self._gen_fusable = (
            not self._fusable
            and spec.join_type in ("inner", "left")
            and _settings.get("sql.distsql.fusion.general_probe")
        )
        self._emit_mode = (
            "learn" if (self._fusable and _settings.get(
                "sql.distsql.join_compact_emit"))
            else ("general" if self._gen_fusable else "transparent")
        )
        self._emit_cap = None
        self._emit_counts: list = []
        self._emit_tilecap = 0

    def _plan_analytic(self):
        """Dense analytic build detection: the build side is a position-
        preserving chain (Scan + Filter/Project only — masks, never row
        movement) over a table whose first build-key column is an affine
        function of the row index (catalog Table.dense_key_info). Probing
        such a build is pure arithmetic + one liveness gather — no hash
        table, no sorted index, no build-spool sync (ops/join.py rationale).
        """
        if not self._fusable:
            return None
        key = self.build_keys[0]
        op = self.build
        while not isinstance(op, ScanOp):
            if isinstance(op, ProjectOp):
                e = op.exprs[key]
                if not isinstance(e, ex.ColRef):
                    return None
                key = e.idx
                op = op.child
            elif isinstance(op, FilterOp):
                op = op.child
            else:
                return None
        table = op.table
        dense_fn = getattr(table, "dense_key_info", None)
        if not callable(dense_fn):
            return None
        name = table.schema.names[op.col_idxs[key]]
        got = dense_fn().get(name)
        if got is None:
            return None
        lo, fanout = got
        if (self.spec.build_unique and fanout > 1
                and len(self.build_keys) < 2):
            return None  # fanout rows share the first key: not unique by it
        # the analytic build materializes the WHOLE table (plus projection-
        # derived columns) on device with no spill path — honor the workmem
        # byte budget the Grace-join spool enforces, falling back to the
        # metered hash path when the table is too big to pin
        from ..utils import settings

        row_bytes = sum(
            ((t.width or 8) if t.family is Family.BYTES
             else t.dtype.itemsize) + 1
            for t in self.build.output_schema.types
        ) + 1  # +1s: valid bitmaps and the row mask (bool each)
        if table.num_rows * row_bytes > settings.get(
            "sql.distsql.workmem_bytes"
        ):
            return None
        return join_ops.DenseAnalytic(
            key_lo=lo, fanout=fanout, build_rows=table.num_rows
        )

    def init(self):
        self.build.init()
        super().init()
        self._built = False
        self._grace = None
        if getattr(self, "_build_alloc", None) is not None:
            # cached-plan re-run: the prior build batch is garbage now
            self._build_alloc.close()
            self._build_alloc = None
        self._analytic = self._plan_analytic()
        if hasattr(self, "_build_fn"):
            return
        bschema = self.build.output_schema
        bkeys = self.build_keys
        bht = self.build_hash_tables or None
        layout = self.exact_layout
        eremaps = self.build_code_remaps or None

        @functools.partial(dispatch.jit, static_argnames=("cap",))
        def build_fn(tiles, cap):
            big = concat(list(tiles), capacity=cap)
            index = join_ops.build_index(big, bschema, bkeys, bht,
                                         exact_layout=layout,
                                         exact_remaps=eremaps)
            return big, index

        self._build_fn = build_fn

        @functools.partial(dispatch.jit, static_argnames=("cap",))
        def lut_fn(tiles, cap):
            big = concat(list(tiles), capacity=cap)
            return big, join_ops.build_dense_lut(big, bkeys, layout, eremaps)

        self._lut_fn = lut_fn
        self._probe_raw = None
        if not self._fusable:
            pschema = self.child.output_schema
            pkeys = self.probe_keys
            pht = self.probe_hash_tables or None
            remaps = self.build_code_remaps or None
            spec = self.spec

            def probe_gen_raw(p, build, index, out_cap):
                return join_ops.hash_join_general(
                    p, pschema, pkeys, build, bschema, bkeys, spec, out_cap,
                    pht, bht, remaps, index=index, exact_layout=layout,
                )

            self._probe_gen_raw = probe_gen_raw
            self._probe_gen_fn = functools.partial(
                dispatch.jit, static_argnames=("out_cap",)
            )(probe_gen_raw)
            self._out_cap = 0

    def _set_probe(self, kind: str):
        """Install the probe function for the index strategy chosen at build
        time. All strategies share the (probe, build_batch, index) calling
        convention so fusion and the pull path stay uniform. Cached per
        strategy kind: a fresh closure per init() would invalidate every
        downstream jit composition keyed on its identity (re-tracing the
        whole fused segment once per query run)."""
        if getattr(self, "_probe_kind", None) == kind and (
                kind != "analytic" or self._probe_analytic == self._analytic):
            return
        self._probe_kind = kind
        self._probe_analytic = self._analytic if kind == "analytic" else None
        pschema = self.child.output_schema
        bschema = self.build.output_schema
        pkeys, bkeys = self.probe_keys, self.build_keys
        pht = self.probe_hash_tables or None
        bht = self.build_hash_tables or None
        remaps = self.build_code_remaps or None
        layout = self.exact_layout
        spec = self.spec

        if kind == "analytic":
            info = self._analytic

            def probe_raw(p, build, index):
                fi, fo = join_ops.dense_analytic_probe(
                    p, pkeys, build, bkeys, info, remaps
                )
                return join_ops.emit_unique(p, build, spec, fi, fo)
        elif kind == "lut":

            def probe_raw(p, build, index):
                fi, fo = join_ops.dense_lut_probe(p, pkeys, layout, index)
                return join_ops.emit_unique(p, build, spec, fi, fo)
        elif spec.build_unique:

            def probe_raw(p, build, index):
                return join_ops.hash_join_unique(
                    p, pschema, pkeys, build, bschema, bkeys, spec,
                    pht, bht, remaps, index=index, exact_layout=layout,
                )
        else:  # sorted-index existence probe over duplicate build keys

            def probe_raw(p, build, index):
                out, _ = join_ops.hash_join_general(
                    p, pschema, pkeys, build, bschema, bkeys, spec,
                    out_capacity=1,
                    probe_hash_tables=pht, build_hash_tables=bht,
                    build_code_remaps=remaps, index=index,
                    exact_layout=layout,
                )
                return out

        self._probe_raw = probe_raw
        self._probe_fn = dispatch.jit(probe_raw)

    def _ensure_built(self):
        from ..utils import settings
        from .memory import Allocator, batch_bytes

        if self._built:
            return
        if self._analytic is not None:
            # position-preserving concat (NO compaction): row i of the build
            # batch is row i of the table, so key arithmetic addresses it.
            # No live-count host sync, no workmem spill (the build is the
            # resident table plus projection-derived columns).
            tiles = list(_consume_op(self.build, "build_spool"))
            if tiles:
                if len(tiles) == 1:
                    self._build_batch = tiles[0]
                else:
                    self._build_batch = jax.tree_util.tree_map(
                        lambda *xs: jnp.concatenate(xs), *tiles
                    )
                self._index = ()
                self._set_probe("analytic")
                self._built = True
                return
            tiles = []
        else:
            alloc = self._build_alloc = Allocator("hash join build",
                                                  stats=self.stats)
            tiles = []
            for b in _consume_op(self.build, "build_spool"):
                nb = batch_bytes(b)
                over = alloc.would_exceed(nb)
                # account the tile even past the budget: it is resident,
                # and the spilling build's max-mem must show it
                alloc.reserve(nb, force=over)
                if over:
                    # build side exceeds workmem: swap in the Grace hash join
                    # (both sides hash-partition so each partition's build
                    # fits the budget — disk_spiller.go's swap), attributed
                    # to the owning query's monitor
                    from .external import ChainOp, GraceHashJoinOp
                    from .memory import note_spill

                    note_spill("join")
                    self.stats.spilled = True
                    alloc.close()
                    self._build_alloc = None
                    chain = ChainOp(tiles + [b], self.build.output_schema,
                                    self.build.dictionaries, self.build)
                    self._grace = GraceHashJoinOp(
                        self.child, chain, self.probe_keys, self.build_keys,
                        self.spec,
                    )
                    self._grace.init()
                    self._built = True
                    return
                tiles.append(b)
        if not tiles:
            from ..coldata.batch import empty_batch

            self._build_batch = empty_batch(self.build.output_schema, 1024)
            self._index = join_ops.build_index(
                self._build_batch, self.build.output_schema, self.build_keys,
                self.build_hash_tables or None,
            )
            if self._fusable:
                self._set_probe("sorted")
        else:
            cap = _spool_cap(tiles)
            use_lut = (
                self._fusable
                and self.exact_layout is not None
                and self.exact_layout.total_bits
                <= settings.get("sql.distsql.dense_lut_bits")
            )
            if use_lut:
                self._build_batch, self._index = self._lut_fn(
                    tuple(tiles), cap=cap
                )
                self._set_probe("lut")
            else:
                self._build_batch, self._index = self._build_fn(
                    tuple(tiles), cap=cap
                )
                if self._fusable:
                    self._set_probe("sorted")
        self._built = True

    def children(self):
        return [self.child, self.build]

    def fused_depth(self) -> int:
        """Join probes sharing ONE composed jit below (and including) this
        join. The count stops where composition actually splits: at a
        fusion-pass segment boundary (_chain_split barrier source) and at
        source-mode joins (learn/compact/general emission), which drive
        their own kernel — joins below those never enter this jit."""
        d = 1
        op = self.child
        while op is not None:
            if getattr(op, "_chain_split", False):
                break
            if isinstance(op, (HashJoinOp, MergeJoinOp)):
                if getattr(op, "_emit_mode", "transparent") != "transparent":
                    break
                d += 1
            op = getattr(op, "child", None)
        return d

    def stream_parts(self):
        from ..utils import settings

        if not (self._fusable or self._gen_fusable):
            return None
        if getattr(self, "_grace", None) is not None:
            return None  # spilled: the Grace join drives the probe itself
        if not self._initialized:
            self.init()
        if self._emit_mode != "transparent":
            # learn/compact: this join is a tile SOURCE — it drives the
            # child chain through its own (chain o probe [o compact])
            # kernel, records a live count per tile (device future, fetched
            # once per query in post_run_update) and hands downstream
            # consumers small compacted tiles to compose their kernels on.
            # Costs one extra async dispatch per tile; saves O(tile x
            # ncols) per downstream operator when the probe is selective.
            return self, _identity_fn, ()
        if self.fused_depth() > settings.get("sql.distsql.max_fused_joins"):
            # compile-size safety valve: very deep probe pipelines split at
            # this join (it runs as its own per-operator jit) so one fused
            # segment never accretes unbounded XLA program size
            return None
        parts = self.child.stream_parts()
        if parts is None:
            return None
        self._ensure_built()
        if getattr(self, "_grace", None) is not None:
            return None  # the build spilled while spooling
        src, cfn, cargs = parts
        chain = getattr(self, "_chain_fn", None)
        if (chain is None or getattr(self, "_chain_base", None) is not cfn
                or getattr(self, "_chain_raw", None) is not self._probe_raw):
            nc = len(cargs)
            raw = self._probe_raw

            def chain(t, *a):
                return raw(cfn(t, *a[:nc]), a[nc], a[nc + 1])

            self._chain_fn = chain
            self._chain_base = cfn
            self._chain_raw = raw
        return src, self._chain_fn, cargs + (self._build_batch, self._index)

    def _emit_kernel(self, cfn, nc):
        """(chain o probe o count [o compact]) jit for source-mode emission,
        cached on (chain fn, probe fn, emission cap). General duplicate-key
        probes emit speculatively at the learned static capacity — the
        kernel's second output is the TRUE total, so a truncating overflow
        is detectable at query end without a per-tile host sync."""
        from ..coldata.batch import compact as compact_batch

        cap = self._emit_cap
        if self._emit_mode == "general":
            graw = self._probe_gen_raw
            key = (cfn, graw, cap)
            if getattr(self, "_emit_kern_key", None) == key:
                return self._emit_kern

            def kern(t, *a):
                p = cfn(t, *a[:nc]) if cfn is not None else t
                return graw(p, a[nc], a[nc + 1], cap)

        else:
            raw = self._probe_raw
            key = (cfn, raw, cap)
            if getattr(self, "_emit_kern_key", None) == key:
                return self._emit_kern

            def kern(t, *a):
                out = raw(cfn(t, *a[:nc]) if cfn is not None else t,
                          a[nc], a[nc + 1])
                cnt = jnp.sum(out.mask, dtype=jnp.int64)
                if cap is not None:
                    out = compact_batch(out, capacity=cap)
                return out, cnt

        self._emit_kern = dispatch.jit(kern)
        self._emit_kern_key = key
        return self._emit_kern

    def stream_tiles(self):
        """Source-mode drive loop (learn/compact emission)."""
        self._ensure_built()
        if getattr(self, "_grace", None) is not None:
            # build spilled mid-spool: serve grace output as plain tiles
            while True:
                b = self._grace._next()
                if b is None:
                    return
                yield b
            return
        parts = self.child.stream_parts()
        if parts is not None:
            src, cfn, cargs = parts
            args = cargs + (self._build_batch, self._index)
            if self._emit_mode == "general" and self._emit_cap is None:
                # initial speculation: FK-ish fanout <= 1 per probe row at
                # full scan tiles (the _next estimate — source tiles are raw
                # tuples here, so the setting stands in for their capacity);
                # post_run_update corrects in either direction
                from ..utils import settings

                self._emit_cap = max(4096, _canonical_cap(
                    settings.get("sql.distsql.tile_size")))
            kern = self._emit_kernel(cfn, len(cargs))
            for t in src.stream_tiles():
                out, cnt = kern(t, *args)
                self._emit_counts.append(cnt)
                if self._emit_cap is None:
                    self._emit_tilecap = max(self._emit_tilecap, out.capacity)
                yield out
            return
        kern = None
        while True:
            b = self.child.next_batch()
            if b is None:
                return
            if kern is None:
                if self._emit_mode == "general" and self._emit_cap is None:
                    self._emit_cap = max(4096, _canonical_cap(b.capacity))
                kern = self._emit_kernel(None, 0)
            out, cnt = kern(b, self._build_batch, self._index)
            self._emit_counts.append(cnt)
            if self._emit_cap is None:
                self._emit_tilecap = max(self._emit_tilecap, out.capacity)
            yield out

    def post_run_update(self) -> bool:
        if not self._emit_counts:
            return False
        # crlint: allow-host-sync(post_run_update: ONE stacked sync per query)
        counts = np.asarray(jax.block_until_ready(
            jnp.stack(self._emit_counts)
        ))
        self._emit_counts = []
        mx = int(counts.max()) if counts.size else 0
        if self._emit_mode == "general":
            # speculative duplicate-key probe: a total past the emission
            # capacity means that tile's rows were truncated — grow (with
            # headroom: every retry recompiles) and re-run the query
            if mx > self._emit_cap:
                from ..utils import log

                self._emit_cap = _canonical_cap(2 * mx)
                log.warning(log.SQL_EXEC,
                            "general join emission cap overflowed; re-running",
                            max_rows=mx)
                return True
            if mx * 8 <= self._emit_cap and self._emit_cap > 4096:
                # learned fanout far below speculation: shrink (keeping 2x
                # headroom) so steady-state tiles stop carrying dead rows
                self._emit_cap = max(4096, _canonical_cap(2 * mx))
            return False
        overflow = (
            self._emit_mode == "compact" and self._emit_cap is not None
            and mx > self._emit_cap
        )
        tile = self._emit_tilecap
        cap = max(1024, _canonical_cap(2 * mx))
        if tile and mx * 4 <= tile and cap < tile:
            # compacting only pays when the learned cap actually SHRINKS the
            # tile — at small tile sizes the cap floor equals the tile and
            # "compact" degenerates to one extra kernel per tile for nothing
            # (every join in a chain then self-drives: q9's five-join run
            # used to pay 5 kernels/tile instead of composing into 2)
            self._emit_cap = cap
            self._emit_mode = "compact"
        else:
            self._emit_mode = "transparent"
            self._emit_cap = None
        if overflow:
            from ..utils import log

            log.warning(log.SQL_EXEC,
                        "join emission cap overflowed; re-running",
                        max_rows=mx)
        return overflow

    def _next(self):
        self._ensure_built()
        if getattr(self, "_grace", None) is not None:
            return self._grace._next()
        p = self.child.next_batch()
        if p is None:
            return None
        if self._probe_raw is not None:
            if self._emit_mode != "transparent":
                out, cnt = self._emit_kernel(None, 0)(
                    p, self._build_batch, self._index
                )
                self._emit_counts.append(cnt)
                if self._emit_cap is None:
                    self._emit_tilecap = max(self._emit_tilecap, out.capacity)
                return out
            return self._probe_fn(p, self._build_batch, self._index)
        if self._out_cap <= 0:
            # initial capacity: assume FK-ish fanout <= 1 per probe row
            # (planner estimate), double on overflow — the retry recompiles,
            # so the estimate errs large
            self._out_cap = max(4096, _canonical_cap(p.capacity))
        while True:
            out, total = self._probe_gen_fn(
                p, self._build_batch, self._index, out_cap=self._out_cap
            )
            if int(total) <= self._out_cap:
                return out
            self._out_cap = _canonical_cap(int(total))

    def close(self):
        super().close()
        self.build.close()
        if getattr(self, "_build_alloc", None) is not None:
            self._build_alloc.close()
            self._build_alloc = None


def _consume_op(op: Operator, tag: str):
    """Pull every tile from `op`, fused with its streaming chain when
    possible (build-side spools ride one jit instead of one per operator)."""
    parts = (None if (op._collect or not _fusion_enabled())
             else op.stream_parts())
    if parts is None:
        while True:
            b = op.next_batch()
            if b is None:
                return
            yield b
        return
    src, cfn, args = parts
    attr = f"_fused_src_{tag}"
    cached = getattr(op, attr, None)
    if cached is None or cached[0] is not cfn:
        cached = (cfn, dispatch.jit(cfn))
        setattr(op, attr, cached)
    fn = cached[1]
    for t in src.stream_tiles():
        yield fn(t, *args)


class WindowOp(OneInputOperator):
    """Buffering window-function operator (colexecwindow analog): spool all
    tiles, one sorted segmented-scan pass appends the window columns."""

    def __init__(self, child: Operator, partition_cols: tuple[int, ...],
                 order_keys, specs):
        from ..ops import window as win_ops

        super().__init__(child)
        self.partition_cols = partition_cols
        self.order_keys = tuple(order_keys)
        self.specs = tuple(specs)
        self.output_schema = win_ops.window_output_schema(
            child.output_schema, self.specs
        )
        self.dictionaries = dict(child.dictionaries)
        # string-valued window outputs (lag/lead/min/max/first/last over a
        # STRING column) carry the source column's dictionary
        base_len = len(child.output_schema)
        for i, sp in enumerate(self.specs):
            if (sp.col is not None and sp.col in child.dictionaries
                    and sp.func in ("lag", "lead", "min", "max",
                                    "first_value", "last_value")):
                self.dictionaries[base_len + i] = child.dictionaries[sp.col]
        self._emitted = False

    def init(self):
        super().init()
        self._emitted = False
        if hasattr(self, "_fn"):
            return
        from ..ops import window as win_ops

        schema = self.child.output_schema
        # rank tables for every STRING column the kernel sorts or reduces:
        # order keys, partition keys, and min/max inputs
        need = {k.col for k in self.order_keys}
        need.update(self.partition_cols)
        need.update(
            sp.col for sp in self.specs
            if sp.col is not None and sp.func in ("min", "max")
        )
        for c in need:
            if getattr(self.child.dictionaries.get(c), "_runtime", False):
                raise ValueError(
                    "window functions over a string_agg result are not "
                    "supported (its dictionary fills at runtime)"
                )
        rank_tables = {
            c: self.child.dictionaries[c].ranks
            for c in need
            if c in self.child.dictionaries
        }
        pcols = self.partition_cols
        okeys = self.order_keys
        specs = self.specs

        @functools.partial(dispatch.jit, static_argnames=("cap",))
        def fn(batches, cap):
            big = concat(list(batches), capacity=cap)
            return win_ops.compute_windows(
                big, schema, pcols, okeys, specs, rank_tables
            )

        self._fn = fn

    def _next(self):
        if self._emitted:
            return None
        tiles = list(_consume(self, "spool", _identity_fn))
        self._emitted = True
        if not tiles:
            return None
        return self._fn(tuple(tiles), cap=_spool_cap(tiles))


class OrderedSyncOp(Operator):
    """Merge-ordered fan-in — the OrderedSynchronizer analog (colexec/
    ordered_synchronizer.eg.go): K inputs whose streams are each sorted
    on `keys` merge into one sorted stream, INCREMENTALLY: per round,
    one tile is pulled from each input that needs one, the buffered rows
    merge (concat + packed-key sort, the TPU merge idiom), and rows at or
    below the BARRIER — the smallest of the inputs' maximum buffered
    keys — are safe to emit (no later row can sort before them). Rows
    past the barrier carry to the next round in a fixed-capacity tile
    (bounded: each input contributes at most one tile beyond the
    barrier).

    Streams whenever the key list packs into uint64 words (ops/keys.py
    bit-packing; true for int/date/string/bool keys — barrier compares
    compose lexicographically across words). Float keys ride native f64
    operands and fall back to a full spool + one sort — same results, no
    streaming."""

    def __init__(self, children_ops: tuple[Operator, ...], keys):
        super().__init__()
        assert children_ops, "ordered fan-in needs at least one input"
        self._children = list(children_ops)
        self.keys = tuple(keys)
        self.output_schema = children_ops[0].output_schema
        self.dictionaries = dict(children_ops[0].dictionaries)
        self.col_stats = {}
        self._rank_tables = {
            k.col: children_ops[0].dictionaries[k.col].ranks
            for k in self.keys
            if k.col in children_ops[0].dictionaries
        }

    def children(self):
        return list(self._children)

    def _packed_words(self, b: Batch):
        """Packed sort-key words per row ([w0, w1, ...], lexicographic),
        or None when any operand is not a uint64 word (float keys ride
        native f64 — fallback path)."""
        ops = sort_ops.pack_sort_operands(
            b, self.output_schema, self.keys, self._rank_tables,
            include_mask=False,
        )
        if any(o.dtype != jnp.uint64 for o in ops):
            return None
        return ops

    @staticmethod
    def _lex_max(words, live):
        """Lexicographic max of multi-word keys over live rows (no host
        sync): fix each word greedily, narrowing the candidate set."""
        sel = live
        out = []
        for w in words:
            m = jnp.max(jnp.where(sel, w, jnp.uint64(0)))
            out.append(m)
            sel = sel & (w == m)
        return out

    @staticmethod
    def _lex_le(words, barrier):
        """rowwise (w0, w1, ...) <= (b0, b1, ...)."""
        lt = jnp.zeros(words[0].shape, jnp.bool_)
        eq = jnp.ones(words[0].shape, jnp.bool_)
        for w, b in zip(words, barrier):
            lt = lt | (eq & (w < b))
            eq = eq & (w == b)
        return lt | eq

    def init(self):
        for c in self._children:
            c.init()
        self._bufs: list[Batch | None] = [None] * len(self._children)
        self._done = [False] * len(self._children)
        self._carry: Batch | None = None
        self._flushed = False
        from ..coldata.batch import empty_batch

        probe = empty_batch(self.output_schema, 16)
        self._streaming = self._packed_words(probe) is not None
        self._spooled = None
        self._initialized = True

    # -- fallback: full spool + one sort (correct, not streaming) ----------

    def _fallback_next(self):
        if self._spooled is None:
            tiles = []
            for c in self._children:
                while True:
                    b = c.next_batch()
                    if b is None:
                        break
                    tiles.append(b)
            if not tiles:
                self._spooled = ()
                return None
            big = concat(tiles, capacity=_spool_cap(tiles))
            self._spooled = (sort_ops.sort_batch(
                big, self.output_schema, self.keys, self._rank_tables),)
        if self._spooled:
            out, self._spooled = self._spooled[0], ()
            return out
        return None

    # -- streaming rounds --------------------------------------------------

    def _round(self):
        """(emit_batch | None). Pull-missing, merge, split at barrier."""
        for i, c in enumerate(self._children):
            if not self._done[i] and self._bufs[i] is None:
                b = c.next_batch()
                if b is None:
                    self._done[i] = True
                else:
                    self._bufs[i] = b
        tiles = [b for b in self._bufs if b is not None]
        live_inputs = [
            i for i in range(len(self._children))
            if not self._done[i] or self._bufs[i] is not None
        ]
        parts = ([self._carry] if self._carry is not None else []) + tiles
        if not parts:
            return None
        cap = _spool_cap(parts)
        big = concat(parts, capacity=cap)
        merged = sort_ops.sort_batch(
            big, self.output_schema, self.keys, self._rank_tables)
        if all(self._done) :
            # final flush: everything is safe
            self._carry = None
            self._bufs = [None] * len(self._children)
            self._flushed = True
            return merged
        words = self._packed_words(merged)
        # barrier: lexicographic MIN over NON-EXHAUSTED inputs of their
        # buffered max key (no later row of any input can sort below it)
        bars = []
        for i in range(len(self._children)):
            if self._done[i] or self._bufs[i] is None:
                continue
            bw = self._packed_words(self._bufs[i])
            bars.append(self._lex_max(bw, self._bufs[i].mask))
        barrier = bars[0]
        for b in bars[1:]:
            # lex min of two multi-word values via the compare helper
            b_le = self._lex_le([jnp.asarray(x)[None] for x in b],
                                [jnp.asarray(x)[None] for x in barrier])[0]
            barrier = [jnp.where(b_le, x, y) for x, y in zip(b, barrier)]
        safe = self._lex_le(words, barrier)
        emit_mask = merged.mask & safe
        hold_mask = merged.mask & ~safe
        out = merged.with_mask(emit_mask)
        # carry holds the tail in ORDER (compact preserves row order);
        # bounded by sum of per-input tile caps, so a static capacity of
        # the current spool cap always fits
        from ..coldata.batch import compact as compact_batch

        self._carry = compact_batch(merged.with_mask(hold_mask),
                                    capacity=cap)
        self._bufs = [None] * len(self._children)
        return out

    def _next(self):
        if not self._streaming:
            return self._fallback_next()
        while not self._flushed:
            out = self._round()
            if out is None:
                return None
            return out
        return None

    def close(self):
        for c in self._children:
            c.close()


class ParallelUnorderedSyncOp(Operator):
    """Unordered fan-in with one PULLER THREAD per input — the
    ParallelUnorderedSynchronizer analog (colexec/parallel_unordered_
    synchronizer.go:66): batches surface in arrival order through a
    bounded queue, so inputs overlap their waits. Essential for remote
    FlowInboxes (serial draining would serialize the hosts' compute and
    network time); for local inputs it adds pipeline overlap at the cost
    of thread handoff."""

    _QUEUE_DEPTH = 4  # per-flow backpressure (bounded buffering)
    _DONE = object()

    def __init__(self, children_ops: tuple[Operator, ...]):
        super().__init__()
        assert children_ops, "fan-in needs at least one input"
        self._children = list(children_ops)
        self.output_schema = children_ops[0].output_schema
        for c in children_ops[1:]:
            assert len(c.output_schema) == len(self.output_schema), \
                "fan-in inputs must have equal arity"
        self.dictionaries = dict(children_ops[0].dictionaries)
        self.col_stats = {}

    def children(self):
        return list(self._children)

    def init(self):
        import queue
        import threading

        # a re-init (run_operator's capacity-retry loop) must not leave
        # the previous run's pullers racing the new ones on the children
        self._shutdown_pullers()
        for c in self._children:
            c.init()
        self._q = queue.Queue(
            maxsize=self._QUEUE_DEPTH * len(self._children))
        self._stop = threading.Event()
        self._live = len(self._children)
        self._threads = []
        for c in self._children:
            t = threading.Thread(target=self._pull, args=(c,),
                                 name="unordered-sync", daemon=True)
            t.start()
            self._threads.append(t)
        self._initialized = True

    def _pull(self, child: Operator) -> None:
        try:
            while not self._stop.is_set():
                b = child.next_batch()
                if b is None:
                    break
                self._q.put(b)
        except BaseException as e:  # surface in the consumer, not a log  # crlint: allow-broad-except(producer thread forwards the exception to the consumer via the queue)
            self._q.put(e)
            return
        self._q.put(self._DONE)

    def _next(self):
        while self._live > 0:
            item = self._q.get()
            if item is self._DONE:
                self._live -= 1
                continue
            if isinstance(item, BaseException):
                self._stop.set()
                raise item
            return item
        return None

    def _shutdown_pullers(self) -> None:
        """Stop + join puller threads, draining the queue while joining so
        a producer blocked in put() always gets space to observe stop."""
        if not getattr(self, "_threads", None):
            return
        import queue

        self._stop.set()
        for t in self._threads:
            while t.is_alive():
                try:
                    while True:
                        self._q.get_nowait()
                except queue.Empty:
                    pass  # drained — producers have space to observe stop
                t.join(timeout=0.05)
        self._threads = []

    def close(self):
        # children first: closing a remote FlowInbox closes its socket,
        # which is the ONLY thing that unblocks a puller stuck in a
        # timeout-less recv (the drain-while-join below only unblocks
        # pullers stuck in q.put)
        self._stop.set()
        for c in self._children:
            c.close()
        self._shutdown_pullers()


class UnionOp(Operator):
    """UNION ALL: pull each input to exhaustion in order (the plan-level
    unordered fan-in; inputs share one output schema)."""

    def __init__(self, children_ops: tuple[Operator, ...]):
        super().__init__()
        assert children_ops, "UNION ALL needs at least one input"
        self._children = list(children_ops)
        self.output_schema = children_ops[0].output_schema
        for c in children_ops[1:]:
            assert len(c.output_schema) == len(self.output_schema), \
                "UNION ALL inputs must have equal arity"
        self.dictionaries = dict(children_ops[0].dictionaries)
        self._cur = 0

    def children(self):
        return list(self._children)

    def init(self):
        for c in self._children:
            c.init()
        self._cur = 0
        self._initialized = True

    def _next(self):
        while self._cur < len(self._children):
            b = self._children[self._cur].next_batch()
            if b is not None:
                return b
            self._cur += 1
        return None

    def close(self):
        for c in self._children:
            c.close()
        super().close()


class MergeJoinOp(OneInputOperator):
    """Merge join: spool+sort the build side by exact (possibly composite)
    key order, stream probe tiles through vectorized lexicographic binary
    search (mergejoiner.go analog; no hash, no collision loop)."""

    def __init__(self, probe: Operator, build: Operator, probe_key,
                 build_key, spec):
        from ..ops import join as join_ops
        from ..ops.merge_join import _norm_keys

        super().__init__(probe)
        self.build = build
        self.probe_key = _norm_keys(probe_key)
        self.build_key = _norm_keys(build_key)
        self.spec = spec
        self.output_schema = join_ops.join_output_schema(
            probe.output_schema, build.output_schema, spec
        )
        self.dictionaries = dict(probe.dictionaries)
        self.col_stats = dict(probe.col_stats)
        if spec.join_type not in ("semi", "anti"):
            off = len(probe.output_schema)
            for i, d in build.dictionaries.items():
                self.dictionaries[off + i] = d
            for i, s in build.col_stats.items():
                self.col_stats[off + i] = s
        # STRING keys need a shared rank space per key position: remap
        # build codes into the probe dictionary's rank table (shared helper
        # with the SPMD lowering so the two paths can't diverge)
        from ..ops.merge_join import rank_tables_for

        self.probe_rank, self.build_rank = rank_tables_for(
            probe.output_schema, self.probe_key, probe.dictionaries,
            self.build_key, build.dictionaries,
        )
        self._built = False

    def children(self):
        return [self.child, self.build]

    def init(self):
        self.build.init()
        super().init()
        self._built = False
        if hasattr(self, "_probe_fn"):
            return
        from ..ops import merge_join as mj_ops

        bschema = self.build.output_schema
        bkey = self.build_key
        brank = self.build_rank

        @functools.partial(dispatch.jit, static_argnames=("cap",))
        def build_fn(tiles, cap):
            big = concat(list(tiles), capacity=cap)
            return big, mj_ops.build_merge_index(big, bschema, bkey, brank)

        self._build_fn = build_fn
        pschema = self.child.output_schema
        pkey = self.probe_key
        prank = self.probe_rank
        spec = self.spec

        @functools.partial(dispatch.jit, static_argnames=("out_cap",))
        def probe_fn(p, build, index, out_cap):
            return mj_ops.merge_join(
                p, pschema, pkey, build, bschema, bkey, spec, out_cap,
                prank, brank, build_index=index,
            )

        self._probe_fn = probe_fn
        self._out_cap = 4096

    def _ensure_built(self):
        if self._built:
            return
        tiles = list(_consume_op(self.build, "build_spool"))
        if not tiles:
            from ..coldata.batch import empty_batch
            from ..ops import merge_join as mj_ops

            self._build_batch = empty_batch(self.build.output_schema, 1024)
            self._index = mj_ops.build_merge_index(
                self._build_batch, self.build.output_schema, self.build_key,
                self.build_rank,
            )
        else:
            self._build_batch, self._index = self._build_fn(
                tuple(tiles), cap=_spool_cap(tiles)
            )
        self._built = True

    def _next(self):
        self._ensure_built()
        p = self.child.next_batch()
        if p is None:
            return None
        while True:
            out, total = self._probe_fn(
                p, self._build_batch, self._index, out_cap=self._out_cap
            )
            if int(total) <= self._out_cap:
                return out
            self._out_cap = _canonical_cap(int(total))

    def close(self):
        super().close()
        self.build.close()


# one-hot membership beats scatter only while the [rows, G] matrix stays a
# cheap fused VPU pass; past this, scatter's O(rows + G) wins
_ONEHOT_MAX_G = 64


class SmallGroupAggregateOp(OneInputOperator):
    """Dense-code aggregation for planner-bounded group key spaces — the
    hashAggregator specialization where the packed key IS the (collision-
    free) hash-table slot. Two kernels by cardinality:

    - tiny G (<= _ONEHOT_MAX_G, e.g. TPC-H Q1's returnflag x linestatus):
      one-hot membership matrix, a single fused VPU pass;
    - large-but-bounded G (e.g. GROUP BY l_orderkey with catalog bounds):
      segment scatters — O(rows) scatter + O(G) states, NO sort and NO
      live-count host sync (the sort path's per-spool capacity sync costs a
      tunnel RTT on remote-attached TPU).

    Keys are dictionary codes (lo=0) or integer-family columns bounded by
    catalog/ANALYZE stats (key_lows offsets). Rows outside the planned
    bounds (stale stats) scatter to a detectable overflow slot; the
    operator re-runs the spool through the general sort path in that case
    rather than mis-grouping, checking the overflow count ONCE per spool.

    States are positionally aligned [G] arrays, so cross-tile (and
    cross-device) merging is elementwise."""

    def __init__(self, child: Operator, group_cols: tuple[int, ...],
                 aggs: tuple[agg_ops.AggSpec, ...], key_sizes: tuple[int, ...],
                 key_lows: tuple[int, ...] | None = None):
        super().__init__(child)
        self.group_cols = group_cols
        self.aggs = aggs
        self.key_sizes = key_sizes
        self.key_lows = key_lows or (0,) * len(group_cols)
        base = child.output_schema
        self.base_schema = base
        self.partial_specs, _, self.final_map = partial_layout(
            base, group_cols, aggs
        )
        self.G, self.strides = agg_ops.dense_layout(key_sizes)
        self.output_schema = agg_ops.agg_output_schema(base, group_cols, aggs)
        self.dictionaries = {
            group_cols.index(gi): d
            for gi, d in child.dictionaries.items()
            if gi in group_cols
        }
        self.col_stats = {
            group_cols.index(gi): s
            for gi, s in child.col_stats.items()
            if gi in group_cols
        }
        # group keys keep exact bounds even without upstream stats: the
        # output column g is in [lo, lo+size)
        for pos, (size, lo) in enumerate(zip(self.key_sizes, self.key_lows)):
            self.col_stats.setdefault(pos, (lo, lo + size - 1))
        self._emitted = False

    def init(self):
        super().init()
        self._emitted = False
        if hasattr(self, "_tile_raw"):
            return
        base = self.base_schema
        gcols = self.group_cols
        strides = self.strides
        G = self.G
        sizes = self.key_sizes
        lows = self.key_lows
        pspecs = self.partial_specs

        # the one-hot kernel covers the plain reductions only; statistical
        # states (sum_f/sum_sq) always take the scatter kernel. Platform
        # split (segscan.use_scans rationale inverted): on CPU scatter is a
        # cheap serial loop and one-hot is O(rows x G) real work, so scatter
        # wins at EVERY G; on TPU the [rows, G] membership matrix rides the
        # VPU in one fused pass while scatter serializes, so tiny G keeps
        # one-hot
        from ..ops import segscan

        use_onehot = (
            segscan.use_scans()
            and G <= _ONEHOT_MAX_G
            and all(
                s.func in ("sum", "count", "count_rows", "min", "max",
                           "any_not_null") for s in pspecs
            )
        )

        def tile_fn(b: Batch):
            code, oob = agg_ops.dense_group_codes(b, gcols, strides, sizes,
                                                  lows)
            if use_onehot:
                states, rows = agg_ops.dense_onehot_states(
                    b, base, code, G, pspecs
                )
            else:
                states, rows = agg_ops.dense_scatter_states(
                    b, base, code, G, pspecs
                )
            return states, rows, jnp.sum(oob & b.mask, dtype=jnp.int64)

        def merge_fn(acc, new):
            astates, arows, aoob = acc
            nstates, nrows, noob = new
            return (agg_ops.merge_dense_states(pspecs, astates, nstates),
                    arows + nrows, aoob + noob)

        def finalize_fn(acc):
            states, rows, _ = acc
            return agg_ops.dense_finalize(
                base, gcols, strides, sizes, G, self.final_map, states, rows,
                key_lows=lows,
            )

        self._tile_raw = tile_fn
        self._tile_fn = dispatch.jit(tile_fn)
        self._merge_raw = merge_fn
        self._merge_fn = dispatch.jit(merge_fn, donate_argnums=0)
        self._finalize_fn = dispatch.jit(finalize_fn)

    def _next(self):
        if self._emitted:
            return None
        acc = _fold(self, "dense", self._tile_raw, self._tile_fn,
                    self._merge_raw, self._merge_fn)
        self._emitted = True
        if acc is None:
            return None
        if int(acc[2]) > 0:
            # stale-stats overflow: re-run the whole spool through the
            # general sort-groupby path (correctness over speed; ONE check
            # per spool, after the streaming pass)
            from ..utils import log

            log.warning(log.SQL_EXEC,
                        "dense agg overflow; sort-path fallback",
                        oob_rows=int(acc[2]))
            fb = AggregateOp(self.child, self.group_cols, self.aggs,
                             input_schema=self.base_schema)
            fb.init()
            return fb._next()
        return self._finalize_fn(acc)
