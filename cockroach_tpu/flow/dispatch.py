"""Kernel-dispatch accounting for the flow layer.

Every jitted call the engine issues is one XLA executable dispatch — and on
a remote-attached TPU each dispatch costs a tunnel round trip, so dispatch
COUNT (not FLOP count) dominates short queries. The fusion work
(flow/fuse.py, the _consume composition in flow/operators.py) exists to
drive that count down to ~one per tile; this module makes the count
observable so the win is measurable and regressions are catchable:

- ``jit`` wraps ``jax.jit`` so every *call* of the compiled function bumps
  one process-global counter (thread-safe: ParallelUnorderedSyncOp calls
  kernels from puller threads). All flow-layer kernels are jitted through
  it.
- ``flow/runtime.py`` snapshots ``total()`` around a query and attributes
  the delta to the root's ``ComponentStats.kernel_dispatches`` (surfaced
  by EXPLAIN ANALYZE).
- ``scripts/check_dispatch_budget.py`` turns the per-query count into a
  tier-1 regression budget.
"""

from __future__ import annotations

import functools
import threading

import jax

from ..utils import metric

_lock = threading.Lock()
_total = 0


def note(n: int = 1) -> None:
    """Record n dispatches issued outside a ``jit`` wrapper (direct calls
    of a shared jitted kernel, e.g. coldata.batch.compact)."""
    global _total
    with _lock:
        _total += n
    metric.KERNEL_DISPATCHES.inc(n)


def total() -> int:
    """Process-lifetime dispatch count (monotonic — snapshot before/after
    a query for per-query attribution)."""
    return _total


def jit(fn=None, **jit_kwargs):
    """``jax.jit`` with per-call dispatch accounting. Usable like jax.jit,
    both directly and via ``functools.partial(jit, static_argnames=...)``
    as a decorator."""
    if fn is None:
        return functools.partial(jit, **jit_kwargs)
    jitted = jax.jit(fn, **jit_kwargs)

    @functools.wraps(fn)
    def counted(*args, **kwargs):
        note()
        return jitted(*args, **kwargs)

    counted._jitted = jitted  # uncounted handle (AOT lowering/inspection)
    return counted
