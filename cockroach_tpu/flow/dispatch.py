"""Kernel-dispatch accounting and the process-global kernel cache.

Every jitted call the engine issues is one XLA executable dispatch — and on
a remote-attached TPU each dispatch costs a tunnel round trip, so dispatch
COUNT (not FLOP count) dominates short queries. The fusion work
(flow/fuse.py, the _consume composition in flow/operators.py) exists to
drive that count down to ~one per tile; this module makes the count
observable so the win is measurable and regressions are catchable:

- ``jit`` wraps ``jax.jit`` so every *call* of the compiled function bumps
  one process-global counter (thread-safe: ParallelUnorderedSyncOp calls
  kernels from puller threads). All flow-layer kernels are jitted through
  it.
- ``flow/runtime.py`` snapshots ``total()`` around a query and attributes
  the delta to the root's ``ComponentStats.kernel_dispatches`` (surfaced
  by EXPLAIN ANALYZE).
- ``scripts/check_dispatch_budget.py`` turns the per-query count into a
  tier-1 regression budget.

Compile-wall accounting (the L1 cache of the plan/kernel cache hierarchy —
see README "Cache hierarchy"):

- every trace bumps ``compiles()``: the wrapped function body is plain
  Python, so it executes exactly once per jax trace — and a trace is a new
  executable specialization (one XLA compile, or one persistent-cache
  deserialize). ``scripts/check_recompiles.py`` holds repeat queries to a
  ZERO delta on this counter.
- ``jit(fn, key=...)`` routes through a process-global kernel cache: two
  structurally identical kernels (same ``key``) share ONE jitted wrapper,
  so the second query's filter/project/slice reuses the first's traced
  executables instead of re-tracing an identical closure. jax.jit itself
  keys on shapes/dtypes/static args beneath each wrapper, so the composite
  key is (function identity via ``key``) x (canonical shapes) — the T5X
  PjittedFnWithContext shape. Keys must be hashable and must fully
  determine the traced computation; ``kernel_key`` returns None (= no
  sharing) for unhashable parts.
"""

from __future__ import annotations

import functools
import threading
import time

import jax

from ..utils import metric, tracing

_lock = threading.Lock()
_total = 0
_compiles = 0
_cache_hits = 0
_kernel_cache: dict = {}


def note(n: int = 1) -> None:
    """Record n dispatches issued outside a ``jit`` wrapper (direct calls
    of a shared jitted kernel, e.g. coldata.batch.compact)."""
    global _total
    with _lock:
        _total += n
    metric.KERNEL_DISPATCHES.inc(n)


def total() -> int:
    """Process-lifetime dispatch count (monotonic — snapshot before/after
    a query for per-query attribution)."""
    return _total


def note_compile(n: int = 1) -> None:
    """Record n new traces/compiles (called from inside the traced body)."""
    global _compiles
    with _lock:
        _compiles += n
    metric.KERNEL_COMPILES.inc(n)


def compiles() -> int:
    """Process-lifetime trace/compile count (monotonic — snapshot around a
    query to assert the zero-recompile serving path). Read under the
    counter lock: warm-menu workers poll this for their budget check
    concurrently with serving-path note_compile writes."""
    with _lock:
        return _compiles


def kernel_cache_hits() -> int:
    """Process-lifetime kernel-cache hits (jit(key=...) lookups answered
    by an already-built wrapper)."""
    return _cache_hits


def kernel_cache_size() -> int:
    return len(_kernel_cache)


def clear_kernel_cache() -> None:
    """Drop all shared wrappers (tests; frees the underlying executables
    only once operator trees release their references)."""
    with _lock:
        _kernel_cache.clear()


def kernel_key(*parts):
    """Build a kernel-cache key from hashable parts, or None (no sharing)
    when any part is unhashable. The key must fully determine the traced
    computation: callers put the op kind, schema, and the full expression
    tree in — and keep runtime-varying values (params, row counts) OUT."""
    try:
        hash(parts)
    except TypeError:
        return None
    return parts


def jit(fn=None, key=None, **jit_kwargs):
    """``jax.jit`` with per-call dispatch accounting, per-trace compile
    accounting, and optional process-global sharing under ``key``. Usable
    like jax.jit, both directly and via ``functools.partial(jit, ...)`` as
    a decorator."""
    if fn is None:
        return functools.partial(jit, key=key, **jit_kwargs)
    if key is not None:
        global _cache_hits
        with _lock:
            cached = _kernel_cache.get(key)
        if cached is not None:
            with _lock:
                _cache_hits += 1
            metric.KERNEL_CACHE_HITS.inc()
            return cached

    @functools.wraps(fn)
    def traced(*args, **kwargs):
        # plain-Python body: runs once per jax trace == one new compile
        note_compile()
        return fn(*args, **kwargs)

    jitted = jax.jit(traced, **jit_kwargs)

    @functools.wraps(fn)
    def counted(*args, **kwargs):
        note()
        sp = tracing.current()
        if sp is None:
            return jitted(*args, **kwargs)
        # traced call: split wall time into compile (trace happened under
        # this call) vs execute, folded into the enclosing span's tags so
        # EXPLAIN ANALYZE (DEBUG) shows where dispatch time went
        # crlint: allow-race-coverage(_compiles is a monotonic counter: every write holds _lock; these lockless GIL-atomic snapshot reads only split telemetry into compile-vs-dispatch buckets — taking _lock per dispatch on the serving hot path buys nothing a stale-by-one read can break)
        c0 = _compiles
        t0 = time.perf_counter()
        out = jitted(*args, **kwargs)
        dt_ms = (time.perf_counter() - t0) * 1e3
        if _compiles > c0:
            sp.inc_tag("jit_compiles", _compiles - c0)
            sp.inc_tag("jit_compile_ms", round(dt_ms, 3))
        else:
            sp.inc_tag("jit_dispatches", 1)
            sp.inc_tag("jit_dispatch_ms", round(dt_ms, 3))
        return out

    counted._jitted = jitted  # uncounted handle (AOT lowering/inspection)
    counted._kernel_key = key
    if key is not None:
        with _lock:
            # racing builders: first insert wins so every caller shares it
            counted = _kernel_cache.setdefault(key, counted)
    return counted
