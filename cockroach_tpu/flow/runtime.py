"""Flow execution — the FlowCoordinator/Materializer pull loop.

Reference: distsql_running.go:710 Run drives the root operator;
colexec/materializer.go:30 converts the final columnar batches to rows for
pgwire. Here run_plan pulls every tile from the root operator and materializes
live rows to host numpy columns (decoding string dictionaries).

The pull loop is double-buffered (sql.distsql.readback_overlap): tile k's
device->host copies are kicked off asynchronously as soon as the tile is
dispatched, and the blocking materialization of tile k happens while the
root computes tile k+1 — so the readback tunnel (tens of MB/s on
remote-attached TPU) overlaps compute instead of serializing after it.
"""

from __future__ import annotations

import threading

import numpy as np

from ..catalog import Catalog
from ..coldata.batch import to_host
from ..plan import builder as plan_builder
from ..plan.spec import PlanNode


def _start_readback(b) -> None:
    """Begin the device->host copy of every array in an already-dispatched
    tile (jax.Array.copy_to_host_async); the np.asarray calls inside
    to_host then find the bytes already landing instead of starting the
    transfer at block time."""
    import jax

    for leaf in jax.tree_util.tree_leaves(b):
        start = getattr(leaf, "copy_to_host_async", None)
        if start is not None:
            try:
                start()
            except Exception:  # crlint: allow-broad-except(best-effort async prefetch; to_host still blocks correctly)
                return  # best-effort: to_host still blocks correctly


class _ReadbackShrink:
    """Device-side output compaction before materialization. A top-10
    result living in a 2M-row padded tile would dominate query time on the
    readback tunnel, so large tiles compact to capacity/64 on-device.

    The decision is SPECULATIVE — no host sync in the pull loop: each
    compaction keeps a deferred device live-count and retains the original
    tile; finish() fetches all counts in one stacked sync at query end and
    re-materializes any tile the compaction truncated from its retained
    original (no recompute, no query re-run)."""

    MIN_CAP = 1 << 16

    def __init__(self):
        self._checks = []  # (output index, original tile, cap, count future)
        self._n = 0

    def shrink(self, b):
        import jax.numpy as jnp

        from ..coldata.batch import compact
        from . import dispatch

        i = self._n
        self._n += 1
        if b.capacity < self.MIN_CAP:
            return b
        cap = max(1024, b.capacity >> 6)
        count = jnp.sum(b.mask, dtype=jnp.int32)  # deferred device scalar
        out = compact(b, capacity=cap)
        dispatch.note()  # compact is a shared jitted kernel
        self._checks.append((i, b, cap, count))
        return out

    def finish(self, outs, schema, dictionaries) -> None:
        """ONE stacked count fetch; patch truncated tiles from their
        retained originals. Call only on the attempt whose output is kept
        (after _post_run_updates decides no re-run)."""
        if not self._checks:
            return
        import jax.numpy as jnp

        # crlint: allow-host-sync(deferred shrink counts: ONE stacked sync at query end by design)  # crlint: allow-mem-accounting(one int32 per shrunk tile — bounded by the query's tile count)
        counts = np.asarray(jnp.stack([c for *_, c in self._checks]))
        for (i, orig, cap, _), n in zip(self._checks, counts):
            if int(n) > cap:
                outs[i] = to_host(orig, schema, dictionaries)
        self._checks = []


def _xla_profile_ctx():
    """jax.profiler trace annotation for the query, gated behind
    sql.trace.xla_profile — TPU rounds then show up as named regions in an
    XLA profile linkable from the trace. Degrades to a no-op context when
    the profiler is unavailable."""
    from contextlib import nullcontext

    from ..utils import settings

    if not settings.get("sql.trace.xla_profile"):
        return nullcontext()
    try:
        import jax.profiler

        return jax.profiler.TraceAnnotation("cockroach_tpu.query")
    except Exception:  # crlint: allow-broad-except(profiler optional; query must run without it)
        return nullcontext()


def _fold_operator_spans(parent_span, op) -> None:
    """Fold the operator tree's ComponentStats into synthetic child spans
    (the execstats/traceanalyzer.go fold): inclusive wall time per
    operator, nesting mirroring the operator tree, so the trace tree shows
    where query latency went without per-tile span overhead in the pull
    loop. Exclusive times telescope: summing (self - children) over the
    whole subtree recovers the root operator's wall time."""
    from ..utils import tracing

    st = getattr(op, "stats", None)
    if st is None:
        child = parent_span
    else:
        child = tracing.synthetic_span(
            parent_span, f"operator/{type(op).__name__}",
            float(getattr(st, "time_s", 0.0) or 0.0),
            rows=int(getattr(st, "rows", 0)),
            batches=int(getattr(st, "batches", 0)))
    for c in op.children():
        _fold_operator_spans(child, c)


def _post_run_updates(op) -> bool:
    """Give every operator its end-of-query adaptive update (deferred
    device-counter fetch — the ONE host sync speculative execution pays per
    query). Returns True when any operator invalidated this run's output
    (speculative emission capacity overflowed) and the query must re-run."""
    rerun = op.post_run_update()
    for c in op.children():
        rerun = _post_run_updates(c) or rerun
    return rerun


def run_operator(root) -> dict[str, np.ndarray]:
    import time

    from ..utils import metric, settings, tracing
    from ..utils.errors import QueryError, _PASSTHROUGH
    from . import dispatch

    from . import memory

    metric.QUERIES.inc()
    t0 = time.perf_counter()
    d0 = dispatch.total()
    c0 = dispatch.compiles()
    overlap = settings.get("sql.distsql.readback_overlap")
    # joins the session's statement monitor when sql/session.py opened one;
    # otherwise (direct rel-API use) an ephemeral query monitor under ROOT.
    # Entered manually so the exit lands AFTER root.close() in the finally:
    # operators drain their accounts in close(), and only then is the query
    # monitor judged for drain failures.
    _scope = memory.query_scope()
    qmon = _scope.__enter__()
    try:
        # speculative-capacity retry loop: operators run with sticky learned
        # shapes and validate their deferred counters after the pull; an
        # overflow (rare: first run after a data change) re-runs the query
        # with corrected capacities rather than paying a sync per tile
        with _xla_profile_ctx():
            for attempt in range(4):
                outs: list[dict[str, np.ndarray]] = []
                shrink = _ReadbackShrink()
                with tracing.leaf_span("flow/pull", attempt=attempt) as psp:
                    root.init()
                    if overlap:
                        # one-tile lag: materialize tile k (blocking host
                        # copy) while the root's async dispatches compute
                        # tile k+1
                        prev = None
                        while True:
                            b = root.next_batch()
                            if b is not None:
                                b = shrink.shrink(b)
                                _start_readback(b)
                            if prev is not None:
                                r0 = time.perf_counter()
                                outs.append(to_host(prev, root.output_schema,
                                                    root.dictionaries))
                                if psp is not None:
                                    psp.inc_tag("readback_ms", round(
                                        (time.perf_counter() - r0) * 1e3, 3))
                            prev = b
                            if b is None:
                                break
                    else:
                        while True:
                            b = root.next_batch()
                            if b is None:
                                break
                            b = shrink.shrink(b)
                            r0 = time.perf_counter()
                            outs.append(to_host(b, root.output_schema,
                                                root.dictionaries))
                            if psp is not None:
                                psp.inc_tag("readback_ms", round(
                                    (time.perf_counter() - r0) * 1e3, 3))
                    if psp is not None:
                        psp.add_tag("tiles", len(outs))
                if not _post_run_updates(root):
                    shrink.finish(outs, root.output_schema,
                                  root.dictionaries)
                    break
            else:
                raise RuntimeError(
                    "speculative emission capacities failed to converge"
                )
    except _PASSTHROUGH:
        raise
    except Exception as e:
        # the colexecerror boundary: engine/kernel failures surface as a
        # typed query error, never a raw JAX traceback mid-flow
        from ..utils import log

        log.error(log.SQL_EXEC, "query failed",
                  operator=type(root).__name__, error=str(e))
        raise QueryError(f"operator {type(root).__name__}", e) from e
    finally:
        metric.QUERY_SECONDS.observe(time.perf_counter() - t0)
        st = getattr(root, "stats", None)
        if st is not None:
            # per-query dispatch attribution (EXPLAIN ANALYZE header);
            # dispatches are process-global so they land on the root
            st.kernel_dispatches += dispatch.total() - d0
            st.kernel_compiles += dispatch.compiles() - c0
        root.close()
        _scope.__exit__(None, None, None)
        # peak/spills survive monitor close — EXPLAIN ANALYZE's query
        # footer and sqlstats read them off the root operator
        root._query_mem_peak = qmon.high_water
        root._query_mem_spills = qmon.spills
    if not outs:
        return {n: np.array([]) for n in root.output_schema.names}
    return {
        n: np.concatenate([o[n] for o in outs])
        for n in root.output_schema.names
    }


def run_plan_with_stats(plan: PlanNode, catalog: Catalog):
    """Run with ComponentStats collection; returns (results, root operator).
    The stats land on the active tracing span."""
    from ..utils import tracing

    root = plan_builder.build(plan, catalog)
    root.collect_stats(True)
    with tracing.span("query") as sp:
        res = run_operator(root)
        sp.record(root.stats)
        _fold_operator_spans(sp, root)
    root._trace_span = sp  # EXPLAIN ANALYZE renders the tree from here
    _LAST_TRACE.span = sp
    return res, root


_LAST_TRACE = threading.local()


def last_trace_span():
    """This thread's most recent run_plan_with_stats root span — EXPLAIN
    ANALYZE (DEBUG) reads it for bundle capture after the rel API has
    already discarded the root operator."""
    return getattr(_LAST_TRACE, "span", None)


def run_plan(plan: PlanNode, catalog: Catalog) -> dict[str, np.ndarray]:
    from ..utils import settings

    if settings.get("sql.stats.collect_execution_stats"):
        res, _ = run_plan_with_stats(plan, catalog)
        return res
    return run_operator(plan_builder.build(plan, catalog))
