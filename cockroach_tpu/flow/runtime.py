"""Flow execution — the FlowCoordinator/Materializer pull loop.

Reference: distsql_running.go:710 Run drives the root operator;
colexec/materializer.go:30 converts the final columnar batches to rows for
pgwire. Here run_plan pulls every tile from the root operator and materializes
live rows to host numpy columns (decoding string dictionaries)."""

from __future__ import annotations

import numpy as np

from ..catalog import Catalog
from ..coldata.batch import to_host
from ..plan import builder as plan_builder
from ..plan.spec import PlanNode


def _shrink_for_readback(b):
    """Compact a sparse output tile to a small pow2 capacity on-device before
    materializing. Device->host readback over the TPU tunnel runs at tens of
    MB/s — a top-10 result living in a 2M-row padded tile would dominate
    query time without this."""
    from ..coldata.batch import compact

    if b.capacity < (1 << 16):
        return b
    import jax.numpy as jnp

    n = int(jnp.sum(b.mask, dtype=jnp.int32))
    cap = 1024
    while cap < n:
        cap *= 2
    if cap * 2 <= b.capacity:
        b = compact(b, capacity=cap)
    return b


def _post_run_updates(op) -> bool:
    """Give every operator its end-of-query adaptive update (deferred
    device-counter fetch — the ONE host sync speculative execution pays per
    query). Returns True when any operator invalidated this run's output
    (speculative emission capacity overflowed) and the query must re-run."""
    rerun = op.post_run_update()
    for c in op.children():
        rerun = _post_run_updates(c) or rerun
    return rerun


def run_operator(root) -> dict[str, np.ndarray]:
    import time

    from ..utils import metric
    from ..utils.errors import QueryError, _PASSTHROUGH

    metric.QUERIES.inc()
    t0 = time.perf_counter()
    try:
        # speculative-capacity retry loop: operators run with sticky learned
        # shapes and validate their deferred counters after the pull; an
        # overflow (rare: first run after a data change) re-runs the query
        # with corrected capacities rather than paying a sync per tile
        for attempt in range(4):
            outs: list[dict[str, np.ndarray]] = []
            root.init()
            while True:
                b = root.next_batch()
                if b is None:
                    break
                b = _shrink_for_readback(b)
                outs.append(to_host(b, root.output_schema, root.dictionaries))
            if not _post_run_updates(root):
                break
        else:
            raise RuntimeError(
                "speculative emission capacities failed to converge"
            )
    except _PASSTHROUGH:
        raise
    except Exception as e:
        # the colexecerror boundary: engine/kernel failures surface as a
        # typed query error, never a raw JAX traceback mid-flow
        from ..utils import log

        log.error(log.SQL_EXEC, "query failed",
                  operator=type(root).__name__, error=str(e))
        raise QueryError(f"operator {type(root).__name__}", e) from e
    finally:
        metric.QUERY_SECONDS.observe(time.perf_counter() - t0)
        root.close()
    if not outs:
        return {n: np.array([]) for n in root.output_schema.names}
    return {
        n: np.concatenate([o[n] for o in outs])
        for n in root.output_schema.names
    }


def run_plan_with_stats(plan: PlanNode, catalog: Catalog):
    """Run with ComponentStats collection; returns (results, root operator).
    The stats land on the active tracing span."""
    from ..utils import tracing

    root = plan_builder.build(plan, catalog)
    root.collect_stats(True)
    with tracing.span("query") as sp:
        res = run_operator(root)
        sp.record(root.stats)
    return res, root


def run_plan(plan: PlanNode, catalog: Catalog) -> dict[str, np.ndarray]:
    from ..utils import settings

    if settings.get("sql.stats.collect_execution_stats"):
        res, _ = run_plan_with_stats(plan, catalog)
        return res
    return run_operator(plan_builder.build(plan, catalog))
