"""Plan-fragment wire format — the execinfrapb spec-shipping reduction.

Reference: SetupFlowRequest carries a FlowSpec of ProcessorSpecs
(pkg/sql/execinfrapb/api.proto:143, processors*.proto); the remote node
builds operators from the SPEC, not from SQL text. This module serializes
the plan IR (plan/spec.py) and its expressions (ops/expr.py) to JSON so a
flow fragment travels to a peer process and rebuilds there with
plan/builder.py against the peer's catalog.

Scope: the scan->filter->project->partial-aggregate fragments the host
distributor ships (flow/disthost.py). Joins/sorts stay on the gateway for
now — the same encoder grows with the planner."""

from __future__ import annotations

import numpy as np

from ..coldata import types as T
from ..ops import expr as ex
from ..ops.aggregation import AggSpec
from ..plan import spec as S


# -- types -------------------------------------------------------------------


def _enc_type(t: T.SQLType) -> dict:
    return {"family": t.family.name, "width": t.width,
            "precision": t.precision, "scale": t.scale}


def _dec_type(d: dict) -> T.SQLType:
    return T.SQLType(T.Family[d["family"]], d["width"], d["precision"],
                     d["scale"])


# -- expressions -------------------------------------------------------------


def enc_expr(e: ex.Expr) -> dict:
    if isinstance(e, ex.ColRef):
        return {"k": "col", "i": e.idx}
    if isinstance(e, ex.Const):
        v = e.value
        if isinstance(v, (np.integer,)):
            v = int(v)
        elif isinstance(v, (np.floating,)):
            v = float(v)
        return {"k": "const", "v": v, "t": _enc_type(e.type)}
    if isinstance(e, ex.Cmp):
        return {"k": "cmp", "op": e.op, "l": enc_expr(e.left),
                "r": enc_expr(e.right)}
    if isinstance(e, ex.BinOp):
        return {"k": "bin", "op": e.op, "l": enc_expr(e.left),
                "r": enc_expr(e.right)}
    if isinstance(e, ex.BoolOp):
        return {"k": "bool", "op": e.op,
                "args": [enc_expr(a) for a in e.args]}
    if isinstance(e, ex.Not):
        return {"k": "not", "a": enc_expr(e.arg)}
    if isinstance(e, ex.IsNull):
        return {"k": "isnull", "a": enc_expr(e.arg),
                "negate": bool(e.negate)}
    if isinstance(e, ex.Coalesce):
        return {"k": "coalesce", "args": [enc_expr(a) for a in e.args]}
    if isinstance(e, ex.Cast):
        return {"k": "cast", "a": enc_expr(e.arg), "t": _enc_type(e.to)}
    if isinstance(e, ex.ExtractYear):
        return {"k": "year", "a": enc_expr(e.arg)}
    if isinstance(e, ex.Func1):
        return {"k": "func1", "name": e.func, "a": enc_expr(e.arg)}
    if isinstance(e, ex.Case):
        return {"k": "case",
                "whens": [[enc_expr(c), enc_expr(v)] for c, v in e.whens],
                "else": enc_expr(e.otherwise)}
    if isinstance(e, ex.CodeLookup):
        return {"k": "codes", "col": e.col,
                "table": np.asarray(e.table).tolist(),
                "t": _enc_type(e.out_type)}
    raise TypeError(f"unencodable expr {type(e).__name__}")


def dec_expr(d: dict) -> ex.Expr:
    k = d["k"]
    if k == "col":
        return ex.ColRef(d["i"])
    if k == "const":
        return ex.Const(d["v"], _dec_type(d["t"]))
    if k == "cmp":
        return ex.Cmp(d["op"], dec_expr(d["l"]), dec_expr(d["r"]))
    if k == "bin":
        return ex.BinOp(d["op"], dec_expr(d["l"]), dec_expr(d["r"]))
    if k == "bool":
        return ex.BoolOp(d["op"], tuple(dec_expr(a) for a in d["args"]))
    if k == "not":
        return ex.Not(dec_expr(d["a"]))
    if k == "isnull":
        return ex.IsNull(dec_expr(d["a"]), d.get("negate", False))
    if k == "coalesce":
        return ex.Coalesce(tuple(dec_expr(a) for a in d["args"]))
    if k == "cast":
        return ex.Cast(dec_expr(d["a"]), _dec_type(d["t"]))
    if k == "year":
        return ex.ExtractYear(dec_expr(d["a"]))
    if k == "func1":
        return ex.Func1(d["name"], dec_expr(d["a"]))
    if k == "case":
        return ex.Case(
            tuple((dec_expr(c), dec_expr(v)) for c, v in d["whens"]),
            dec_expr(d["else"]),
        )
    if k == "codes":
        return ex.CodeLookup(d["col"], np.asarray(d["table"]),
                             _dec_type(d["t"]))
    raise TypeError(f"unknown expr kind {k}")


# -- plan nodes --------------------------------------------------------------


def enc_plan(p: S.PlanNode) -> dict:
    if isinstance(p, S.TableScan):
        return {"k": "scan", "table": p.table,
                "columns": list(p.columns) if p.columns else None,
                "shard": list(p.shard) if p.shard else None}
    if isinstance(p, S.Filter):
        return {"k": "filter", "in": enc_plan(p.input),
                "pred": enc_expr(p.predicate)}
    if isinstance(p, S.Project):
        if p.dict_overrides:
            raise TypeError("dict-override projections do not ship")
        return {"k": "project", "in": enc_plan(p.input),
                "exprs": [enc_expr(e) for e in p.exprs],
                "names": list(p.names)}
    if isinstance(p, S.Aggregate):
        return {"k": "agg", "in": enc_plan(p.input),
                "group_cols": list(p.group_cols),
                "aggs": [[a.func, a.col, a.name] for a in p.aggs],
                "mode": p.mode}
    if isinstance(p, S.HashBucket):
        return {"k": "bucket", "in": enc_plan(p.input),
                "keys": list(p.keys), "n_parts": p.n_parts, "part": p.part}
    if isinstance(p, S.RemoteStream):
        return {"k": "remote", "addr": list(p.addr), "flow_id": p.flow_id,
                "stream_id": p.stream_id, "schema": enc_schema(p.schema)}
    if isinstance(p, S.StreamUnion):
        return {"k": "stream_union",
                "inputs": [enc_plan(x) for x in p.inputs]}
    if isinstance(p, S.HashJoin):
        return {"k": "hash_join", "probe": enc_plan(p.probe),
                "build": enc_plan(p.build),
                "probe_keys": list(p.probe_keys),
                "build_keys": list(p.build_keys),
                "join_type": p.spec.join_type,
                "build_unique": p.spec.build_unique}
    raise TypeError(f"unshippable plan node {type(p).__name__}")


def enc_schema(s: T.Schema) -> dict:
    return {"names": list(s.names), "types": [_enc_type(t) for t in s.types]}


def dec_schema(d: dict) -> T.Schema:
    return T.Schema(tuple(d["names"]),
                    tuple(_dec_type(t) for t in d["types"]))


def dec_plan(d: dict) -> S.PlanNode:
    k = d["k"]
    if k == "scan":
        return S.TableScan(
            d["table"],
            tuple(d["columns"]) if d["columns"] else None,
            shard=tuple(d["shard"]) if d["shard"] else None,
        )
    if k == "filter":
        return S.Filter(dec_plan(d["in"]), dec_expr(d["pred"]))
    if k == "project":
        return S.Project(dec_plan(d["in"]),
                         tuple(dec_expr(e) for e in d["exprs"]),
                         tuple(d["names"]))
    if k == "agg":
        return S.Aggregate(
            dec_plan(d["in"]), tuple(d["group_cols"]),
            tuple(AggSpec(f, c, n) for f, c, n in d["aggs"]),
            mode=d["mode"],
        )
    if k == "bucket":
        return S.HashBucket(dec_plan(d["in"]), tuple(d["keys"]),
                            d["n_parts"], d["part"])
    if k == "remote":
        return S.RemoteStream(tuple(d["addr"]), d["flow_id"],
                              d["stream_id"], dec_schema(d["schema"]))
    if k == "stream_union":
        return S.StreamUnion(tuple(dec_plan(x) for x in d["inputs"]))
    if k == "hash_join":
        from ..ops.join import JoinSpec

        return S.HashJoin(
            dec_plan(d["probe"]), dec_plan(d["build"]),
            tuple(d["probe_keys"]), tuple(d["build_keys"]),
            JoinSpec(d["join_type"], d["build_unique"]),
        )
    raise TypeError(f"unknown plan kind {k}")
