"""Memory accounting — the colmem.Allocator / mon.BytesMonitor analog.

Reference: pkg/sql/colmem/allocator.go:32 wraps every batch mutation with
byte accounting against a BytesMonitor; pkg/sql/colexec/colexecdisk/
disk_spiller.go:103 swaps an in-memory operator for its external variant
when the account would exceed the budget. Here buffering operators charge
their spools to an Allocator sized by `sql.distsql.workmem_bytes` (device
HBM is the scarce resource; XLA owns the actual allocations, so accounting
tracks LOGICAL bytes of live tiles — capacity x dtype width — which is what
HBM pressure follows under static shapes)."""

from __future__ import annotations


from ..coldata.batch import Batch


class BudgetExceededError(Exception):
    """An operator's reservation would exceed its memory budget — callers
    spill to the external variant or fail the query cleanly."""

    def __init__(self, op: str, want: int, budget: int):
        super().__init__(
            f"{op}: memory budget exceeded "
            f"({want} bytes wanted, budget {budget})"
        )
        self.want = want
        self.budget = budget


def batch_bytes(b: Batch) -> int:
    """Logical device bytes of a tile: data + valid bitmap per column, plus
    the liveness mask (bools are 1 byte under XLA's dense layout)."""
    total = b.capacity  # mask
    for c in b.cols:
        total += c.data.size * c.data.dtype.itemsize
        total += c.valid.size * c.valid.dtype.itemsize
    return int(total)


class Allocator:
    """Byte account for one operator (or operator subtree).

    Unlike the reference's hierarchical monitors, budgets here are flat
    per-operator accounts against the workmem setting — the multi-tenant
    monitor tree arrives with the control plane."""

    def __init__(self, op: str, budget: int | None = None):
        from ..utils import settings

        self.op = op
        self.budget = (budget if budget is not None
                       else settings.get("sql.distsql.workmem_bytes"))
        self.used = 0
        self.high_water = 0

    def would_exceed(self, nbytes: int) -> bool:
        return self.used + int(nbytes) > self.budget

    def reserve(self, nbytes: int) -> None:
        n = int(nbytes)
        if self.used + n > self.budget:
            raise BudgetExceededError(self.op, self.used + n, self.budget)
        self.used += n
        self.high_water = max(self.high_water, self.used)

    def reserve_batch(self, b: Batch) -> int:
        n = batch_bytes(b)
        self.reserve(n)
        return n

    def release(self, nbytes: int | None = None) -> None:
        self.used = 0 if nbytes is None else max(0, self.used - int(nbytes))
