"""Memory accounting — the mon.BytesMonitor tree + colmem.Allocator analog.

Reference: pkg/util/mon/bytes_usage.go:240 arranges BytesMonitor instances
into a tree (node root -> session -> txn/query -> operator accounts);
every reservation charges the whole ancestor chain, so the root's gauge is
the node's true SQL memory figure and a query's high water is its peak.
pkg/sql/colmem/allocator.go:32 wraps batch mutations with byte accounting;
pkg/sql/colexec/colexecdisk/disk_spiller.go:103 swaps an in-memory
operator for its external variant when the account would exceed the
budget.

Here the same tree over LOGICAL device bytes (capacity x dtype width —
XLA owns the actual HBM allocations; under static shapes logical bytes
are what HBM pressure follows, cross-checkable against
``device_memory_stats`` where the backend reports them):

- ``ROOT`` is the process (node) monitor feeding the ``sql_mem_current``/
  ``sql_mem_max`` gauges;
- sessions hang a monitor off ROOT (sql/session.py);
- every statement opens a QUERY monitor via :func:`query_scope` (a
  contextvar carries it, so operators need no constructor plumbing);
- buffering operators open :class:`Allocator` accounts under the current
  query monitor, budgeted by ``sql.distsql.workmem_bytes`` — exceeding
  the budget raises :class:`BudgetExceededError` and the operator spills
  to its external variant, attributed to the owning query by
  :func:`note_spill`.

A query monitor that closes with bytes still reserved is a LEAK (an
operator failed to release its account): it is counted in
``sql_mem_query_leaks`` and surfaced through :func:`drain_failures` so
scripts/check_no_leaks.py can assert drains across the test suite.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import threading
import weakref

from ..coldata.batch import Batch
from ..utils import metric


class BudgetExceededError(Exception):
    """An operator's reservation would exceed its memory budget — callers
    spill to the external variant or fail the query cleanly."""

    def __init__(self, op: str, want: int, budget: int):
        super().__init__(
            f"{op}: memory budget exceeded "
            f"({want} bytes wanted, budget {budget})"
        )
        self.want = want
        self.budget = budget


def batch_bytes(b: Batch) -> int:
    """Logical device bytes of a tile: data + valid bitmap per column, plus
    the liveness mask (bools are 1 byte under XLA's dense layout)."""
    total = b.capacity  # mask
    for c in b.cols:
        total += c.data.size * c.data.dtype.itemsize
        total += c.valid.size * c.valid.dtype.itemsize
    return int(total)


# one lock for the whole tree: reservations are per-spool-tile (hundreds
# per query, not per row), so contention is negligible and charge/unwind
# up the ancestor chain stays atomic
_TREE_LOCK = threading.RLock()


class BytesMonitor:
    """One node of the monitor tree. ``budget`` of 0 means unlimited at
    this level (ancestors may still refuse). Reservations charge every
    ancestor up to ROOT; high_water is the peak of ``used``."""

    def __init__(self, name: str, parent: "BytesMonitor | None" = None,
                 budget: int = 0, level: str = "operator"):
        self.name = name
        self.parent = parent
        self.budget = int(budget)
        self.level = level
        self.used = 0
        self.high_water = 0
        self.spills = 0
        self.closed = False
        self._children: list[weakref.ref] = []
        if parent is not None:
            with _TREE_LOCK:
                parent._children.append(weakref.ref(self))

    def child(self, name: str, budget: int = 0,
              level: str = "operator") -> "BytesMonitor":
        return BytesMonitor(name, parent=self, budget=budget, level=level)

    def children(self) -> "list[BytesMonitor]":
        """Live (unclosed) child monitors; dead weakrefs are compacted."""
        with _TREE_LOCK:
            out, alive = [], []
            for r in self._children:
                m = r()
                if m is not None and not m.closed:
                    out.append(m)
                    alive.append(r)
            self._children = alive
            return out

    def would_exceed(self, nbytes: int) -> bool:
        n = int(nbytes)
        with _TREE_LOCK:
            m = self
            while m is not None:
                if m.budget and m.used + n > m.budget:
                    return True
                m = m.parent
        return False

    def reserve(self, nbytes: int, force: bool = False) -> None:
        """Charge ``nbytes`` up the ancestor chain. ``force`` skips the
        budget check — for buffered state that CANNOT spill (host-side
        string_agg) where over-budget accounting beats no accounting."""
        n = int(nbytes)
        if n <= 0:
            return
        with _TREE_LOCK:
            # check the whole chain BEFORE charging so a refusal anywhere
            # leaves every ancestor untouched
            if not force:
                m = self
                while m is not None:
                    if m.budget and m.used + n > m.budget:
                        raise BudgetExceededError(
                            m.name, m.used + n, m.budget)
                    m = m.parent
            m = self
            while m is not None:
                m.used += n
                if m.used > m.high_water:
                    m.high_water = m.used
                m = m.parent
            _update_gauges()

    def reserve_batch(self, b: Batch) -> int:
        n = batch_bytes(b)
        self.reserve(n)
        return n

    def release(self, nbytes: int | None = None) -> None:
        with _TREE_LOCK:
            n = self.used if nbytes is None else min(int(nbytes), self.used)
            if n <= 0:
                return
            m = self
            while m is not None:
                m.used = max(0, m.used - n)
                m = m.parent
            _update_gauges()

    def note_spill(self) -> None:
        with _TREE_LOCK:
            m = self
            while m is not None:
                m.spills += 1
                m = m.parent

    def close(self) -> int:
        """Release everything into the parent chain and detach. Returns the
        bytes that were still reserved (0 = the account drained cleanly)."""
        with _TREE_LOCK:
            if self.closed:
                return 0
            leaked = self.used
            self.release()
            self.closed = True
            return leaked


# the node-level root monitor (the mon.BytesMonitor the server owns)
ROOT = BytesMonitor("root", level="root")


# -- long-lived staging accounts ---------------------------------------------
#
# Node-level "cache"-level children of ROOT for allocations that outlive a
# query scope (spill staging, storage run/bloom residency, ingest blocks).
# The per-query drain census ignores cache-level monitors, so these charge
# the node budget without tripping leak detection — the block cache
# (storage/blockcache.py) established the pattern.

_STAGING: dict[str, BytesMonitor] = {}


def staging_monitor(name: str, budget: int = 0) -> BytesMonitor:
    """Get-or-create the named cache-level account. ``budget`` (when
    non-zero) installs/updates a cap on the account — the changefeed
    fan-out plane bounds its whole buffer pool this way while its
    per-subscriber children carry their own budgets."""
    with _TREE_LOCK:
        m = _STAGING.get(name)
        if m is None or m.closed:
            m = _STAGING[name] = ROOT.child(name, level="cache")
        if budget:
            m.budget = int(budget)
        return m


@contextlib.contextmanager
def staged(name: str, nbytes: int):
    """Scoped charge for a transient staging buffer (host padding blocks,
    quantile key vectors): reserved for the materialization's lifetime,
    released on exit. ``force=True`` — the buffer must exist either way;
    over-budget accounting beats no accounting (the operators.py spool
    discipline)."""
    mon = staging_monitor(name)
    n = int(nbytes)
    mon.reserve(n, force=True)
    try:
        yield mon
    finally:
        mon.release(n)


def charge_object(name: str, obj, nbytes: int) -> None:
    """Charge residency for ``obj``'s lifetime — released when the object
    is garbage-collected (weakref.finalize), for structures whose drop
    point is diffuse (per-run bloom filters discarded by compaction)."""
    mon = staging_monitor(name)
    n = int(nbytes)
    if n <= 0:
        return
    mon.reserve(n, force=True)
    weakref.finalize(obj, mon.release, n)


def _update_gauges() -> None:
    # called under _TREE_LOCK on every root-visible delta
    metric.SQL_MEM_CURRENT.set(ROOT.used)
    metric.SQL_MEM_MAX.set(ROOT.high_water)


def refresh_gauges() -> None:
    """Re-publish the root monitor gauges (the background metrics scraper
    calls this so a quiet node still exports truthful values)."""
    with _TREE_LOCK:
        _update_gauges()


def root_budget() -> int:
    from ..utils import settings

    return int(settings.get("sql.mem.root_budget_bytes"))


def mem_pressure() -> float:
    """ROOT used / configured root budget (0.0 when the budget is
    unlimited) — the signal admission's IOGovernor folds into write
    pacing."""
    b = root_budget()
    return (ROOT.used / b) if b > 0 else 0.0


def session_monitor(name: str) -> BytesMonitor:
    return BytesMonitor(name, parent=ROOT, level="session")


# -- query scope (contextvar-carried, like utils/tracing's current span) ----

_CURRENT_QUERY: contextvars.ContextVar[BytesMonitor | None] = (
    contextvars.ContextVar("ctpu_query_monitor", default=None))
_QUERY_SEQ = itertools.count(1)

# drain-failure census (scripts/check_no_leaks.py): monotonic count plus a
# bounded ring of (monitor name, leaked bytes) for the assertion message
_DRAIN_FAILURES: list[tuple[str, int]] = []
_DRAIN_TOTAL = 0


def current_query() -> BytesMonitor | None:
    return _CURRENT_QUERY.get()


@contextlib.contextmanager
def query_scope(parent: BytesMonitor | None = None, name: str | None = None):
    """Enter (or join) the current statement's query monitor. Nested scopes
    (a diagnostics re-run inside a session statement) share the outer
    monitor; the outermost exit closes it, records the peak into
    ``sql_mem_query_peak_bytes`` and flags any retained reservation as a
    drain failure."""
    existing = _CURRENT_QUERY.get()
    if existing is not None:
        yield existing
        return
    qm = BytesMonitor(name or f"query-{next(_QUERY_SEQ)}",
                      parent=parent or ROOT, level="query")
    tok = _CURRENT_QUERY.set(qm)
    try:
        yield qm
    finally:
        _CURRENT_QUERY.reset(tok)
        _close_query(qm)


def _close_query(qm: BytesMonitor) -> None:
    global _DRAIN_TOTAL
    with _TREE_LOCK:
        # an operator account still open at query end is the operator's
        # bug, but its bytes must not poison the session/root gauges —
        # close (force-release) children first, then judge the monitor
        leaked = 0
        for c in qm.children():
            leaked += c.close()
        leaked += qm.used
        qm.close()
        if leaked:
            _DRAIN_TOTAL += 1
            _DRAIN_FAILURES.append((qm.name, leaked))
            del _DRAIN_FAILURES[:-100]
            metric.SQL_MEM_QUERY_LEAKS.inc()
    metric.SQL_MEM_QUERY_PEAK.observe(float(qm.high_water))


def drain_failure_count() -> int:
    """Monotonic count of query monitors that closed with bytes still
    reserved (each is a leak — scripts/check_no_leaks.py asserts this
    stays flat across every test)."""
    return _DRAIN_TOTAL


def drain_failures(last: int = 10) -> list[tuple[str, int]]:
    return list(_DRAIN_FAILURES[-last:])


def note_spill(kind: str) -> None:
    """Attribute one spill-to-external-variant event to the owning query
    (and its ancestors), plus the per-kind node counters."""
    qm = _CURRENT_QUERY.get()
    if qm is not None:
        qm.note_spill()
    else:
        ROOT.note_spill()
    if kind == "sort":
        metric.EXTERNAL_SORT_SPILLS.inc()
    elif kind == "join":
        metric.GRACE_JOIN_SPILLS.inc()
    # agg spills count through sql_external_agg_spills at the Grace
    # staging site (flow/external.py) — not double-counted here


def monitor_rows() -> list[dict]:
    """Depth-first snapshot of the live monitor tree (the
    crdb_internal.node_memory_monitors / /_status/load row shape)."""
    rows: list[dict] = []

    def walk(m: BytesMonitor, depth: int) -> None:
        rows.append({
            "name": m.name, "level": m.level, "depth": depth,
            "used": m.used, "peak": m.high_water,
            "budget": m.budget, "spills": m.spills,
        })
        for c in m.children():
            walk(c, depth + 1)

    with _TREE_LOCK:
        walk(ROOT, 0)
    return rows


def device_memory_stats() -> dict:
    """Physical-side cross-check of the logical accounting: per-device
    allocator stats summed over the backend's devices plus the live jax
    buffer total. Empty dict when the backend reports nothing (CPU)."""
    try:
        import jax

        devs = jax.devices()
    except Exception:  # crlint: allow-broad-except(no backend = no physical stats; logical accounting stands alone)
        return {}
    in_use = peak = 0
    reported = False
    for d in devs:
        try:
            ms = d.memory_stats()
        except Exception:  # crlint: allow-broad-except(backends without allocator stats raise; skip them)
            ms = None
        if ms:
            reported = True
            in_use += int(ms.get("bytes_in_use", 0))
            peak += int(ms.get("peak_bytes_in_use",
                               ms.get("bytes_in_use", 0)))
    out: dict = {}
    if reported:
        out["bytes_in_use"] = in_use
        out["peak_bytes_in_use"] = peak
        out["devices"] = len(devs)
    try:
        out["live_buffer_bytes"] = int(
            sum(a.nbytes for a in jax.live_arrays()))
    except (AttributeError, RuntimeError):
        pass  # backend without live-array introspection; field omitted
    return out


class Allocator:
    """Byte account for one operator (the colmem.Allocator / BoundAccount
    role): a leaf monitor under the CURRENT query monitor (contextvar),
    budgeted by ``sql.distsql.workmem_bytes``. The owner must ``close()``
    it when its buffered state dies — a query monitor that reaches close
    with open accounts flags a drain failure."""

    def __init__(self, op: str, budget: int | None = None, stats=None):
        from ..utils import settings

        self.op = op
        if budget is None:
            budget = settings.get("sql.distsql.workmem_bytes")
        parent = _CURRENT_QUERY.get() or ROOT
        self._mon = BytesMonitor(f"operator/{op}", parent=parent,
                                 budget=int(budget), level="operator")
        self._stats = stats

    @property
    def budget(self) -> int:
        return self._mon.budget

    @property
    def used(self) -> int:
        return self._mon.used

    @property
    def high_water(self) -> int:
        return self._mon.high_water

    def would_exceed(self, nbytes: int) -> bool:
        return self._mon.would_exceed(nbytes)

    def reserve(self, nbytes: int, force: bool = False) -> None:
        self._mon.reserve(nbytes, force=force)
        if self._stats is not None:
            self._stats.max_mem_bytes = max(
                self._stats.max_mem_bytes, self._mon.high_water)

    def reserve_batch(self, b: Batch) -> int:
        n = batch_bytes(b)
        self.reserve(n)
        return n

    def release(self, nbytes: int | None = None) -> None:
        self._mon.release(nbytes)

    def close(self) -> None:
        self._mon.close()
