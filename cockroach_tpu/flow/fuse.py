"""Whole-pipeline fusion pass over the built operator tree.

The operator set already fuses *consumer-driven* chains: a buffering
consumer (aggregate spool, sort spool, join build spool) composes its tile
function with its child chain's raw functions into one jit (``_consume`` /
``_consume_op`` in flow/operators.py over the ``stream_parts`` contract).
What that cannot cover is a maximal chain whose PARENT pulls per-operator —
the tree root, a limit, a fan-in input, a merge-join probe: there every
per-tile operator still dispatches its own kernel and materializes a full
padded intermediate tile, which is exactly the kernel-launch/intermediate-
materialization tax of fine-grained operator offload.

This pass closes the gap at plan-build time (invoked from plan/builder.py
behind ``sql.distsql.fusion.enabled``):

- ``FusedPipeline`` wraps the top of any maximal chain of stateless
  per-tile operators (filter / project / hash-bucket / fusable hash-join
  probes) whose parent does not fuse. Its pull loop composes the chain's
  raw tile functions into ONE jitted function, so XLA fuses the whole
  chain into one kernel and the intermediate padded tiles never exist.
- ``_BarrierSource`` adapts a pipeline barrier (general join, fan-in,
  remote inbox, index scan) into a chain *source*, so the per-tile
  operators above it still collapse even when the chain does not bottom
  out at a ScanOp. Consumer-driven fusion benefits too: an aggregate
  spool above filter-over-general-join now composes its chain.

Runtime contracts preserved: ``children()`` keeps every member reachable
(so ``_post_run_updates`` still validates each member's deferred
speculative-capacity counters, and collect_stats/close cascade); stats
collection (EXPLAIN ANALYZE) falls back to per-operator pulls exactly
like ``_consume`` does; speculative-emission joins keep driving their own
counted kernels (``stream_parts`` passthrough).
"""

from __future__ import annotations

from ..utils import metric
from .operator import Operator
from .operators import (
    AggregateOp,
    DistinctOp,
    FilterOp,
    HashBucketOp,
    HashJoinOp,
    LimitOp,
    MergeJoinOp,
    OrderedSyncOp,
    ParallelUnorderedSyncOp,
    ProjectOp,
    ScalarAggregateOp,
    ScanOp,
    SmallGroupAggregateOp,
    SortOp,
    TopKOp,
    UnionOp,
    WindowOp,
    _identity_fn,
)
from . import dispatch

# stateless per-tile chain links the pass collapses
_CHAIN = (FilterOp, ProjectOp, HashBucketOp)
# buffering consumers that already fuse their own spool chain (_consume);
# their children are never wrapped — the consumer drives the composition
_CONSUMERS = (AggregateOp, ScalarAggregateOp, SortOp, TopKOp, WindowOp,
              SmallGroupAggregateOp)


def _is_chain_link(op) -> bool:
    if isinstance(op, _CHAIN):
        return True
    # general (duplicate-key inner/left) joins are chain members too: they
    # run source-mode, driving the chain below through their speculative
    # emit kernel, and the chain above composes on their compacted tiles
    return isinstance(op, HashJoinOp) and (op._fusable or op._gen_fusable)


class _BarrierSource(Operator):
    """Adapts a pipeline barrier into a fused-chain source: stream_tiles
    pulls the barrier per batch, so the per-tile chain ABOVE it still
    composes into one kernel. Pure delegation otherwise."""

    # a segment boundary: joins below it never share the jit composed above
    # it, so chain walks (HashJoinOp.fused_depth) stop counting here
    _chain_split = True

    def __init__(self, inner: Operator):
        super().__init__()
        self.inner = inner
        self.child = inner  # chain walks see through it for metadata
        self.output_schema = inner.output_schema
        self.dictionaries = inner.dictionaries
        self.col_stats = inner.col_stats

    def children(self):
        return [self.inner]

    def init(self):
        self.inner.init()
        self._initialized = True

    def stream_parts(self):
        if not self._initialized:
            self.init()
        return self, _identity_fn, ()

    def stream_tiles(self):
        while True:
            b = self.inner.next_batch()
            if b is None:
                return
            yield b

    def _next(self):
        return self.inner.next_batch()

    def close(self):
        self.inner.close()


class FusedPipeline(Operator):
    """Consumer-of-last-resort for a streaming chain: drives the chain
    below ``top`` through one jit per tile via the stream_parts contract
    (the role _consume plays for buffering consumers, for parents that
    pull per-operator)."""

    def __init__(self, top: Operator, members: list[Operator]):
        super().__init__()
        self.top = top
        self.child = top  # chain walks (fused_depth) see through the wrapper
        self.members = members
        self.output_schema = top.output_schema
        # shared refs, not copies: runtime-filled dictionaries (string_agg)
        # must stay visible through the wrapper
        self.dictionaries = top.dictionaries
        self.col_stats = top.col_stats
        self._gen = None

    def children(self):
        return [self.top]

    def init(self):
        self.top.init()
        self._gen = None
        self._initialized = True

    def stream_parts(self):
        # a parent that CAN fuse composes straight through the wrapper
        return self.top.stream_parts()

    def _tiles(self):
        # stats collection forces the per-operator path so every member's
        # batch/row counts stay observable (same rule as _consume)
        parts = None if self._collect else self.top.stream_parts()
        if parts is None:
            # barrier below (grace spill, stats, deep-join valve): classic
            # per-operator pulls
            while True:
                b = self.top.next_batch()
                if b is None:
                    return
                yield b
            return
        src, cfn, args = parts
        if cfn is _identity_fn:
            # the top drives itself (source-mode join emission, streaming
            # scan): its stream_tiles yields finished batches — composing
            # jit(identity) would add a dispatch per tile for nothing
            yield from src.stream_tiles()
            return
        cached = getattr(self, "_pipe_fn", None)
        if cached is None or cached[0] is not cfn:
            # chains with structural keys (set by _compose_parts during the
            # stream_parts call above) share one jitted pipeline globally:
            # a repeat query's fused chain reuses the first's executables
            pkey = getattr(self.top, "_parts_key", None)
            cached = (cfn, dispatch.jit(
                cfn, key=None if pkey is None else ("pipe", pkey)))
            self._pipe_fn = cached
        fn = cached[1]
        for t in src.stream_tiles():
            yield fn(t, *args)

    def _next(self):
        if self._gen is None:
            self._gen = self._tiles()
        return next(self._gen, None)

    def close(self):
        self.top.close()


def _wrap(op: Operator) -> FusedPipeline:
    members: list[Operator] = []
    cur = op
    while _is_chain_link(cur):
        members.append(cur)
        cur = cur.child
    members.append(cur)  # the source (scan / barrier adapter) included
    metric.FUSED_PIPELINE_LENGTHS.observe(len(members))
    return FusedPipeline(op, members)


def _chain_child(child: Operator, jrun: int = 0) -> Operator:
    """Rewrite an input that a fusing parent composes through: recurse
    (never wrap — the parent drives the chain), then adapt a barrier
    child into a chain source so composition does not stop there.

    ``jrun`` counts join probes already committed to the jit being composed
    above this point. When admitting one more fusable join would push the
    program past sql.distsql.max_fused_joins, the chain splits HERE — the
    deeper part becomes its own FusedPipeline segment behind a barrier
    source — instead of the runtime valve de-fusing the whole pipeline."""
    from ..utils import settings

    if (isinstance(child, HashJoinOp) and child._fusable
            and jrun >= settings.get("sql.distsql.max_fused_joins")):
        return _BarrierSource(_rewrite(child, parent_fuses=False))
    child = _rewrite(child, parent_fuses=True, jrun=jrun)
    if _is_chain_link(child) or isinstance(child, ScanOp):
        return child
    return _BarrierSource(child)


def _rewrite(op: Operator, parent_fuses: bool, jrun: int = 0) -> Operator:
    if isinstance(op, _CHAIN):
        op.child = _chain_child(op.child, jrun)
        return op if parent_fuses else _wrap(op)
    if isinstance(op, HashJoinOp):
        if op._fusable:
            # this probe joins the composed jit: one more toward the budget
            op.child = _chain_child(op.child, jrun + 1)
        elif op._gen_fusable:
            # source-mode: the chain below composes into THIS join's emit
            # kernel (own jit, own budget), not the parent's
            op.child = _chain_child(op.child, 1)
        else:
            op.child = _rewrite(op.child, parent_fuses=False)
        # build sides already spool through one fused jit (_consume_op)
        # and _plan_analytic walks their concrete types — never wrap them
        op.build = _rewrite(op.build, parent_fuses=True)
        fusy = op._fusable or op._gen_fusable
        return op if (not fusy or parent_fuses) else _wrap(op)
    if isinstance(op, MergeJoinOp):
        op.child = _rewrite(op.child, parent_fuses=False)
        op.build = _rewrite(op.build, parent_fuses=True)
        return op
    if isinstance(op, DistinctOp):
        # DistinctOp and its inner AggregateOp share ONE child object;
        # rewire both to the same rewritten instance
        child = _rewrite(op._inner.child, parent_fuses=True)
        op._inner.child = child
        op.child = child
        return op
    if isinstance(op, _CONSUMERS):
        # no barrier adapter here: a consumer's DIRECT barrier child has no
        # chain to compose with, and spools whose tile fn is the identity
        # (sort/window) would pay a jit(identity) dispatch per tile for it
        op.child = _rewrite(op.child, parent_fuses=True)
        return op
    if isinstance(op, LimitOp):
        op.child = _rewrite(op.child, parent_fuses=False)
        return op
    if isinstance(op, (UnionOp, OrderedSyncOp, ParallelUnorderedSyncOp)):
        op._children = [
            _rewrite(c, parent_fuses=False) for c in op._children
        ]
        return op
    # sources and external/remote operators: nothing below to fuse here
    return op


def fuse_operators(root: Operator) -> Operator:
    """Apply the fusion pass to a built operator tree; returns the (possibly
    wrapped) root. Mutates child links in place — run before init()."""
    return _rewrite(root, parent_fuses=False)


# ---------------------------------------------------------------------------
# EXPLAIN support: mirror the grouping over the PLAN tree


def plan_fusion_groups(plan) -> dict[int, int]:
    """Map id(plan node) -> pipeline group number, mirroring the pass (and
    the consumer-driven spool fusion) over the plan tree so EXPLAIN can
    show which operators collapse. Advisory: runtime-only fallbacks (grace
    spills, the max_fused_joins valve, stats collection) are not modeled.
    Groups of one are omitted."""
    from ..plan import spec as S

    links = (S.Filter, S.Project, S.HashBucket)
    heads = (S.Aggregate, S.ScalarAggregate, S.Sort, S.TopK, S.Window,
             S.Distinct)
    groups: dict[int, int] = {}
    next_group = [1]

    def fusable_join(n) -> bool:
        from ..utils import settings

        if not isinstance(n, S.HashJoin):
            return False
        if n.spec.build_unique or n.spec.join_type in ("semi", "anti"):
            return True
        return (n.spec.join_type in ("inner", "left")
                and settings.get("sql.distsql.fusion.general_probe"))

    def assign(members) -> None:
        if len(members) < 2:
            return
        g = next_group[0]
        next_group[0] += 1
        for m in members:
            groups[id(m)] = g

    def descend(n):
        """Collect the chain below a group head; returns (members, barrier
        node still to walk — None when the chain ends at a table scan)."""
        members = []
        while True:
            if isinstance(n, S.Exchange):
                n = n.input  # single-device builds elide the exchange
            elif isinstance(n, links):
                members.append(n)
                n = n.input
            elif fusable_join(n):
                members.append(n)
                walk(n.build)  # the build spool fuses its own chain
                n = n.probe
            elif isinstance(n, S.TableScan):
                members.append(n)
                return members, None
            else:
                return members, n

    def walk(n) -> None:
        if isinstance(n, S.Exchange):
            walk(n.input)
            return
        if isinstance(n, heads):
            members, barrier = descend(n.input)
            assign([n] + members)
            if barrier is not None:
                walk(barrier)
            return
        if isinstance(n, links) or fusable_join(n):
            members, barrier = descend(n)
            assign(members)
            if barrier is not None:
                walk(barrier)
            return
        if isinstance(n, (S.HashJoin, S.MergeJoin)):
            walk(n.probe)
            walk(n.build)
            return
        if isinstance(n, (S.Union, S.StreamUnion)):
            for c in n.inputs:
                walk(c)
            return
        if hasattr(n, "input"):
            walk(n.input)

    walk(plan)
    return groups


def unwrap(op):
    """Strip pass-inserted wrappers so plan-tree walks (EXPLAIN ANALYZE)
    keep their one-to-one plan-node/operator correspondence."""
    while isinstance(op, (FusedPipeline, _BarrierSource)):
        op = op.top if isinstance(op, FusedPipeline) else op.inner
    return op
