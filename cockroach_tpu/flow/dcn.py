"""Cross-host flow streams — the colrpc Outbox/Inbox over DCN.

Reference: a remote DistSQL flow streams Arrow-encoded batches over gRPC
FlowStream (pkg/sql/colflow/colrpc/outbox.go:44 serializes via colserde at
:280; inbox.go:48 is an Operator whose Next() reads the stream; the service
is execinfrapb/api.proto:143-166 SetupFlow/FlowStream). The TPU mapping
(SURVEY §2.3): in-slice shuffles ride ICI collectives (parallel/shuffle.py);
ACROSS slices/hosts batches travel as Arrow IPC over the data-center
network. This module is that DCN lane:

- ``FlowOutbox``: drives a local operator and streams its batches as Arrow
  IPC messages over a socket (length-prefixed), then an end-of-stream
  marker.
- ``FlowInbox``: a SourceOperator whose next_batch() reads one Arrow
  message from the socket and uploads it as a device Batch — downstream
  operators cannot tell it from a local scan.
- ``FlowServer``: listens for SetupFlow-style requests naming a registered
  flow (a callable returning an Operator) and answers with the stream —
  the ServerImpl.SetupFlow reduction (one request per connection; the
  FlowRegistry/StreamID matching arrives with the full control plane).

Framing: 4-byte little-endian length + Arrow IPC stream bytes per batch;
length 0 terminates. Arrow IPC is self-describing, so schema and
dictionaries travel with the data (colserde's RecordBatchSerializer role).
"""

from __future__ import annotations

import io
import json
import socket
import struct
import threading

import pyarrow as pa

from ..coldata import arrow as arrow_mod
from ..coldata.batch import Batch, Dictionary
from ..coldata.types import Schema
from ..utils import settings, tracing
from .operator import Operator, SourceOperator

_LEN = struct.Struct("<I")


def _send_msg(sock: socket.socket, payload: bytes) -> None:
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> bytes:  # crlint: allow-untimed-wait(deadline is owner-set: every socket reaching here is already armed — dials pass timeout= to create_connection, which persists as the stream timeout, and FlowServer settimeouts accepted conns before the handshake read)
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("flow stream closed mid-message")
        buf.extend(chunk)
    return bytes(buf)


def _recv_msg(sock: socket.socket) -> bytes | None:
    n = _LEN.unpack(_recv_exact(sock, _LEN.size))[0]
    if n == 0:
        return None
    return _recv_exact(sock, n)


def _encode_batch(b: Batch, schema: Schema, dictionaries) -> bytes:
    rb = arrow_mod.batch_to_arrow(b, schema, dictionaries)
    sink = io.BytesIO()
    with pa.ipc.new_stream(sink, rb.schema) as w:
        w.write_batch(rb)
    return sink.getvalue()


def _decode_batch(payload: bytes):
    with pa.ipc.open_stream(io.BytesIO(payload)) as r:
        rb = r.read_next_batch()
    return arrow_mod.batch_from_arrow(rb)


class FlowOutbox:
    """Stream every batch of `op` over the socket (outbox.go:44 role)."""

    def __init__(self, op: Operator, sock: socket.socket):
        self.op = op
        self.sock = sock

    def run(self) -> int:
        sent = 0
        self.op.init()
        while True:
            b = self.op.next_batch()
            if b is None:
                break
            payload = _encode_batch(
                b, self.op.output_schema, self.op.dictionaries
            )
            _send_msg(self.sock, payload)
            sent += 1
        self.sock.sendall(_LEN.pack(0))  # end of stream
        self.op.close()
        return sent


class FlowInbox(SourceOperator):
    """An Operator fed by a remote flow stream (inbox.go:48 role). The
    schema arrives with the first Arrow message; callers that need it
    before pulling can pass the expected schema up front."""

    def __init__(self, sock: socket.socket, schema: Schema,
                 dictionaries: dict[int, Dictionary] | None = None,
                 expect_trace: bool = False):
        super().__init__()
        self.sock = sock
        self.output_schema = schema
        self.dictionaries = dict(dictionaries or {})
        self._done = False
        # when the handshake carried a trace context the server appends
        # its span recording as one JSON message AFTER the end-of-stream
        # marker; graft it under the span that set the flow up (captured
        # here — the inbox may be pulled from a puller thread whose
        # context is empty)
        self._expect_trace = expect_trace
        self._trace_parent = tracing.current() if expect_trace else None

    def _next(self):
        if self._done:
            return None
        payload = _recv_msg(self.sock)
        if payload is None:
            self._done = True
            if self._expect_trace:
                try:
                    trailer = _recv_msg(self.sock)
                    if trailer:
                        tracing.graft(
                            json.loads(trailer.decode("utf-8")),
                            into=self._trace_parent)
                except (OSError, ConnectionError, ValueError):
                    # trailer is best-effort: the data stream is already
                    # complete, a lost recording must not fail the query
                    trailer = None
            # a drained stream's socket is dead weight: close it HERE so
            # fd censuses don't depend on when the inbox gets collected
            try:
                self.sock.close()
            except OSError:
                pass
            return None
        b, schema, dicts = _decode_batch(payload)
        # remote dictionaries override (codes are stream-relative)
        self.dictionaries.update(dicts)
        return b


class FlowServer:
    """Answers SetupFlow requests: the client sends a flow name (one line),
    the server streams that flow's batches back. One request per
    connection — the reduced ServerImpl.SetupFlow/FlowStream pairing."""

    def __init__(self, flows: dict[str, object], host: str = "127.0.0.1",
                 port: int = 0):
        self.flows = flows
        self._srv = socket.create_server((host, port))
        self.addr = self._srv.getsockname()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    def serve_background(self) -> "FlowServer":
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self

    def _serve(self) -> None:
        self._srv.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            # one serve thread handles connections serially: a client
            # that dials and then goes silent before the handshake (or
            # stops draining mid-stream) must not wedge every other
            # flow behind it — bound all I/O on this connection
            conn.settimeout(settings.get("flow.dcn.io_timeout_s"))
            try:
                # a bad client (empty handshake, unknown flow, mid-stream
                # reset) must not kill the accept loop — per-connection
                # errors are that connection's problem (the RangefeedServer
                # handshake discipline)
                msg = _recv_msg(conn)
                if msg is None:
                    continue
                name = msg.decode("utf-8", errors="replace")
                tctx = None
                if name.startswith("{"):
                    # JSON handshake (trace-carrying clients); a plain
                    # flow name still works for legacy peers
                    try:
                        hello = json.loads(name)
                        name = str(hello.get("flow", ""))
                        tctx = hello.get("trace")
                    except ValueError:
                        tctx = None
                make_op = self.flows.get(name)
                if make_op is None:
                    continue
                with tracing.remote_span("flow/outbox", tctx,
                                         flow=name) as osp:
                    sent = FlowOutbox(make_op(), conn).run()
                    if osp is not None:
                        osp.add_tag("batches", sent)
                if osp is not None:
                    # ship the recording as one extra message after the
                    # end-of-stream marker; the inbox grafts it
                    _send_msg(conn,
                              json.dumps(osp.to_dict()).encode("utf-8"))
            except Exception as e:  # crlint: allow-broad-except(accept loop survives any one connection/operator failure; logged below)
                # operator/stream errors too: one connection's failure
                # (including a flow whose operator raises mid-stream) must
                # never take down the accept loop
                from ..utils import log

                log.warning(log.OPS, "flow connection failed",
                            error=f"{type(e).__name__}: {e}")
            finally:
                conn.close()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._srv.close()


def setup_remote_flow(addr, name: str, schema: Schema) -> FlowInbox:
    """Dial a FlowServer and return the Inbox for the named flow — the
    DistSQLPlanner.setupFlows remote half (distsql_running.go:391)."""
    # the timeout bounds the TCP connect AND persists as the socket
    # timeout, so every subsequent FlowInbox stream read inherits the
    # same deadline — a wedged remote surfaces as socket.timeout
    # instead of hanging the puller thread forever
    sock = socket.create_connection(
        tuple(addr), timeout=settings.get("flow.dcn.io_timeout_s"))
    tctx = tracing.context()
    if tctx is None:
        _send_msg(sock, name.encode("utf-8"))
        return FlowInbox(sock, schema)
    # trace-carrying handshake: the server opens a remote span under our
    # (trace_id, span_id) and ships its recording after the stream
    _send_msg(sock, json.dumps(
        {"flow": name, "trace": tctx}).encode("utf-8"))
    return FlowInbox(sock, schema, expect_trace=True)
