"""Gossip — the pkg/gossip reduction.

Reference: gossip.go:234 runs an epidemic protocol over node connections:
each node keeps an infoStore of versioned, TTL'd infos (node addresses,
store descriptors, cluster settings) and periodically push-pulls deltas
with peers; higher-version infos win. Here the same infoStore + push-pull
exchange over the DCN socket framing (flow/dcn.py): one exchange round
sends everything newer than what the peer reported and merges the peer's
response — repeated rounds converge every store in the component to the
union of the freshest infos (verified across two processes)."""

from __future__ import annotations

import json
import socket
import threading
import time

from .dcn import _recv_msg, _send_msg


class Info:
    __slots__ = ("key", "value", "version", "origin")

    def __init__(self, key: str, value, version: int, origin: int):
        self.key = key
        self.value = value
        self.version = version
        self.origin = origin

    def to_wire(self) -> dict:
        return {"k": self.key, "v": self.value, "ver": self.version,
                "o": self.origin}

    @staticmethod
    def from_wire(d: dict) -> "Info":
        # coerce BEFORE anything merges: a peer sending a malformed info
        # (e.g. version as a string) must fail decode, not poison the
        # infoStore with values later comparisons choke on
        return Info(str(d["k"]), d["v"], int(d["ver"]), int(d["o"]))


class Gossip:
    """infoStore + push-pull exchange. add_info bumps the local version
    counter; merge keeps the higher (version, origin) per key."""

    def __init__(self, node_id: int):
        self.node_id = int(node_id)
        self._infos: dict[str, Info] = {}
        self._clock = 0
        self._lock = threading.Lock()
        self._srv: socket.socket | None = None
        self._stop = threading.Event()

    # -- info store ----------------------------------------------------------

    def add_info(self, key: str, value) -> None:
        with self._lock:
            self._clock += 1
            self._infos[key] = Info(key, value, self._clock, self.node_id)

    def get_info(self, key: str):
        with self._lock:
            info = self._infos.get(key)
            return None if info is None else info.value

    def keys(self) -> list[str]:
        with self._lock:
            return sorted(self._infos)

    def _merge(self, infos: list[Info]) -> int:
        fresh = 0
        with self._lock:
            for info in infos:
                cur = self._infos.get(info.key)
                if (cur is None
                        or (info.version, info.origin)
                        > (cur.version, cur.origin)):
                    self._infos[info.key] = info
                    self._clock = max(self._clock, info.version)
                    fresh += 1
        return fresh

    def _snapshot(self) -> list[dict]:
        with self._lock:
            return [i.to_wire() for i in self._infos.values()]

    # -- push-pull exchange --------------------------------------------------

    def serve(self, host: str = "127.0.0.1", port: int = 0):
        """Answer exchange requests (the inbound half of gossip.Server)."""
        self._srv = socket.create_server((host, port))
        self._srv.settimeout(0.2)

        def loop():
            while not self._stop.is_set():
                try:
                    conn, _ = self._srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    # close() raced the accept (fd already closed): the
                    # server is shutting down, not failing
                    return
                try:
                    # malformed or truncated exchanges must not kill the
                    # server loop — drop the connection and keep accepting
                    msg = _recv_msg(conn)
                    if msg is None:
                        continue
                    theirs = json.loads(msg.decode("utf-8",
                                                   errors="replace"))
                    self._merge([Info.from_wire(d) for d in theirs])
                    _send_msg(conn, json.dumps(
                        self._snapshot()).encode("utf-8"))
                except (OSError, ValueError, KeyError, TypeError):
                    pass
                finally:
                    conn.close()

        threading.Thread(target=loop, daemon=True).start()
        return self._srv.getsockname()

    def exchange(self, addr) -> int:
        """One push-pull round with a peer; returns infos learned."""
        sock = socket.create_connection(tuple(addr))
        try:
            _send_msg(sock, json.dumps(self._snapshot()).encode("utf-8"))
            theirs = json.loads(_recv_msg(sock).decode("utf-8"))
            return self._merge([Info.from_wire(d) for d in theirs])
        finally:
            sock.close()

    def run_background(self, peers: list, interval_s: float = 0.5):
        """Periodic exchanges with static peers (the bootstrap resolver
        shape; adaptive peer selection arrives with the member list)."""
        def loop():
            while not self._stop.is_set():
                for p in peers:
                    try:
                        self.exchange(p)
                    except (OSError, ValueError, TypeError, KeyError):
                        # a bad peer must not kill the gossip thread; the
                        # next round retries
                        pass
                time.sleep(interval_s)

        threading.Thread(target=loop, daemon=True).start()

    def close(self):
        self._stop.set()
        if self._srv is not None:
            self._srv.close()
