"""Gossip — the pkg/gossip reduction.

Reference: gossip.go:234 runs an epidemic protocol over node connections:
each node keeps an infoStore of versioned, TTL'd infos (node addresses,
store descriptors, cluster settings) and periodically push-pulls deltas
with peers; higher-version infos win. Here the same infoStore + push-pull
exchange over the DCN socket framing (flow/dcn.py): one exchange round
sends everything newer than what the peer reported and merges the peer's
response — repeated rounds converge every store in the component to the
union of the freshest infos (verified across two processes)."""

from __future__ import annotations

import json
import socket
import threading
import time

from ..utils import locks, racesan, settings
from .dcn import _recv_msg, _send_msg


class Info:
    __slots__ = ("key", "value", "version", "origin")

    def __init__(self, key: str, value, version: int, origin: int):
        self.key = key
        self.value = value
        self.version = version
        self.origin = origin

    def to_wire(self) -> dict:
        return {"k": self.key, "v": self.value, "ver": self.version,
                "o": self.origin}

    @staticmethod
    def from_wire(d: dict) -> "Info":
        # coerce BEFORE anything merges: a peer sending a malformed info
        # (e.g. version as a string) must fail decode, not poison the
        # infoStore with values later comparisons choke on
        return Info(str(d["k"]), d["v"], int(d["ver"]), int(d["o"]))


class Gossip:
    """infoStore + push-pull exchange. add_info bumps the local version
    counter; merge keeps the higher (version, origin) per key.

    The store is BOUNDED (`max_infos`, gossip.go's infoStore limits
    role): when full, the lowest-version foreign info is evicted — a
    flapping peer republishing junk cannot grow memory without bound.
    `note_epoch` expires every info a fenced origin published: once a
    node's liveness epoch is bumped, state it gossiped under the old
    epoch is stale by definition (its leases are fenced, its address
    may be reused)."""

    def __init__(self, node_id: int, max_infos: int = 4096):
        self.node_id = int(node_id)
        self.max_infos = int(max_infos)
        self._infos: dict[str, Info] = {}
        self._node_epochs: dict[int, int] = {}  # highest KNOWN epoch
        self._clock = 0
        self._lock = locks.lock("gossip")
        self._srv: socket.socket | None = None
        self._stop = threading.Event()

    # -- info store ----------------------------------------------------------

    def add_info(self, key: str, value) -> None:
        with self._lock:
            racesan.note_write(self, "_infos")
            self._clock += 1
            self._infos[key] = Info(key, value, self._clock, self.node_id)
            self._enforce_bound()

    def note_epoch(self, node_id: int, epoch: int) -> None:
        """A node's liveness epoch was observed at `epoch`: drop every
        info that node originated under any earlier observation. The
        node itself keeps gossiping after it re-heartbeats — its NEW
        infos merge normally (higher versions win as usual)."""
        from ..utils import metric

        node_id = int(node_id)
        with self._lock:
            if self._node_epochs.get(node_id, 0) >= epoch:
                return
            self._node_epochs[node_id] = int(epoch)
            stale = [k for k, i in self._infos.items()
                     if i.origin == node_id]
            for k in stale:
                del self._infos[k]
            if stale:
                metric.GOSSIP_INFOS_EVICTED.inc(len(stale))

    def _enforce_bound(self) -> None:
        """Caller holds self._lock. Evict lowest-version FOREIGN infos
        first (our own infos are authoritative here and re-publishable
        only by us); fall back to lowest-version overall if the store is
        somehow all-local."""
        from ..utils import metric

        while len(self._infos) > self.max_infos:
            foreign = [i for i in self._infos.values()
                       if i.origin != self.node_id]
            pool = foreign if foreign else list(self._infos.values())
            victim = min(pool, key=lambda i: (i.version, i.origin))
            del self._infos[victim.key]
            metric.GOSSIP_INFOS_EVICTED.inc()

    def get_info(self, key: str):
        with self._lock:
            racesan.note_read(self, "_infos")
            info = self._infos.get(key)
            return None if info is None else info.value

    def keys(self) -> list[str]:
        with self._lock:
            return sorted(self._infos)

    def _merge(self, infos: list[Info]) -> int:
        fresh = 0
        with self._lock:
            racesan.note_write(self, "_infos")
            for info in infos:
                cur = self._infos.get(info.key)
                if (cur is None
                        or (info.version, info.origin)
                        > (cur.version, cur.origin)):
                    self._infos[info.key] = info
                    self._clock = max(self._clock, info.version)
                    fresh += 1
            self._enforce_bound()
        return fresh

    def _snapshot(self) -> list[dict]:
        with self._lock:
            return [i.to_wire() for i in self._infos.values()]

    # -- push-pull exchange --------------------------------------------------

    def serve(self, host: str = "127.0.0.1", port: int = 0):
        """Answer exchange requests (the inbound half of gossip.Server)."""
        self._srv = socket.create_server((host, port))
        self._srv.settimeout(0.2)

        def loop():
            while not self._stop.is_set():
                try:
                    conn, _ = self._srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    # close() raced the accept (fd already closed): the
                    # server is shutting down, not failing
                    return
                # the single serve thread reads the peer's delta before
                # answering: a peer that dials and stalls mid-exchange
                # must time out, not wedge gossip for the whole cluster
                conn.settimeout(settings.get("flow.dcn.io_timeout_s"))
                try:
                    # malformed or truncated exchanges must not kill the
                    # server loop — drop the connection and keep accepting
                    msg = _recv_msg(conn)
                    if msg is None:
                        continue
                    theirs = json.loads(msg.decode("utf-8",
                                                   errors="replace"))
                    self._merge([Info.from_wire(d) for d in theirs])
                    _send_msg(conn, json.dumps(
                        self._snapshot()).encode("utf-8"))
                except (OSError, ValueError, KeyError, TypeError):
                    pass
                finally:
                    conn.close()

        threading.Thread(target=loop, daemon=True).start()
        return self._srv.getsockname()

    def exchange(self, addr) -> int:
        """One push-pull round with a peer; returns infos learned."""
        from ..utils import faults

        # chaos site: a dropped broadcast round models a partitioned
        # gossip link (node-scoped so tests can isolate one node)
        faults.fire_scoped("gossip.broadcast", self.node_id)
        # bounds the connect AND persists as the per-read deadline: a
        # peer that accepts and then goes silent fails this round with
        # socket.timeout (caught by run_background's retry loop) instead
        # of freezing the node's only gossip thread forever
        sock = socket.create_connection(
            tuple(addr), timeout=settings.get("flow.dcn.io_timeout_s"))
        try:
            _send_msg(sock, json.dumps(self._snapshot()).encode("utf-8"))
            theirs = json.loads(_recv_msg(sock).decode("utf-8"))
            return self._merge([Info.from_wire(d) for d in theirs])
        finally:
            sock.close()

    def run_background(self, peers: list, interval_s: float = 0.5):
        """Periodic exchanges with static peers (the bootstrap resolver
        shape; adaptive peer selection arrives with the member list)."""
        def loop():
            while not self._stop.is_set():
                for p in peers:
                    try:
                        self.exchange(p)
                    except (OSError, ValueError, TypeError, KeyError):
                        # a bad peer must not kill the gossip thread; the
                        # next round retries
                        pass
                time.sleep(interval_s)

        threading.Thread(target=loop, daemon=True).start()

    def close(self):
        self._stop.set()
        if self._srv is not None:
            self._srv.close()
