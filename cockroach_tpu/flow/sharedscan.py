"""Shared tile streams — concurrent scans of one table ride one cursor.

Reference intent: when eight sessions run reporting queries over the
same resident table, each session's ScanOp slices the SAME device
buffer into the SAME tiles — eight identical ``slice_tile`` dispatch
streams where one would do. This module lets concurrent resident scans
attach to a per-(table, columns, tile) shared stream: whichever
subscriber needs a tile first produces it (one dispatch), every other
subscriber consumes the buffered result for free
(``sql_shared_scan_dispatches_saved``).

Design — produce-on-demand, never block. A subscriber asking for tile
``i`` either (a) finds it in the stream's bounded buffer window
(``sql.distsql.sharedscan.window`` tiles) and takes it, (b) finds the
window already trimmed past ``i`` — it fell behind — and slices that
tile solo (catch-up; the stream never waits for laggards and never
holds tiles for them), or (c) produces it into the window for everyone
behind it. No subscriber ever parks on another's progress, so the
stream cannot deadlock and a slow consumer degrades only itself.

Safety is identity, not equality: attach joins an existing stream ONLY
when the subscriber's batch is the same device arrays (column data,
valid bitmaps, and liveness mask all ``is``-identical) as the stream's
— anything else (sharded scans, a table re-devived mid-stream) runs
solo. Tiles are immutable jax arrays, so sharing is free of aliasing
hazards. Bit-identity with the solo path follows: the shared tile IS
the output of the same jitted ``slice_tile`` kernel on the same
operands a solo scan would dispatch.

Chaos site ``flow.sharedscan.attach``: an injected fault at attach
degrades that scan to slicing its own tiles — identical results, the
dispatch saving lost. Buffered tiles are charged to the
``flow.sharedscan`` staging account; each subscriber carries its
attach-time mask bytes until detach.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..utils import faults, locks, metric, racesan, settings
from . import memory as flowmem

__all__ = ["attach", "detach", "reset", "SharedStream"]


def _same_batch(a, b) -> bool:
    """True when two Batch views are the SAME device arrays (catalog
    device-cache hits), so slicing either yields bit-identical tiles."""
    if a is b:
        return True
    if a.capacity != b.capacity or len(a.cols) != len(b.cols):
        return False
    if a.mask is not b.mask:
        return False
    return all(ca.data is cb.data and ca.valid is cb.valid
               for ca, cb in zip(a.cols, b.cols))


class SharedStream:
    """One shared cursor over one resident table's tile sequence."""

    def __init__(self, key, batch, res_tile: int, slice_fn, snap=None):
        self.key = key
        self.batch = batch
        # snapshot token of the decode that produced `batch` (KV-backed
        # tables re-decode per scan; an equal token means a later
        # decode is bit-identical, so the subscriber may adopt ours)
        self.snap = snap
        self.res_tile = int(res_tile)
        self.n_tiles = batch.capacity // self.res_tile
        self.slice_fn = slice_fn
        self.mu = locks.lock("flow.sharedscan")
        # bounded tile window: idx -> (tile, producer). Trimmed from the
        # bottom; an idx below `base` is gone for good (solo catch-up).
        self._tiles: dict[int, tuple] = {}
        self.base = 0
        # attached subscribers (ScanOp identity -> bytes charged at
        # attach). racesan-annotated: attach/detach from different
        # sessions meet here.
        self._subs: dict[int, int] = {}
        self._staging = flowmem.staging_monitor("flow.sharedscan")

    # caller holds _reg_mu for attach/detach bookkeeping --------------------

    def _attach(self, op) -> None:
        # a subscriber's standing cost is its view of the liveness mask
        # (1 byte/row under XLA's dense bool layout)
        n = int(self.batch.capacity)
        self._staging.reserve(n, force=True)
        with self.mu:
            racesan.note_write(self, "_subs")
            self._subs[id(op)] = n

    def _detach(self, op) -> bool:
        """Drop one subscriber; True when the stream is now empty."""
        with self.mu:
            racesan.note_write(self, "_subs")
            n = self._subs.pop(id(op), 0)
        if n:
            self._staging.release(n)
        with self.mu:
            racesan.note_read(self, "_subs")
            return not self._subs

    def _close(self) -> None:
        with self.mu:
            dropped = [t for t, _ in self._tiles.values()]
            self._tiles.clear()
        for t in dropped:
            self._staging.release(flowmem.batch_bytes(t))

    def next_tile(self, op, idx: int):
        """('tile', batch) — shared tile for idx; ('solo', None) — the
        window moved past idx, the caller slices its own catch-up tile."""
        window = settings.get("sql.distsql.sharedscan.window")
        with self.mu:
            if idx < self.base:
                return "solo", None
            ent = self._tiles.get(idx)
            if ent is None:
                t = self.slice_fn(self.batch, jnp.int32(idx * self.res_tile))
                self._tiles[idx] = ent = (t, id(op))
                self._staging.reserve(flowmem.batch_bytes(t), force=True)
                while len(self._tiles) > window:
                    m = min(self._tiles)
                    old, _ = self._tiles.pop(m)
                    self.base = max(self.base, m + 1)
                    self._staging.release(flowmem.batch_bytes(old))
            t, producer = ent
            if producer != id(op):
                # this dispatch was someone else's; we ride for free
                metric.SQL_SHARED_SCAN_DISPATCHES_SAVED.inc()
            return "tile", t


# stream registry: (table id, columns, tile) -> live SharedStream.
# Guarded by one control-plane lock; streams die with their last
# subscriber, so the registry only ever holds streams someone is reading.
_reg_mu = locks.lock("flow.sharedscan.registry")
_streams: dict[tuple, SharedStream] = {}


def reset() -> None:
    """Drop all streams (test isolation)."""
    with _reg_mu:
        for s in _streams.values():
            s._close()
        _streams.clear()


def attach(op) -> SharedStream | None:
    """Attach a resident tiled ScanOp to the shared stream for its
    (table, columns, tile) — or None for solo: sharding, a batch that
    is not the device-cache arrays, or an injected attach fault."""
    if not settings.get("sql.distsql.sharedscan.enabled"):
        return None
    if op.shard is not None or op.streaming:
        return None
    try:
        # chaos site: attach failure degrades to slicing our own tiles
        faults.fire("flow.sharedscan.attach")
    except faults.InjectedFault:
        return None
    key = (id(op.table), tuple(op.output_schema.names), op._res_tile)
    with _reg_mu:
        s = _streams.get(key)
        if s is not None:
            if not _same_batch(s.batch, op._batch):
                # KV-backed scans decode a fresh batch per init; equal
                # snapshot tokens prove the decodes are bit-identical,
                # so adopt the stream's arrays and share its tiles
                if (s.snap is None or getattr(op, "_snap", None) != s.snap
                        or s.batch.capacity != op._batch.capacity):
                    return None  # different snapshot: run solo
                op._batch = s.batch
            s._attach(op)
            metric.SQL_SHARED_SCAN_ATTACHED.inc()
            return s
        s = SharedStream(key, op._batch, op._res_tile, op._slice,
                         snap=getattr(op, "_snap", None))
        s._attach(op)
        _streams[key] = s
        return s


def detach(op, stream: SharedStream | None) -> None:
    if stream is None:
        return
    with _reg_mu:
        if stream._detach(op) and _streams.get(stream.key) is stream:
            del _streams[stream.key]
            stream._close()
