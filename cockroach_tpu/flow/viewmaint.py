"""Incremental materialized-view maintenance — delta tiles into standing
fold states, device-batched across views.

Reference: CockroachDB's changefeed plane feeding downstream consumers
(changefeedccl) composed with the fusion pass's ``_fold`` discipline
(flow/operators.py): a grouped-aggregate query's standing state IS the
dense partial-state arrays the scan path folds tile by tile — so view
maintenance is the SAME filter/project/group/fold kernel, applied to a
delta tile instead of a base-table tile, with retractions subtracted.

Architecture (one :class:`ViewMaintainer` per base KV table):

- **feed**: an in-process :class:`~..kv.fanout.LocalSubscriber` on the
  table's span buffers raw ``(ts, key, value|None)`` events under the
  fan-out plane's monitor accounting and backpressure ladder; the
  maintainer drains it with the two-phase ``peek``/``ack`` protocol so a
  flush that dies mid-apply re-reads the identical delta (the
  reconnect-from-frontier discipline, PR 17);
- **shadow**: a host dict ``key -> value bytes`` of the base table at
  the applied frontier turns an MVCC update/tombstone event into a
  *retraction* of the old row plus (for updates) an insertion of the
  new one — the classic incremental-view-maintenance delta algebra;
- **shape classes**: views whose defining query differs only in filter
  literals share one :class:`ShapeClass` (keyed by the parameterized
  plan's structural key, sql/plancache.py). A flush runs ONE fused
  dispatch per class: the insert/retract tiles decode once, then a
  ``jax.vmap`` over the view axis evaluates each view's parameterized
  filter/project pipeline and applies ``acc + ins - ret`` to the
  ``[V, G]`` state arrays — N views refresh as a handful of kernels,
  never N row loops;
- **retractable accumulators**: sum/count/count_rows/avg retract
  natively (integer/DECIMAL sums are exact and order-invariant, so the
  incremental state stays BIT-identical to a full rescan; float sums
  are maintained but only approximately order-invariant — documented,
  not oracle-checked); min/max/any_not_null keep a contributing count
  and flag ``dirty`` when a retraction hits the current extremum — the
  per-view re-scan fallback (MATVIEW_MINMAX_RESCANS) recomputes from
  the base table at the new frontier;
- **frontier**: all views of one maintainer share a resolved frontier;
  every flush computes everything first — states, rescans, shadow
  updates — and only then checkpoints + swaps + acks, so an injected
  fault at ``matview.flush`` / ``matview.delta.apply`` /
  ``matview.frontier.checkpoint`` leaves the old state and the buffered
  delta intact and the retry is bit-exact.

Out-of-bounds group keys (a dictionary value minted after CREATE falls
outside the view's dense layout) cannot be represented in the standing
``[V, G]`` arrays at all: the kernel counts them per view and the
registry rebuilds the view from a fresh bind + base rescan
(MATVIEW_FULL_RESCANS) — correctness over speed, never silent loss.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

from ..coldata.batch import Batch, Column
from ..coldata.types import Family, Schema
from ..ops import aggregation as agg
from ..ops import expr as ex
from ..plan import spec as S
from ..utils import faults, locks, log, metric, racesan, settings
from . import dispatch
from . import memory as flowmem

_MIN_TILE = 64


def _bucket(n: int) -> int:
    """Pad tile/view capacities to power-of-two buckets so shape-keyed
    retraces stay O(log n) over a run, not O(distinct sizes)."""
    cap = _MIN_TILE
    while cap < n:
        cap *= 2
    return cap


# ---------------------------------------------------------------------------
# pipeline extraction — the shape a standing view supports


@dataclass(frozen=True)
class PipelineInfo:
    """A dense grouped-aggregate pipeline carved out of a plan tree:
    TableScan -> [Filter|Project]* -> Aggregate(key_sizes set). The
    stages' column refs are relative to each stage's input schema."""

    scan: S.TableScan
    stages: tuple  # Filter | Project nodes, scan-side first
    aggregate: S.Aggregate
    # input schema per stage (stage_schemas[i] feeds stages[i]);
    # stage_schemas[-1] is the Aggregate's input schema
    stage_schemas: tuple[Schema, ...]


def extract_pipeline(plan: S.PlanNode, scan_schema: Schema
                     ) -> PipelineInfo | None:
    """The maintainable pipeline under ``plan``, or None when the plan is
    not a dense grouped aggregate over a single unsharded scan. The
    key_sizes requirement is what guarantees a bounded ``[G]`` state —
    exactly the SmallGroupAggregateOp gating (sql/rel.py groupby)."""
    if (not isinstance(plan, S.Aggregate) or plan.mode != "complete"
            or plan.key_sizes is None):
        return None
    stages = []
    node = plan.input
    while isinstance(node, (S.Filter, S.Project)):
        stages.append(node)
        node = node.input
    if not isinstance(node, S.TableScan) or node.shard is not None:
        return None
    stages.reverse()
    schemas = [scan_schema]
    for st in stages:
        cur = schemas[-1]
        if isinstance(st, S.Filter):
            schemas.append(cur)
        else:
            schemas.append(Schema(
                tuple(st.names),
                tuple(ex.expr_type(e, cur) for e in st.exprs)))
    return PipelineInfo(node, tuple(stages), plan, tuple(schemas))


def _spec_state_dtype(spec, schema: Schema):
    if spec.func in ("count", "count_rows"):
        return jnp.int64
    t = schema.types[spec.col]
    if spec.func == "sum":
        return jnp.float64 if t.family is Family.FLOAT else jnp.int64
    return t.dtype  # min / max / any_not_null carry the input dtype


# ---------------------------------------------------------------------------
# standing view + shape class


@dataclass
class ViewState:
    """One registered view: its identity, slot in a shape class, and the
    per-view resolved frontier the standing state reflects. ``frontier``
    is written under the maintainer lock and racesan-instrumented — it
    is the crash-recovery anchor the vtable and chaos tests read."""

    name: str
    select_text: str
    values: tuple          # scaled filter literals, one per param slot
    out_schema: Schema
    table: object          # catalog.Table registered under `name`
    cls: "ShapeClass" = None
    slot: int = -1
    frontier: int = 0
    created_s: float = field(default_factory=time.time)
    minmax_rescans: int = 0
    full_rescans: int = 0
    stale: bool = True     # host table behind the standing state
    last_lag_s: float = 0.0


class ShapeClass:
    """Views sharing one parameterized pipeline: one set of ``[V, G]``
    state arrays and ONE fused delta kernel per flush. Per-spec state is
    ``(data, cnt)`` where cnt counts contributing non-null rows — the
    retractable basis for the scan path's validity flags (sum/min/max
    valid == cnt > 0; count/count_rows always valid)."""

    def __init__(self, key, info: PipelineInfo, param_types,
                 table_schema: Schema, scan_idxs: tuple[int, ...]):
        self.key = key
        self.info = info
        self.param_types = tuple(param_types)
        self.table_schema = table_schema
        self.scan_idxs = scan_idxs
        a = info.aggregate
        self.gcols = a.group_cols
        self.key_sizes = a.key_sizes
        self.key_lows = (0,) * len(a.group_cols)
        self.G, self.strides = agg.dense_layout(a.key_sizes)
        self.in_schema = info.stage_schemas[-1]
        self.pspecs, _, self.final_map = agg.partial_layout(
            self.in_schema, a.group_cols, a.aggs)
        self.views: list[ViewState | None] = []  # slot -> view (None=free)
        self.gen = 0          # bumped on every state swap (read-sync key)
        cap = _bucket(1)
        self.datas = [self._empty_state(sp, cap) for sp in self.pspecs]
        self.cnts = [jnp.zeros((cap, self.G), jnp.int64)
                     for _ in self.pspecs]
        self.rows = jnp.zeros((cap, self.G), jnp.int64)
        self._params_np: list[np.ndarray] | None = None
        self._charged = 0
        self._recharge()
        self._delta_kernel = dispatch.jit(self._make_delta_kernel())
        self._scan_kernel = dispatch.jit(self._make_scan_kernel())
        self._finalize_kernel = dispatch.jit(self._make_finalize_kernel())

    def _recharge(self) -> None:
        """Standing ``[V, G]`` state is resident memory for the life of
        the class: keep the matview staging account in sync with its
        current footprint (delta-charged on capacity growth, released on
        close)."""
        n = int(self.rows.nbytes)
        for d in self.datas:
            n += int(d.nbytes)
        for c in self.cnts:
            n += int(c.nbytes)
        mon = flowmem.staging_monitor("matview")
        if n > self._charged:
            mon.reserve(n - self._charged, force=True)
        elif n < self._charged:
            mon.release(self._charged - n)
        self._charged = n

    def close(self) -> None:
        if self._charged:
            flowmem.staging_monitor("matview").release(self._charged)
            self._charged = 0

    # -- state array management -----------------------------------------

    def _empty_state(self, spec, cap: int):
        dt = _spec_state_dtype(spec, self.in_schema)
        if spec.func in ("min", "max", "any_not_null"):
            sent = agg._minmax_sentinel(np.dtype(dt), spec.func == "min")
            return jnp.full((cap, self.G), sent, dtype=dt)
        return jnp.zeros((cap, self.G), dt)

    @property
    def cap(self) -> int:
        return int(self.rows.shape[0])

    def live_count(self) -> int:
        return sum(1 for v in self.views if v is not None)

    def alloc_slot(self, view: ViewState) -> int:
        for i, v in enumerate(self.views):
            if v is None:
                self.views[i] = view
                break
        else:
            self.views.append(view)
            i = len(self.views) - 1
        if i >= self.cap:
            grow = _bucket(i + 1) - self.cap
            self.datas = [
                jnp.concatenate([d, self._empty_state(sp, grow)])
                for sp, d in zip(self.pspecs, self.datas)]
            self.cnts = [
                jnp.concatenate([c, jnp.zeros((grow, self.G), jnp.int64)])
                for c in self.cnts]
            self.rows = jnp.concatenate(
                [self.rows, jnp.zeros((grow, self.G), jnp.int64)])
            self._recharge()
        view.cls, view.slot = self, i
        self._params_np = None
        return i

    def free_slot(self, view: ViewState) -> None:
        if 0 <= view.slot < len(self.views):
            self.views[view.slot] = None
        view.cls, view.slot = None, -1
        self._params_np = None

    def _padded_params(self):
        """Per-slot ``[cap]`` value vectors + live mask + per-view
        frontier vector, padded to the state capacity. Dead slots repeat
        a live view's values so the vmapped lanes trace over real
        dtypes and never divide by surprise garbage."""
        if self._params_np is None:
            cap = self.cap
            cols = [np.zeros((cap,), dtype=t.dtype)
                    for t in self.param_types]
            live = np.zeros((cap,), dtype=bool)
            fill = next((v.values for v in self.views if v is not None),
                        tuple(np.zeros((), t.dtype)
                              for t in self.param_types))
            for s in range(cap):
                v = self.views[s] if s < len(self.views) else None
                vals = v.values if v is not None else fill
                for ci, x in enumerate(vals):
                    cols[ci][s] = x
                live[s] = v is not None
            self._params_np = cols
            self._live_np = live
        min_ts = np.zeros((self.cap,), np.int64)
        for s, v in enumerate(self.views):
            if v is not None:
                min_ts[s] = v.frontier
        return tuple(self._params_np), self._live_np, min_ts

    # -- the fused kernels ------------------------------------------------

    def _tile_states(self, cols, mask, ts, min_ts):
        """filter/project/group/fold over one delta tile for ONE view
        (traced inside param_scope; vmapped over views by the delta
        kernel). Mirrors SmallGroupAggregateOp's one-hot tile fold
        (ops/aggregation.smallgroup_partial_states) plus per-spec
        contributing counts — integer/DECIMAL reductions are exact, so
        this matches the scan path bit for bit."""
        m = mask
        if ts is not None:
            # events at or below the view's frontier are already folded
            # in (or covered by its initial scan): the no-duplication
            # half of the frontier discipline, enforced on-device
            m = m & (ts > min_ts)
        cur = cols
        for st, sch in zip(self.info.stages, self.info.stage_schemas):
            if isinstance(st, S.Filter):
                d, v = ex.eval_expr(st.predicate, cur, sch)
                m = m & d & v
            else:
                cur = tuple(
                    Column(*ex.eval_expr(e, cur, sch)) for e in st.exprs)
        b = Batch(cols=cur, mask=m)
        code, oob = agg.dense_group_codes(
            b, self.gcols, self.strides, self.key_sizes, self.key_lows)
        live = m & ~oob
        codes = jnp.clip(code.astype(jnp.int32), 0, self.G - 1)
        onehot = (codes[:, None]
                  == jnp.arange(self.G, dtype=jnp.int32)[None, :])
        onehot = onehot & live[:, None]
        rows = jnp.sum(onehot, axis=0, dtype=jnp.int64)
        datas, cnts = [], []
        for spec in self.pspecs:
            if spec.func == "count_rows":
                datas.append(rows)
                cnts.append(rows)
                continue
            col = b.cols[spec.col]
            t = self.in_schema.types[spec.col]
            member = onehot & col.valid[:, None]
            cnt = jnp.sum(member, axis=0, dtype=jnp.int64)
            if spec.func == "count":
                datas.append(cnt)
            elif spec.func == "sum":
                if t.family is Family.FLOAT:
                    v = jnp.where(
                        member, col.data.astype(jnp.float64)[:, None], 0.0)
                else:
                    v = jnp.where(
                        member, col.data.astype(jnp.int64)[:, None], 0)
                datas.append(jnp.sum(v, axis=0))
            elif spec.func in ("min", "max", "any_not_null"):
                is_min = spec.func == "min"
                sent = agg._minmax_sentinel(col.data.dtype, is_min)
                v = jnp.where(member, col.data[:, None], sent)
                datas.append(jnp.min(v, axis=0) if is_min
                             else jnp.max(v, axis=0))
            else:
                raise ValueError(
                    f"unsupported standing-view aggregate {spec.func}")
            cnts.append(cnt)
        oob_n = jnp.sum(oob & m, dtype=jnp.int64)
        return datas, cnts, rows, oob_n

    def _apply_delta(self, pvals, min_ts, acc_d, acc_c, acc_r,
                     ins_cols, ins_mask, ins_ts, ret_cols, ret_mask,
                     ret_ts):
        """One view's ``acc + ins - ret`` over precomputed accumulator
        rows. min/max merge inserts monotonically and flag ``dirty``
        when a retraction ties or beats the standing extremum — the only
        case delta algebra cannot answer without the base table."""
        with ex.param_scope(tuple(pvals)):
            i_d, i_c, i_r, i_oob = self._tile_states(
                ins_cols, ins_mask, ins_ts, min_ts)
            r_d, r_c, r_r, r_oob = self._tile_states(
                ret_cols, ret_mask, ret_ts, min_ts)
        new_r = acc_r + i_r - r_r
        out_d, out_c = [], []
        dirty = jnp.zeros((), jnp.bool_)
        for spec, ad, ac, idv, ic, rd, rc in zip(
                self.pspecs, acc_d, acc_c, i_d, i_c, r_d, r_c):
            nc = ac + ic - rc
            if spec.func in ("sum", "count", "count_rows"):
                nd = ad + idv - rd
            else:
                is_min = spec.func == "min"
                sent = agg._minmax_sentinel(np.dtype(ad.dtype), is_min)
                merged = (jnp.minimum(ad, idv) if is_min
                          else jnp.maximum(ad, idv))
                # empty groups reset to the sentinel so later inserts
                # merge cleanly instead of against a stale extremum
                nd = jnp.where(nc > 0, merged, sent)
                hit = (rc > 0) & (nc > 0) & (
                    (rd <= ad) if is_min else (rd >= ad))
                dirty = dirty | jnp.any(hit)
            out_d.append(nd)
            out_c.append(nc)
        return out_d, out_c, new_r, i_oob + r_oob, dirty

    def _make_delta_kernel(self):
        def kernel(acc_d, acc_c, acc_r, live, ins_val, ins_sel, ins_ts,
                   ret_val, ret_sel, ret_ts, pvals, min_ts):
            from ..storage import rowcodec

            ib = rowcodec.decode_columns(
                ins_val, ins_sel, self.table_schema, self.scan_idxs)
            rb = rowcodec.decode_columns(
                ret_val, ret_sel, self.table_schema, self.scan_idxs)

            def one(pv, mt, ad, ac, ar):
                return self._apply_delta(
                    pv, mt, ad, ac, ar, ib.cols, ib.mask, ins_ts,
                    rb.cols, rb.mask, ret_ts)

            nd, nc, nr, oob, dirty = jax.vmap(
                one, in_axes=(0, 0, 0, 0, 0))(
                    pvals, min_ts, acc_d, acc_c, acc_r)
            # dead/padded slots keep their old (zero) state untouched
            nd = [jnp.where(live[:, None], n, o)
                  for n, o in zip(nd, acc_d)]
            nc = [jnp.where(live[:, None], n, o)
                  for n, o in zip(nc, acc_c)]
            nr = jnp.where(live[:, None], nr, acc_r)
            return nd, nc, nr, oob, dirty
        return kernel

    def _make_scan_kernel(self):
        def kernel(cols, mask, pvals):
            with ex.param_scope(tuple(pvals)):
                return self._tile_states(cols, mask, None, None)
        return kernel

    # -- finalize (read path) ---------------------------------------------

    def _make_finalize_kernel(self):
        def kernel(states, rows):
            return agg.dense_finalize(
                self.in_schema, self.gcols, self.strides, self.key_sizes,
                self.G, self.final_map, states, rows,
                key_lows=self.key_lows)
        return kernel

    def finalize_slot(self, slot: int) -> Batch:
        """The view's final result batch from its standing state — the
        same dense_finalize the scan path ends in, COMPILED like the
        scan path ends in it: XLA's division-by-constant lowering (avg
        descaling) differs from the eager op by an ULP, and bit-identity
        to the fused pipeline requires the compiled form."""
        states = []
        for spec, d, c in zip(self.pspecs, self.datas, self.cnts):
            if spec.func in ("count", "count_rows"):
                valid = jnp.ones((self.G,), jnp.bool_)
            else:
                valid = c[slot] > 0
            states.append((d[slot], valid))
        return self._finalize_kernel(states, self.rows[slot])


# ---------------------------------------------------------------------------
# the maintainer


class ViewMaintainer:
    """All standing views over one base KV table: one LocalSubscriber,
    one shadow, one shared resolved frontier, one flush that refreshes
    every view in one fused dispatch per shape class.

    ``rebuild_cb(view)`` is provided by the registry (sql/matview.py):
    it re-binds the view's defining SELECT so an out-of-bounds group key
    (dictionary growth since CREATE) gets a fresh dense layout."""

    def __init__(self, table, hub, rebuild_cb=None):
        from ..storage import rowcodec

        self.table = table          # kv.table.KVTable
        self.db = table.db
        self.hub = hub
        self.rebuild_cb = rebuild_cb
        self.span = rowcodec.table_span(table.table_id)
        self._mu = locks.rlock("sql.matview.state")
        self.classes: dict = {}     # class key -> ShapeClass
        self.frontier = 0
        self._shadow: dict[bytes, bytes] = {}
        self.mon = flowmem.staging_monitor(
            "matview", budget=int(settings.get("sql.matview.staging_bytes")))
        self.sub = hub.add_local(start=self.span[0], end=self.span[1])
        if self.sub is None:
            raise RuntimeError("fan-out hub refused the matview "
                               "subscription (at max_subscribers?)")
        with self._mu:
            self._prime_locked()

    # -- feed plumbing ----------------------------------------------------

    def _scan_delta(self, lo: int):
        """Catch-up path: events in ``(lo, resolved]`` straight from the
        engine with the hub's span-local resolved discipline — what a
        shed/evicted subscription resumes from (and what primes the
        shadow at startup)."""
        from ..kv.changefeed import _scan

        now = int(self.db.clock.now())
        versions, intents = _scan(self.db, lo, now, self.span[0],
                                  self.span[1])
        resolved = now
        for its, _ikey in intents:
            resolved = min(resolved, int(its) - 1)
        resolved = max(resolved, lo)
        events = [(int(t), k, v) for t, k, v in versions
                  if int(t) <= resolved]
        return events, resolved

    def _prime_locked(self) -> None:
        """Build the shadow at the current resolved frontier by replaying
        the table's committed history, then ack the subscription there —
        from here on the buffered feed is the only input."""
        events, resolved = self._scan_delta(0)
        for _ts, key, val in events:
            if val is None:
                self._shadow.pop(key, None)
            else:
                self._shadow[key] = val
        racesan.note_write(self, "frontier")
        self.frontier = resolved
        self.sub.ack(resolved)

    def pending(self) -> bool:
        """Anything to flush? Cheap: one hub-lock peek, no engine scan."""
        events, resolved, _ = self.sub.peek()
        racesan.note_read(self, "frontier")
        return events is None or bool(events) or resolved > self.frontier

    def pump(self) -> None:
        """Deterministically run one hub poll (tests/bench: make writes
        committed before `now` visible in the buffer without waiting on
        the poller thread)."""
        self.hub._poll_once()

    # -- view membership --------------------------------------------------

    def class_for(self, key, info: PipelineInfo, param_types) -> ShapeClass:
        cls = self.classes.get(key)
        if cls is None:
            idxs = (tuple(self.table.schema.index(n)
                          for n in info.scan.columns)
                    if info.scan.columns is not None
                    else tuple(range(len(self.table.schema))))
            cls = ShapeClass(key, info, param_types, self.table.schema,
                             idxs)
            self.classes[key] = cls
        return cls

    def add_view(self, view: ViewState, key, info: PipelineInfo,
                 param_types) -> None:
        """Register + initially populate: flush everyone to the current
        resolved frontier first so the newcomer's base scan (at that
        same frontier) lines up exactly with the feed."""
        with self._mu:
            self.flush()
            cls = self.class_for(key, info, param_types)
            cls.alloc_slot(view)
            self._rescan_slot(view, self.frontier, commit=True)
            view.full_rescans += 1
            metric.MATVIEW_FULL_RESCANS.inc()

    def drop_view(self, view: ViewState) -> None:
        with self._mu:
            cls = view.cls
            if cls is None:
                return
            cls.free_slot(view)
            if cls.live_count() == 0:
                self.classes.pop(cls.key, None)
                cls.close()

    def views(self) -> list[ViewState]:
        with self._mu:
            return [v for c in self.classes.values() for v in c.views
                    if v is not None]

    # -- rescan (init / restart / min-max fallback) -----------------------

    def _rescan_slot(self, view: ViewState, ts: int,
                     commit: bool) -> tuple:
        """Recompute one view's full ``[G]`` state from a base-table
        snapshot at ``ts`` through the SAME pipeline kernel the delta
        path uses — one fused dispatch over the scanned batch. Returns
        the per-spec (datas, cnts, rows); commits into the class arrays
        when ``commit`` (init path), else leaves that to the flush's
        atomic swap (fallback path)."""
        cls = view.cls
        saved = self.table.read_ts
        try:
            self.table.read_ts = int(ts)
            names = (cls.info.scan.columns
                     if cls.info.scan.columns is not None
                     else self.table.schema.names)
            batch = self.table.device_batch(tuple(names))
        finally:
            self.table.read_ts = saved
        nbytes = sum(int(np.asarray(c.data).nbytes) for c in batch.cols)
        with flowmem.staged("matview", nbytes):
            datas, cnts, rows, _oob = cls._scan_kernel(
                batch.cols, batch.mask, view.values)
        if commit:
            cls.datas = [d.at[view.slot].set(nd)
                         for d, nd in zip(cls.datas, datas)]
            cls.cnts = [c.at[view.slot].set(nc)
                        for c, nc in zip(cls.cnts, cnts)]
            cls.rows = cls.rows.at[view.slot].set(rows)
            cls.gen += 1
            racesan.note_write(view, "frontier")
            view.frontier = int(ts)
            view.stale = True
        return datas, cnts, rows

    # -- the flush --------------------------------------------------------

    def _stage_tiles(self, rows: list):
        """list[(ts, value bytes)] -> padded device-tile arrays. Values
        from the feed are vlen-truncated; re-pad to the engine's value
        width so the decode kernel sees the layout it compiled for."""
        vw = int(self.db.engine.val_width)
        cap = _bucket(len(rows))
        vals = np.zeros((cap, vw), np.uint8)
        sel = np.zeros((cap,), bool)
        ts = np.zeros((cap,), np.int64)
        for i, (t, v) in enumerate(rows):
            b = np.frombuffer(v, dtype=np.uint8)
            vals[i, : len(b)] = b
            sel[i] = True
            ts[i] = t
        return vals, sel, ts, vals.nbytes + sel.nbytes + ts.nbytes

    def flush(self) -> bool:
        """Drain the buffered delta into every standing view. Everything
        is computed BEFORE anything is swapped; the three fault sites
        bracket compute so an injected failure anywhere leaves (state,
        shadow, frontier, buffer) exactly as they were — the retry
        re-applies the identical delta. Returns True when state moved."""
        with self._mu:
            return self._flush_locked()

    def _flush_locked(self) -> bool:
        t0 = time.monotonic()
        faults.fire("matview.flush")
        events, resolved, oldest = self.sub.peek()
        racesan.note_read(self, "frontier")
        applied = self.frontier
        if events is None:
            # shed/evicted: the engine holds the delta — resume by
            # scanning from the applied frontier (reconnect discipline)
            events, resolved = self._scan_delta(applied)
        events = [e for e in events if e[0] > applied]
        if not events and resolved <= applied:
            return False
        if not events:
            # frontier-only advance: no delta work, just the watermark
            faults.fire("matview.frontier.checkpoint")
            self._commit_locked(resolved, {}, {}, t0, oldest, 0)
            return True

        # -- delta algebra against the shadow (host, O(events)) ----------
        _absent = object()
        ins_rows: list = []
        ret_rows: list = []
        shadow_upd: dict = {}
        for ts, key, val in events:
            old = shadow_upd.get(key, _absent)
            if old is _absent:
                old = self._shadow.get(key)
            if old is not None:
                ret_rows.append((ts, old))
            if val is not None:
                ins_rows.append((ts, val))
            shadow_upd[key] = val

        ins_val, ins_sel, ins_ts, n_ins = self._stage_tiles(ins_rows)
        ret_val, ret_sel, ret_ts, n_ret = self._stage_tiles(ret_rows)

        # -- one fused dispatch per shape class --------------------------
        new_states: dict = {}
        fallbacks: list = []
        with flowmem.staged("matview", n_ins + n_ret):
            for cls in self.classes.values():
                if cls.live_count() == 0:
                    continue
                faults.fire("matview.delta.apply")
                pvals, live, min_ts = cls._padded_params()
                nd, nc, nr, oob, dirty = cls._delta_kernel(
                    cls.datas, cls.cnts, cls.rows, live, ins_val,
                    ins_sel, ins_ts, ret_val, ret_sel, ret_ts, pvals,
                    min_ts)
                oob_np = np.asarray(oob)
                dirty_np = np.asarray(dirty)
                for slot, view in enumerate(cls.views):
                    if view is None:
                        continue
                    if oob_np[slot] > 0:
                        fallbacks.append(("oob", view))
                    elif dirty_np[slot]:
                        # min/max retraction hit the standing extremum:
                        # recompute this view from the base table at the
                        # NEW frontier and splice it into the pending
                        # swap — still pre-commit, still retry-safe
                        sd, sc, sr = self._rescan_slot(
                            view, resolved, commit=False)
                        nd = [d.at[slot].set(x)
                              for d, x in zip(nd, sd)]
                        nc = [c.at[slot].set(x)
                              for c, x in zip(nc, sc)]
                        nr = nr.at[slot].set(sr)
                        fallbacks.append(("minmax", view))
                new_states[cls.key] = (nd, nc, nr)

        faults.fire("matview.frontier.checkpoint")
        self._commit_locked(resolved, new_states, shadow_upd, t0, oldest,
                            len(events))
        for kind, view in fallbacks:
            if kind == "minmax":
                view.minmax_rescans += 1
                metric.MATVIEW_MINMAX_RESCANS.inc()
            else:
                self._rebuild_view(view)
        return True

    def _commit_locked(self, resolved, new_states, shadow_upd, t0,
                       oldest, n_events) -> None:
        """The atomic half: nothing before this mutated anything; a
        fault past this point cannot fire (no sites) so state, shadow,
        frontier and ack move together."""
        for key, (nd, nc, nr) in new_states.items():
            cls = self.classes.get(key)
            if cls is None:
                continue
            cls.datas, cls.cnts, cls.rows = nd, nc, nr
            cls.gen += 1
            for v in cls.views:
                if v is not None:
                    racesan.note_write(v, "frontier")
                    v.frontier = resolved
                    v.stale = True
        racesan.note_write(self, "frontier")
        self.frontier = resolved
        # views in classes untouched this flush (no events reached them)
        # still advance: their state at `applied` equals their state at
        # `resolved` by definition of an empty delta
        for cls in self.classes.values():
            for v in cls.views:
                if v is not None and v.frontier < resolved:
                    racesan.note_write(v, "frontier")
                    v.frontier = resolved
        for k, v in shadow_upd.items():
            if v is None:
                self._shadow.pop(k, None)
            else:
                self._shadow[k] = v
        self.sub.ack(resolved)
        metric.MATVIEW_FLUSHES.inc()
        if n_events:
            metric.MATVIEW_DELTA_EVENTS.inc(n_events)
        lag = time.monotonic() - (oldest if oldest is not None else t0)
        metric.MATVIEW_REFRESH_LAG_SECONDS.observe(max(0.0, lag))
        for cls in self.classes.values():
            for v in cls.views:
                if v is not None:
                    v.last_lag_s = max(0.0, lag)

    def _rebuild_view(self, view: ViewState) -> None:
        """Out-of-bounds group key: the dense layout minted at CREATE
        cannot hold it. Re-bind the defining SELECT (fresh dictionary
        sizes -> fresh layout) and repopulate by base rescan."""
        view.full_rescans += 1
        metric.MATVIEW_FULL_RESCANS.inc()
        if self.rebuild_cb is not None:
            self.rebuild_cb(view)
        else:  # no registry (unit-test direct use): rescan in place
            log.warning(log.OPS, "matview oob without rebuild_cb",
                        view=view.name)
            with self._mu:
                self._rescan_slot(view, self.frontier, commit=True)

    def close(self) -> None:
        with self._mu:
            for cls in self.classes.values():
                cls.close()
            self.classes.clear()
            self._shadow.clear()
        if self.sub is not None:
            self.sub.close()
            self.sub = None
