"""The Operator contract — the colexecop.Operator analog.

Reference: pkg/sql/colexecop/operator.go:21 — ``Operator { Init(ctx);
Next() coldata.Batch }``, pull-based, zero-length batch means exhausted. Here
``next_batch() -> Batch | None`` returns device-resident tiles; None means
exhausted. Device work inside an operator is jitted once per operator
instance (tiles share static shapes, so each op compiles exactly once).

Operators also surface plan-static metadata the reference carries in specs:
``output_schema`` and per-column string ``dictionaries`` (the host half of the
columnar string representation).
"""

from __future__ import annotations

from ..coldata.batch import Batch, Dictionary
from ..coldata.types import Schema


class Operator:
    """Base pull operator. Subclasses set output_schema/dictionaries in
    __init__ and implement _next()."""

    output_schema: Schema
    dictionaries: dict[int, Dictionary]

    def __init__(self):
        self.dictionaries = {}
        self._initialized = False

    def init(self) -> None:
        """Init(ctx) analog — called once before the first next_batch."""
        self._initialized = True

    def next_batch(self) -> Batch | None:
        if not self._initialized:
            self.init()
        return self._next()

    def _next(self) -> Batch | None:
        raise NotImplementedError

    def close(self) -> None:
        """Closer analog (colexecop/operator.go:194)."""


class SourceOperator(Operator):
    """An operator with no inputs (scan, inbox)."""


class OneInputOperator(Operator):
    def __init__(self, child: Operator):
        super().__init__()
        self.child = child
        self.dictionaries = dict(child.dictionaries)

    def init(self) -> None:
        self.child.init()
        super().init()

    def close(self) -> None:
        self.child.close()
