"""The Operator contract — the colexecop.Operator analog.

Reference: pkg/sql/colexecop/operator.go:21 — ``Operator { Init(ctx);
Next() coldata.Batch }``, pull-based, zero-length batch means exhausted. Here
``next_batch() -> Batch | None`` returns device-resident tiles; None means
exhausted. Device work inside an operator is jitted once per operator
instance (tiles share static shapes, so each op compiles exactly once).

Operators also surface plan-static metadata the reference carries in specs:
``output_schema`` and per-column string ``dictionaries`` (the host half of the
columnar string representation).
"""

from __future__ import annotations

import time

import numpy as np

from ..coldata.batch import Batch, Dictionary
from ..coldata.types import Schema


class ComponentStats:
    """Per-operator execution stats — the execinfrapb.ComponentStats analog
    (execinfrapb/component_stats.proto), folded into EXPLAIN ANALYZE by
    plan/explain.py (the execstats/traceanalyzer.go role)."""

    __slots__ = ("batches", "rows", "time_s", "bytes", "kernel_dispatches",
                 "kernel_compiles", "max_mem_bytes", "spilled")

    def __init__(self):
        self.batches = 0
        self.rows = 0
        self.time_s = 0.0  # inclusive wall time in next_batch (incl. children)
        self.bytes = 0  # logical device bytes emitted (colmem accounting)
        # peak reserved bytes across this operator's memory accounts
        # (mon.BoundAccount high-water, shown as EXPLAIN ANALYZE "max mem")
        self.max_mem_bytes = 0
        # True once a memory account overflow swapped this operator to its
        # external variant (disk_spiller.go's spilled marker)
        self.spilled = False
        # XLA dispatches the whole query issued (flow/dispatch.py delta,
        # attributed to the ROOT's stats by run_operator — dispatches are
        # process-global, not attributable per operator without a sync)
        self.kernel_dispatches = 0
        # fresh XLA traces/compiles the query triggered (same root-level
        # attribution; 0 on the zero-recompile serving path)
        self.kernel_compiles = 0

    def exclusive(self, children: list["Operator"]) -> float:
        return self.time_s - sum(c.stats.time_s for c in children)


class Operator:
    """Base pull operator. Subclasses set output_schema/dictionaries in
    __init__ and implement _next().

    col_stats maps output column index -> (lo, hi) value bounds where known
    (from catalog table statistics, propagated like dictionaries). Sort and
    group-by kernels use them to bit-pack key columns into fewer sort
    operands (ops/keys.py) — the optimizer-statistics analog applied to
    kernel shape instead of plan choice."""

    output_schema: Schema
    dictionaries: dict[int, Dictionary]
    col_stats: dict[int, tuple]

    def __init__(self):
        self.dictionaries = {}
        self.col_stats = {}
        self._initialized = False
        self.stats = ComponentStats()
        self._collect = False

    def init(self) -> None:
        """Init(ctx) analog — called once before the first next_batch."""
        self._initialized = True

    def next_batch(self) -> Batch | None:
        if not self._initialized:
            self.init()
        if not self._collect:
            return self._next()
        t0 = time.perf_counter()
        b = self._next()
        if b is not None:
            # row counting forces a device sync, so exact per-operator times
            # and rows are an EXPLAIN ANALYZE-only cost (like the reference's
            # stats collection wrappers in colflow/stats.go)
            from .memory import batch_bytes

            self.stats.rows += int(np.asarray(b.mask).sum())
            self.stats.batches += 1
            self.stats.bytes += batch_bytes(b)
        self.stats.time_s += time.perf_counter() - t0
        return b

    def children(self) -> list["Operator"]:
        return []

    def collect_stats(self, enabled: bool = True) -> None:
        self._collect = enabled
        self.stats = ComponentStats()
        for c in self.children():
            c.collect_stats(enabled)

    def _next(self) -> Batch | None:
        raise NotImplementedError

    def stream_parts(self):
        """Fused-streaming contract: (source, fn, args) when this operator's
        output is a pure per-tile device function of a source's tiles —
        consumers compose the whole chain into one jit (flow/operators.py).
        None means this operator is a pipeline barrier."""
        return None

    def post_run_update(self) -> bool:
        """End-of-query hook: adaptive operators fetch their deferred device
        counters here (ONE sync at query end, never per tile — a host sync
        costs a tunnel RTT on remote-attached TPU) and update sticky
        execution choices. Returns True when this run's OUTPUT was invalid
        (e.g. a speculative emission capacity overflowed) and the runtime
        must re-run the query with the corrected choices."""
        return False

    def close(self) -> None:
        """Closer analog (colexecop/operator.go:194)."""


class SourceOperator(Operator):
    """An operator with no inputs (scan, inbox)."""


class OneInputOperator(Operator):
    def __init__(self, child: Operator):
        super().__init__()
        self.child = child
        self.dictionaries = dict(child.dictionaries)
        self.col_stats = dict(child.col_stats)

    def init(self) -> None:
        self.child.init()
        super().init()

    def children(self) -> list[Operator]:
        return [self.child]

    def close(self) -> None:
        self.child.close()
