"""External (partitioned) operators — the colexecdisk analog.

Reference: pkg/sql/colexec/colexecdisk swaps an in-memory operator for an
external variant when it exceeds its memory budget (disk_spiller.go:103):
external hash join/agg partition recursively by key hash (Grace —
hash_based_partitioner.go), external sort merges sorted runs
(external_sort.go) staged in colcontainer disk queues.

TPU redesign: the budget is the device tile ceiling. Oversized inputs stage
on the HOST as compacted numpy partitions (the host-RAM tier standing in for
colcontainer's disk queues — an optional spill_dir persists partitions as
.npz, diskqueue.go:177 analog), partitioned ON DEVICE:

- Grace hash join: both sides bucket by the SAME key hash (ops.hashing), so
  partition i of the probe joins only partition i of the build; each
  partition joins in-memory with the existing kernels.
- External sort: rows bucket by range of an order-preserving uint64 of the
  primary sort key (quantile boundaries from the staged data); bucket i's
  rows all precede bucket j's (i<j), ties stay within one bucket, so
  sorting each bucket with the full key list and emitting buckets in order
  is a total order — the k-way merge becomes embarrassingly bucket-parallel
  (the same trick the shuffle plane uses for distributed sort).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from ..coldata.batch import Batch, from_host
from ..coldata.types import Family, Schema
from ..ops import join as join_ops
from ..ops import sort as sort_ops
from ..ops.hashing import hash_columns
from . import dispatch
from .operator import OneInputOperator, Operator


def _pow2(n: int) -> int:
    # partition reload / join output capacities are data-dependent: snap
    # them to the canonical shape ladder so a repeat run with different
    # literals (≈ different partition sizes) reuses the spill kernels
    from .operators import _canonical_cap

    return _canonical_cap(max(1, n))


class HostPartitions:
    """Host-staged row partitions (colcontainer partitioned queue analog).
    Each partition accumulates compacted numpy columns; reload() returns a
    device Batch per partition."""

    def __init__(self, schema: Schema, nparts: int, spill_dir: str | None = None):
        from . import memory as flowmem

        self.schema = schema
        self.nparts = nparts
        self.parts: list[list[dict]] = [[] for _ in range(nparts)]
        self.rows = [0] * nparts
        # staged host rows charge the node-level spill-staging account
        # (NOT the query monitor: partitions outlive operator accounts,
        # and the drain census ignores cache-level children). A finalizer
        # releases whatever free() was never called for; the holder dict
        # keeps the finalizer from retaining self.
        self._mon = flowmem.staging_monitor("flow/spill-staging")
        self._charged = [0] * nparts
        hold, mon = {"n": 0}, self._mon
        self._hold = hold
        import weakref

        weakref.finalize(self, lambda: mon.release(hold["n"]))

    def append_host(self, pid: int, arrays: dict, valids: dict, n: int):
        if n == 0:
            return
        nb = int(sum(a.nbytes for a in arrays.values())
                 + sum(v.nbytes for v in valids.values()))
        self._mon.reserve(nb, force=True)
        self._charged[pid] += nb
        self._hold["n"] += nb
        self.parts[pid].append({"arrays": arrays, "valids": valids, "n": n})
        self.rows[pid] += n

    def free(self, pid: int) -> None:
        """Drop a partition's staged rows and release their reservation —
        callers free as they consume so peak staging tracks the live set."""
        self._mon.release(self._charged[pid])
        self._hold["n"] -= self._charged[pid]
        self._charged[pid] = 0
        self.parts[pid] = []
        self.rows[pid] = 0

    def reload(self, pid: int) -> Batch | None:
        chunks = self.parts[pid]
        if not chunks:
            return None
        n = self.rows[pid]
        arrays = {
            name: np.concatenate([c["arrays"][name] for c in chunks])
            for name in self.schema.names
        }
        valids = {
            name: np.concatenate([c["valids"][name] for c in chunks])
            for name in self.schema.names
        }
        return from_host(self.schema, arrays, valids, capacity=_pow2(n))


def stage_batch(batch: Batch, schema: Schema, pids: np.ndarray | None,
                parts: HostPartitions):
    """Move a device batch's live rows to host partitions. `pids` is the
    per-row partition id (host numpy, dead rows ignored)."""
    mask = np.asarray(batch.mask)
    for pid in range(parts.nparts):
        sel = mask if pids is None else (mask & (pids == pid))
        n = int(sel.sum())
        if n == 0:
            continue
        arrays = {}
        valids = {}
        for name, col in zip(schema.names, batch.cols):
            arrays[name] = np.asarray(col.data)[sel]
            valids[name] = np.asarray(col.valid)[sel]
        parts.append_host(pid, arrays, valids, n)


class ReplayOp(Operator):
    """Re-emits already-spooled device tiles — glue that lets an in-memory
    operator hand its buffered input to the external variant it spills into
    (the disk_spiller handoff, disk_spiller.go:103)."""

    def __init__(self, tiles, schema: Schema, dictionaries):
        super().__init__()
        self.tiles = list(tiles)
        self.output_schema = schema
        self.dictionaries = dict(dictionaries)
        self._i = 0

    def init(self):
        super().init()
        self._i = 0

    def _next(self):
        if self._i >= len(self.tiles):
            return None
        b = self.tiles[self._i]
        self._i += 1
        return b


class ChainOp(ReplayOp):
    """Replays spooled tiles, then continues pulling from the live input —
    the handoff when an operator spills mid-stream. Does NOT re-init the
    live input (it is mid-stream by construction)."""

    def __init__(self, tiles, schema: Schema, dictionaries, rest: Operator):
        super().__init__(tiles, schema, dictionaries)
        self.rest = rest

    def _next(self):
        b = super()._next()
        return self.rest.next_batch() if b is None else b



def _array_key(a):
    """Content key for a small baked-in table (dictionary ranks/hashes) so
    spill kernels can share through the process-global kernel cache. Spill
    operators are constructed at RUNTIME (SortOp/AggregateOp/HashJoinOp
    hand off mid-query), so without a content key every spilling run of a
    cached plan would re-trace identical kernels."""
    if a is None:
        return None
    a = np.asarray(a)
    return (str(a.dtype), a.shape, a.tobytes())


def make_bucket_fn(schema: Schema, keys, tables, nparts: int):
    """Jitted per-row partition id from the key columns' 64-bit hash —
    THE Grace partition function, shared by the external join and
    aggregation so their partitioning can never diverge."""
    def fn(b: Batch):
        cols = [b.cols[i] for i in keys]
        types = [schema.types[i] for i in keys]
        h = hash_columns(cols, types, tables or None)
        return (h % np.uint64(nparts)).astype(jnp.int32)

    key = dispatch.kernel_key(
        "grace_bucket", schema, tuple(keys), nparts,
        tuple(sorted((i, _array_key(t)) for i, t in (tables or {}).items())),
    )
    return dispatch.jit(fn, key=key)


# ---------------------------------------------------------------------------
# Grace hash join


class GraceHashJoinOp(OneInputOperator):
    """External hash join: both sides hash-partition into P buckets staged
    on the host; partition pairs join in-memory (hash_based_partitioner.go
    semantics, one recursion level)."""

    def __init__(self, probe: Operator, build: Operator,
                 probe_keys, build_keys, spec, nparts: int = 8):
        super().__init__(probe)
        self.build = build
        self.probe_keys = tuple(probe_keys)
        self.build_keys = tuple(build_keys)
        self.spec = spec
        self.nparts = nparts
        self.output_schema = join_ops.join_output_schema(
            probe.output_schema, build.output_schema, spec
        )
        self.dictionaries = dict(probe.dictionaries)
        if spec.join_type not in ("semi", "anti"):
            off = len(probe.output_schema)
            for i, d in build.dictionaries.items():
                self.dictionaries[off + i] = d
        # host-side string bridges (same as HashJoinOp)
        self.probe_hash_tables = {}
        self.build_hash_tables = {}
        self.build_code_remaps = {}
        for pos, (pk, bk) in enumerate(zip(self.probe_keys, self.build_keys)):
            pt = probe.output_schema.types[pk]
            if pt.family is Family.STRING:
                pd_ = probe.dictionaries[pk]
                bd = build.dictionaries[bk]
                self.probe_hash_tables[pk] = pd_.hashes
                self.build_hash_tables[bk] = bd.hashes
                # crlint: allow-mem-accounting(dictionary code remap: one int32 per distinct build-side string, bounded by dictionary size)
                self.build_code_remaps[pos] = np.array(
                    [pd_.code_of(str(v)) for v in bd.values], dtype=np.int32
                )

    def children(self):
        return [self.child, self.build]

    def init(self):
        self.build.init()
        super().init()
        self._partitioned = False
        self._pid = 0
        self._pending = []
        if hasattr(self, "_bucket_probe"):
            return
        self._bucket_probe = make_bucket_fn(
            self.child.output_schema, self.probe_keys,
            self.probe_hash_tables, self.nparts,
        )
        self._bucket_build = make_bucket_fn(
            self.build.output_schema, self.build_keys,
            self.build_hash_tables, self.nparts,
        )

    def _partition_all(self):
        pparts = HostPartitions(self.child.output_schema, self.nparts)
        bparts = HostPartitions(self.build.output_schema, self.nparts)
        while True:
            b = self.build.next_batch()
            if b is None:
                break
            stage_batch(b, self.build.output_schema,
                        np.asarray(self._bucket_build(b)), bparts)
        while True:
            p = self.child.next_batch()
            if p is None:
                break
            stage_batch(p, self.child.output_schema,
                        np.asarray(self._bucket_probe(p)), pparts)
        self._pparts = pparts
        self._bparts = bparts
        self._partitioned = True

    def _join_partition(self, pid: int) -> Batch | None:
        probe = self._pparts.reload(pid)
        if probe is None:
            return None
        build = self._bparts.reload(pid)
        if build is None:
            from ..coldata.batch import empty_batch

            build = empty_batch(self.build.output_schema, 1024)
        index = join_ops.build_index(
            build, self.build.output_schema, self.build_keys,
            self.build_hash_tables or None,
        )
        out_cap = _pow2(probe.capacity)
        while True:
            out, total = join_ops.hash_join_general(
                probe, self.child.output_schema, self.probe_keys,
                build, self.build.output_schema, self.build_keys,
                self.spec, out_cap,
                self.probe_hash_tables or None,
                self.build_hash_tables or None,
                self.build_code_remaps or None,
                index=index,
            )
            if int(total) <= out_cap:
                return out
            out_cap = _pow2(int(total) + 1)

    def _next(self):
        if not self._partitioned:
            self._partition_all()
        while self._pid < self.nparts:
            out = self._join_partition(self._pid)
            self._pid += 1
            if out is not None:
                return out
        return None

    def close(self):
        super().close()
        self.build.close()


# ---------------------------------------------------------------------------
# External sort


# crlint: allow-mem-accounting(tile-width device temp for order-preserving key packing; the owning batch is charged by its operator account)
def _primary_u64(batch: Batch, schema: Schema, key: sort_ops.SortKey,
                 rank_table=None) -> jax.Array:
    """Order-preserving uint64 of the primary sort key (NULL ordering
    folded in: null_key gets the top bit band)."""
    c = batch.cols[key.col]
    ops = sort_ops.order_keys(c.data, c.valid, key, schema.types[key.col],
                              rank_table)
    # order_keys returns leading 1-bit bool bands (null ordering, NaN
    # ordering) followed by the payload word(s). Fold the bands into the top
    # bits and range-partition on the FIRST payload word only — for
    # multi-word keys (BYTES wider than 8) this is order-preserving at
    # partition granularity: rows equal in the leading word stay in one
    # bucket, and the within-bucket sort uses the full key list.
    bands, payload = [], None
    for op in ops:
        if op.dtype == jnp.bool_:
            bands.append(op)
        else:
            payload = op
            break
    if payload is None:  # BOOL key: its one bool band IS the payload —
        # promote the bit to the top so the band right-shift below keeps it
        payload = bands.pop().astype(jnp.uint64) << np.uint64(63)
    u = jnp.zeros((batch.capacity,), jnp.uint64)
    shift = np.uint64(62)
    for op in bands:
        u = u | (op.astype(jnp.uint64) << shift)
        shift -= np.uint64(1)
    if payload.dtype in (jnp.float64, jnp.float32):
        f = payload.astype(jnp.float64)
        parts = jax.lax.bitcast_convert_type(f, jnp.uint32)
        p = (parts[..., 1].astype(jnp.uint64) << np.uint64(32)) | parts[
            ..., 0
        ].astype(jnp.uint64)
        neg = (p >> np.uint64(63)) != 0
        p = jnp.where(neg, ~p, p | np.uint64(1 << 63))
    elif payload.dtype == jnp.uint64:
        p = payload
    else:
        p = payload.astype(jnp.int64).astype(jnp.uint64) ^ np.uint64(1 << 63)
    # drop low bits to make room for the null/nan bands (ordering within
    # equal top bands preserved; only boundary granularity is affected)
    return u | (p >> np.uint64(64 - int(shift) - 1))


class ExternalSortOp(OneInputOperator):
    """External sort: range-partition rows by a uint64 of the primary key
    (quantile boundaries over staged samples), then sort each bucket with
    the full key list and emit buckets in order (external_sort.go role; the
    merge phase is bucket-ordered emission instead of a loser tree)."""

    def __init__(self, child: Operator, keys, budget_rows: int = 1 << 20,
                 nparts: int = 8):
        super().__init__(child)
        self.output_schema = child.output_schema
        self.keys = tuple(keys)
        self.budget_rows = budget_rows
        self.nparts = nparts
        self._staged = False

    def init(self):
        super().init()
        self._staged = False
        self._pid = 0
        if hasattr(self, "_u64_fn"):
            return
        schema = self.output_schema
        key = self.keys[0]
        rank_table = None
        if key.col in self.child.dictionaries:
            rank_table = self.child.dictionaries[key.col].ranks
        self._u64_fn = dispatch.jit(
            lambda b: _primary_u64(b, schema, key, rank_table),
            key=dispatch.kernel_key("extsort_u64", schema, key,
                                    _array_key(rank_table)),
        )
        rank_tables = {
            k.col: self.child.dictionaries[k.col].ranks
            for k in self.keys
            if k.col in self.child.dictionaries
        }
        keys = self.keys

        def sort_fn(b):
            return sort_ops.sort_batch(b, schema, keys, rank_tables)

        self._sort_fn = dispatch.jit(sort_fn, key=dispatch.kernel_key(
            "extsort_sort", schema, keys,
            tuple(sorted((c, _array_key(t))
                         for c, t in rank_tables.items())),
        ))

    def _stage_all(self):
        # pass 1: stage all rows + their primary u64 on the host
        chunks = []
        while True:
            b = self.child.next_batch()
            if b is None:
                break
            u = np.asarray(self._u64_fn(b))
            mask = np.asarray(b.mask)
            arrays = {
                name: np.asarray(c.data)[mask]
                for name, c in zip(self.output_schema.names, b.cols)
            }
            valids = {
                name: np.asarray(c.valid)[mask]
                for name, c in zip(self.output_schema.names, b.cols)
            }
            chunks.append((arrays, valids, u[mask]))
        total = sum(len(c[2]) for c in chunks)
        if total == 0:
            self._parts = None
            self._staged = True
            return
        from . import memory as flowmem

        # quantile boundaries over the staged u64s: the transient key
        # vector is 8 B/row over the whole staged input — charge it for
        # the split computation's lifetime
        with flowmem.staged("flow/spill-staging", 8 * total):
            allu = np.concatenate([c[2] for c in chunks])
            P = min(self.nparts, max(1, (total + self.budget_rows - 1)
                                     // self.budget_rows * 2))
            qs = np.quantile(allu, np.linspace(0, 1, P + 1)[1:-1])
            bounds = np.unique(qs.astype(np.uint64))
        parts = HostPartitions(self.output_schema, len(bounds) + 1)
        for arrays, valids, u in chunks:
            pids = np.searchsorted(bounds, u, side="right")
            for pid in range(parts.nparts):
                sel = pids == pid
                n = int(sel.sum())
                if n:
                    parts.append_host(
                        pid,
                        {k: v[sel] for k, v in arrays.items()},
                        {k: v[sel] for k, v in valids.items()},
                        n,
                    )
        self._parts = parts
        self._staged = True

    def _next(self):
        if not self._staged:
            self._stage_all()
        if self._parts is None:
            return None
        while self._pid < self._parts.nparts:
            b = self._parts.reload(self._pid)
            self._pid += 1
            if b is not None:
                return self._sort_fn(b)
        return None


# ---------------------------------------------------------------------------
# Grace external aggregation (external_hash_aggregator.go role) — also the
# external DISTINCT, which is aggregation with no aggregate functions


class GraceAggregateOp(Operator):
    """External aggregation over partial-STATE tiles: rows partition by
    group-key hash, so partitions are GROUP-DISJOINT — each merges and
    finalizes independently and streams out one batch at a time, bounding
    memory by the largest partition instead of the full group count
    (hash_based_partitioner.go recursion is unnecessary here because the
    merge stage re-aggregates: a skewed partition still shrinks to its
    distinct groups).

    Built by AggregateOp's spill handoff: `child` replays the spooled
    state tiles then continues the live partial stream (ChainOp)."""

    def __init__(self, child: Operator, agg_op, nparts: int = 8):
        super().__init__()
        # zero group keys never reach here (no-GROUP-BY plans use
        # ScalarAggregateOp); partitioning without keys would duplicate
        # every row into all partitions
        assert agg_op.num_keys > 0, "Grace aggregation needs group keys"
        self.child = child
        self.agg = agg_op  # the spilling AggregateOp (owns merge/finalize)
        self.nparts = nparts
        self.output_schema = agg_op.output_schema
        self.dictionaries = dict(agg_op.dictionaries)
        self.col_stats = dict(agg_op.col_stats)

    def children(self):
        return [self.child]

    def init(self):
        self._parts = None
        self._pid = 0
        self._initialized = True
        if hasattr(self, "_bucket"):
            return
        schema = self.agg.state_schema
        keys = tuple(range(self.agg.num_keys))
        tables = {
            pos: d.hashes
            for pos, d in self.agg.dictionaries.items()
            if pos < self.agg.num_keys
        }
        self._bucket = make_bucket_fn(schema, keys, tables, self.nparts)

    def _stage_all(self):
        from ..utils import log, metric

        parts = HostPartitions(self.agg.state_schema, self.nparts)
        n_tiles = 0
        while True:
            b = self.child.next_batch()
            if b is None:
                break
            n_tiles += 1
            pids = np.asarray(self._bucket(b))
            stage_batch(b, self.agg.state_schema, pids, parts)
        metric.EXTERNAL_AGG_SPILLS.inc()
        log.info(log.SQL_EXEC, "aggregation spilled to Grace partitions",
                 tiles=n_tiles, partitions=self.nparts,
                 rows=sum(parts.rows))
        self._parts = parts

    def _next(self):
        if self._parts is None:
            self._stage_all()
        while self._pid < self.nparts:
            pid = self._pid
            self._pid += 1
            batch = self._parts.reload(pid)
            self._parts.free(pid)  # free as we go (releases the staging charge)
            if batch is None:
                continue
            cap = batch.capacity
            merged, ng = self.agg._merge_fn((batch,), cap=cap)
            while int(ng) > cap:
                cap = _pow2(int(ng) + 1)
                merged, ng = self.agg._merge_fn((batch,), cap=cap)
            if self.agg.mode == "partial":
                return merged
            return self.agg._finalize_fn(merged)
        return None

    def close(self):
        self.child.close()
        self._parts = None
