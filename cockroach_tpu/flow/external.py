"""External (partitioned) operators — the colexecdisk analog.

Reference: pkg/sql/colexec/colexecdisk swaps an in-memory operator for an
external variant when it exceeds its memory budget (disk_spiller.go:103):
external hash join/agg partition recursively by key hash (Grace —
hash_based_partitioner.go), external sort merges sorted runs
(external_sort.go) staged in colcontainer disk queues.

TPU redesign: the budget is the device tile ceiling. Oversized inputs stage
on the HOST as compacted numpy partitions (the host-RAM tier standing in for
colcontainer's disk queues — an optional spill_dir persists partitions as
.npz, diskqueue.go:177 analog), partitioned ON DEVICE:

- Grace hash join: both sides bucket by the SAME key hash (ops.hashing), so
  partition i of the probe joins only partition i of the build; each
  partition joins in-memory with the existing kernels.
- External sort: rows bucket by range of an order-preserving uint64 of the
  primary sort key (quantile boundaries from the staged data); bucket i's
  rows all precede bucket j's (i<j), ties stay within one bucket, so
  sorting each bucket with the full key list and emitting buckets in order
  is a total order — the k-way merge becomes embarrassingly bucket-parallel
  (the same trick the shuffle plane uses for distributed sort).
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
import numpy as np

from ..coldata.batch import Batch, from_host
from ..coldata.types import Family, Schema
from ..ops import join as join_ops
from ..ops import sort as sort_ops
from ..ops.hashing import hash_columns
from ..utils import faults
from . import dispatch
from .operator import OneInputOperator, Operator


def _pow2(n: int) -> int:
    # partition reload / join output capacities are data-dependent: snap
    # them to the canonical shape ladder so a repeat run with different
    # literals (≈ different partition sizes) reuses the spill kernels
    from .operators import _canonical_cap

    return _canonical_cap(max(1, n))


class HostPartitions:
    """Host-staged row partitions (colcontainer partitioned queue analog).
    Each partition accumulates compacted numpy columns; reload() returns a
    device Batch per partition."""

    def __init__(self, schema: Schema, nparts: int, spill_dir: str | None = None):
        from . import memory as flowmem

        self.schema = schema
        self.nparts = nparts
        self.parts: list[list[dict]] = [[] for _ in range(nparts)]
        self.rows = [0] * nparts
        # staged host rows charge the node-level spill-staging account
        # (NOT the query monitor: partitions outlive operator accounts,
        # and the drain census ignores cache-level children). A finalizer
        # releases whatever free() was never called for; the holder dict
        # keeps the finalizer from retaining self.
        self._mon = flowmem.staging_monitor("flow/spill-staging")
        self._charged = [0] * nparts
        hold, mon = {"n": 0}, self._mon
        self._hold = hold
        import weakref

        weakref.finalize(self, lambda: mon.release(hold["n"]))

    def append_host(self, pid: int, arrays: dict, valids: dict, n: int):
        if n == 0:
            return
        # chaos hook: a failed host partition write (the colcontainer disk
        # queue's enqueue erroring) fires BEFORE the reservation so the
        # staging account never holds bytes for rows that were never staged
        faults.fire("flow.spill.partition_write")
        nb = int(sum(a.nbytes for a in arrays.values())
                 + sum(v.nbytes for v in valids.values()))
        self._mon.reserve(nb, force=True)
        self._charged[pid] += nb
        self._hold["n"] += nb
        self.parts[pid].append({"arrays": arrays, "valids": valids, "n": n})
        self.rows[pid] += n

    def free(self, pid: int) -> None:
        """Drop a partition's staged rows and release their reservation —
        callers free as they consume so peak staging tracks the live set."""
        self._mon.release(self._charged[pid])
        self._hold["n"] -= self._charged[pid]
        self._charged[pid] = 0
        self.parts[pid] = []
        self.rows[pid] = 0

    def charged(self, pid: int) -> int:
        """Staged bytes for one partition — host bytes, but a faithful
        estimate of the device bytes a full reload would pin (from_host
        pads only up to the next capacity rung)."""
        return self._charged[pid]

    def _host_columns(self, pid: int):
        """The partition's rows as contiguous host columns. Compacts the
        chunk list in place on first use (same bytes, one chunk) so
        repeated run/chunk iteration doesn't re-concatenate."""
        chunks = self.parts[pid]
        if len(chunks) > 1:
            arrays = {
                name: np.concatenate([c["arrays"][name] for c in chunks])
                for name in self.schema.names
            }
            valids = {
                name: np.concatenate([c["valids"][name] for c in chunks])
                for name in self.schema.names
            }
            self.parts[pid] = [
                {"arrays": arrays, "valids": valids, "n": self.rows[pid]}
            ]
        c = self.parts[pid][0]
        return c["arrays"], c["valids"]

    def reload(self, pid: int) -> Batch | None:
        chunks = self.parts[pid]
        if not chunks:
            return None
        n = self.rows[pid]
        arrays, valids = self._host_columns(pid)
        return from_host(self.schema, arrays, valids, capacity=_pow2(n))

    def reload_runs(self, pid: int, rows_per: int):
        """Yield the partition's rows as device batches of at most
        ``rows_per`` rows — the bounded-reload primitive behind the hybrid
        join's sorted runs and probe chunks. Capacities snap to the shape
        ladder so per-run kernels are shared across partitions (and
        queries); iteration order is deterministic, so a second pass sees
        the same chunk boundaries."""
        n = self.rows[pid]
        if n == 0:
            return
        if rows_per >= n:
            yield self.reload(pid)
            return
        arrays, valids = self._host_columns(pid)
        cap = _pow2(rows_per)
        for s in range(0, n, rows_per):
            e = min(n, s + rows_per)
            yield from_host(
                self.schema,
                {k: v[s:e] for k, v in arrays.items()},
                {k: v[s:e] for k, v in valids.items()},
                capacity=cap,
            )

    def extract(self, pid: int, sels) -> list[dict]:
        """Remove selected rows from a partition's staged chunks (``sels``:
        one bool array per chunk, parallel to the staging order) and return
        them as chunk dicts. The staging charge is re-measured so the
        accounting follows the surviving rows."""
        chunks = self.parts[pid]
        removed, kept = [], []
        for c, sel in zip(chunks, sels):
            nr = int(sel.sum())
            if nr == 0:
                kept.append(c)
                continue
            keep = ~sel
            removed.append({
                "arrays": {k: v[sel] for k, v in c["arrays"].items()},
                "valids": {k: v[sel] for k, v in c["valids"].items()},
                "n": nr,
            })
            nk = int(keep.sum())
            if nk:
                kept.append({
                    "arrays": {k: v[keep] for k, v in c["arrays"].items()},
                    "valids": {k: v[keep] for k, v in c["valids"].items()},
                    "n": nk,
                })
        if removed:
            self.parts[pid] = kept
            freed = self._charged[pid]
            nb = int(sum(
                sum(a.nbytes for a in c["arrays"].values())
                + sum(v.nbytes for v in c["valids"].values())
                for c in kept))
            self.rows[pid] = sum(c["n"] for c in kept)
            self._mon.release(freed - nb)
            self._hold["n"] -= freed - nb
            self._charged[pid] = nb
        return removed


def stage_batch(batch: Batch, schema: Schema, pids: np.ndarray | None,
                parts: HostPartitions):
    """Move a device batch's live rows to host partitions. `pids` is the
    per-row partition id (host numpy, dead rows ignored)."""
    mask = np.asarray(batch.mask)
    for pid in range(parts.nparts):
        sel = mask if pids is None else (mask & (pids == pid))
        n = int(sel.sum())
        if n == 0:
            continue
        arrays = {}
        valids = {}
        for name, col in zip(schema.names, batch.cols):
            arrays[name] = np.asarray(col.data)[sel]
            valids[name] = np.asarray(col.valid)[sel]
        parts.append_host(pid, arrays, valids, n)


class ReplayOp(Operator):
    """Re-emits already-spooled device tiles — glue that lets an in-memory
    operator hand its buffered input to the external variant it spills into
    (the disk_spiller handoff, disk_spiller.go:103)."""

    def __init__(self, tiles, schema: Schema, dictionaries):
        super().__init__()
        self.tiles = list(tiles)
        self.output_schema = schema
        self.dictionaries = dict(dictionaries)
        self._i = 0

    def init(self):
        super().init()
        self._i = 0

    def _next(self):
        if self._i >= len(self.tiles):
            return None
        b = self.tiles[self._i]
        self._i += 1
        return b


class ChainOp(ReplayOp):
    """Replays spooled tiles, then continues pulling from the live input —
    the handoff when an operator spills mid-stream. Does NOT re-init the
    live input (it is mid-stream by construction)."""

    def __init__(self, tiles, schema: Schema, dictionaries, rest: Operator):
        super().__init__(tiles, schema, dictionaries)
        self.rest = rest

    def _next(self):
        b = super()._next()
        return self.rest.next_batch() if b is None else b



def _array_key(a):
    """Content key for a small baked-in table (dictionary ranks/hashes) so
    spill kernels can share through the process-global kernel cache. Spill
    operators are constructed at RUNTIME (SortOp/AggregateOp/HashJoinOp
    hand off mid-query), so without a content key every spilling run of a
    cached plan would re-trace identical kernels."""
    if a is None:
        return None
    a = np.asarray(a)
    return (str(a.dtype), a.shape, a.tobytes())


def make_bucket_fn(schema: Schema, keys, tables, nparts: int,
                   with_hash: bool = False):
    """Jitted per-row partition id from the key columns' 64-bit hash —
    THE Grace partition function, shared by the external join and
    aggregation so their partitioning can never diverge. With
    ``with_hash`` the full hash rides along (one dispatch), for skew
    sampling and heavy-hitter routing keyed on the same value."""
    def fn(b: Batch):
        cols = [b.cols[i] for i in keys]
        types = [schema.types[i] for i in keys]
        h = hash_columns(cols, types, tables or None)
        pid = (h % np.uint64(nparts)).astype(jnp.int32)
        return (pid, h) if with_hash else pid

    key = dispatch.kernel_key(
        "grace_bucket", schema, tuple(keys), nparts, with_hash,
        tuple(sorted((i, _array_key(t)) for i, t in (tables or {}).items())),
    )
    return dispatch.jit(fn, key=key)


# ---------------------------------------------------------------------------
# Grace hash join


class GraceHashJoinOp(OneInputOperator):
    """External hash join: both sides hash-partition into P buckets staged
    on the host; partition pairs join in-memory (hash_based_partitioner.go
    semantics), with two escape hatches where the reference would recurse:

    - Heavy-hitter routing: build-side key hashes are reservoir-sampled
      while staging (the kv/loadstats request-reservoir idiom). Keys
      owning more than ``sql.distsql.grace_skew_frac`` of the sample keep
      their build rows RESIDENT on device, and probe rows carrying those
      hashes route to a dedicated hot lane that streams against the
      resident table — instead of the whole hot key piling into one
      partition. Routing is hash-consistent on both sides, so every join
      type stays exact: a probe row's complete match set lives wherever
      its hash was routed (collisions route together; the join kernel
      applies the exact key predicate).
    - Hybrid degrade: a partition whose build side alone exceeds workmem
      (the budget says so up front — no device OOM retry involved)
      reloads its build as budget-sized sorted runs and merge-probes each
      run (ops.merge_join's exact-key order); resident partitions keep
      the one-shot hash path. Probe sides reload in budget-sized chunks
      either way, so device footprint is bounded by the budget, not by
      the largest partition."""

    def __init__(self, probe: Operator, build: Operator,
                 probe_keys, build_keys, spec, nparts: int = 8):
        super().__init__(probe)
        self.build = build
        self.probe_keys = tuple(probe_keys)
        self.build_keys = tuple(build_keys)
        self.spec = spec
        self.nparts = nparts
        self.output_schema = join_ops.join_output_schema(
            probe.output_schema, build.output_schema, spec
        )
        self.dictionaries = dict(probe.dictionaries)
        if spec.join_type not in ("semi", "anti"):
            off = len(probe.output_schema)
            for i, d in build.dictionaries.items():
                self.dictionaries[off + i] = d
        # host-side string bridges (same as HashJoinOp)
        self.probe_hash_tables = {}
        self.build_hash_tables = {}
        self.build_code_remaps = {}
        for pos, (pk, bk) in enumerate(zip(self.probe_keys, self.build_keys)):
            pt = probe.output_schema.types[pk]
            if pt.family is Family.STRING:
                pd_ = probe.dictionaries[pk]
                bd = build.dictionaries[bk]
                self.probe_hash_tables[pk] = pd_.hashes
                self.build_hash_tables[bk] = bd.hashes
                # crlint: allow-mem-accounting(dictionary code remap: one int32 per distinct build-side string, bounded by dictionary size)
                self.build_code_remaps[pos] = np.array(
                    [pd_.code_of(str(v)) for v in bd.values], dtype=np.int32
                )

    def children(self):
        return [self.child, self.build]

    def init(self):
        self.build.init()
        super().init()
        self._partitioned = False
        self._gen = None
        self._alloc = None
        self._hot_build = None
        self._hot_index = None
        self._hot_bytes = 0
        if hasattr(self, "_bucket_probe"):
            return
        self._bucket_probe = make_bucket_fn(
            self.child.output_schema, self.probe_keys,
            self.probe_hash_tables, self.nparts, with_hash=True,
        )
        self._bucket_build = make_bucket_fn(
            self.build.output_schema, self.build_keys,
            self.build_hash_tables, self.nparts, with_hash=True,
        )
        import dataclasses

        from ..ops import merge_join as mj

        pschema = self.child.output_schema
        bschema = self.build.output_schema
        pkeys, bkeys, spec = self.probe_keys, self.build_keys, self.spec
        pht = self.probe_hash_tables or None
        bht = self.build_hash_tables or None
        remaps = self.build_code_remaps or None
        tkey = (
            tuple(sorted((i, _array_key(t))
                         for i, t in self.probe_hash_tables.items())),
            tuple(sorted((i, _array_key(t))
                         for i, t in self.build_hash_tables.items())),
            tuple(sorted((i, _array_key(t))
                         for i, t in self.build_code_remaps.items())),
        )

        def hj_raw(p, build, index, out_cap, jt):
            sp = dataclasses.replace(spec, join_type=jt)
            return join_ops.hash_join_general(
                p, pschema, pkeys, build, bschema, bkeys, sp, out_cap,
                pht, bht, remaps, index=index,
            )

        self._hj_fn = dispatch.jit(
            hj_raw, static_argnames=("out_cap", "jt"),
            key=dispatch.kernel_key(
                "grace_hashprobe", pschema, bschema, pkeys, bkeys, spec,
                tkey),
        )

        def hindex_raw(b):
            return join_ops.build_index(b, bschema, bkeys, bht)

        self._hindex_fn = dispatch.jit(
            hindex_raw,
            key=dispatch.kernel_key("grace_hashindex", bschema, bkeys,
                                    tkey),
        )

        # oversized partitions degrade to sorted-run merge probing: the
        # run index orders each reloaded build run by the EXACT composite
        # key (ops.merge_join), probe chunks binary-search it
        pranks, branks = mj.rank_tables_for(
            pschema, pkeys, self.child.dictionaries,
            bkeys, self.build.dictionaries,
        )
        rkey = (tuple(_array_key(r) for r in pranks),
                tuple(_array_key(r) for r in branks))

        def mindex_raw(b):
            return mj.build_merge_index(b, bschema, bkeys, branks)

        self._mindex_fn = dispatch.jit(
            mindex_raw,
            key=dispatch.kernel_key("grace_mergeindex", bschema, bkeys,
                                    rkey),
        )

        def mj_raw(p, b, index, out_cap, jt):
            sp = dataclasses.replace(spec, join_type=jt)
            return mj.merge_join(
                p, pschema, pkeys, b, bschema, bkeys, sp, out_cap,
                pranks, branks, build_index=index,
            )

        self._mj_fn = dispatch.jit(
            mj_raw, static_argnames=("out_cap", "jt"),
            key=dispatch.kernel_key(
                "grace_mergeprobe", pschema, bschema, pkeys, bkeys, spec,
                rkey),
        )

    def _partition_all(self):
        import random as _random

        from ..utils import metric, settings

        pschema = self.child.output_schema
        bschema = self.build.output_schema
        # the probe side gets one extra lane (index nparts): rows carrying
        # a heavy-hitter hash detected from the build sample
        pparts = HostPartitions(pschema, self.nparts + 1)
        bparts = HostPartitions(bschema, self.nparts)
        size = int(settings.get("sql.distsql.grace_skew_sample"))
        frac = float(settings.get("sql.distsql.grace_skew_frac"))
        # fixed seed: a re-run of the same query samples identically
        rng = _random.Random(0x5CE7A11)
        samples: list[int] = []
        seen = 0
        bhashes: list[list[np.ndarray]] = [[] for _ in range(self.nparts)]
        while True:
            b = self.build.next_batch()
            if b is None:
                break
            pids_d, h_d = self._bucket_build(b)
            pids, h = np.asarray(pids_d), np.asarray(h_d)
            mask = np.asarray(b.mask)
            if size > 0 and frac > 0:
                # reservoir-sample live build key hashes (loadstats'
                # algorithm-R request reservoir, applied to join keys)
                for hv in h[mask]:
                    seen += 1
                    if len(samples) < size:
                        samples.append(int(hv))
                    else:
                        j = rng.randrange(seen)
                        if j < size:
                            samples[j] = int(hv)
            for pid in range(self.nparts):
                sel = mask & (pids == pid)
                n = int(sel.sum())
                if n == 0:
                    continue
                arrays = {name: np.asarray(col.data)[sel]
                          for name, col in zip(bschema.names, b.cols)}
                valids = {name: np.asarray(col.valid)[sel]
                          for name, col in zip(bschema.names, b.cols)}
                bparts.append_host(pid, arrays, valids, n)
                bhashes[pid].append(h[sel])
        hot = self._detect_hot(samples, frac, bparts, bhashes)
        while True:
            p = self.child.next_batch()
            if p is None:
                break
            pids_d, h_d = self._bucket_probe(p)
            pids = np.asarray(pids_d)
            if hot is not None:
                routed = np.isin(np.asarray(h_d), hot)
                n_hot = int((routed & np.asarray(p.mask)).sum())
                if n_hot:
                    metric.GRACE_JOIN_SKEW_ROUTED.inc(n_hot)
                pids = np.where(routed, self.nparts, pids)
            stage_batch(p, pschema, pids, pparts)
        self._pparts = pparts
        self._bparts = bparts
        self._partitioned = True

    def _detect_hot(self, samples, frac, bparts, bhashes):
        """Heavy-hitter hashes from the build-side reservoir -> resident
        device build table (extracted out of the staged partitions).
        Returns the sorted hot hash array for probe routing, or None."""
        from ..utils import log, settings

        from .memory import batch_bytes

        if not samples or frac <= 0:
            return None
        thr = max(2, int(frac * len(samples)))
        counts: dict[int, int] = {}
        for hv in samples:
            counts[hv] = counts.get(hv, 0) + 1
        hot_list = sorted(h for h, c in counts.items() if c >= thr)
        if not hot_list:
            return None
        hot = np.array(hot_list, dtype=np.uint64)
        sels = {pid: [np.isin(ch, hot) for ch in bhashes[pid]]
                for pid in range(self.nparts)}
        hot_rows = sum(int(s.sum()) for ss in sels.values() for s in ss)
        if hot_rows == 0:
            return None
        # residency check BEFORE extraction: the hot table must fit well
        # inside workmem, or routing would just move the oversize on-device
        budget = int(settings.get("sql.distsql.workmem_bytes"))
        total_rows = sum(bparts.rows) or 1
        total_bytes = sum(bparts.charged(pid)
                          for pid in range(self.nparts))
        est = int(total_bytes * hot_rows / total_rows)
        if est > budget // 4:
            log.info(log.SQL_EXEC,
                     "grace join skew: hot build side too large to pin",
                     hot_keys=len(hot_list), est_bytes=est)
            return None
        chunks = []
        for pid in range(self.nparts):
            chunks.extend(bparts.extract(pid, sels[pid]))
        bschema = self.build.output_schema
        arrays = {name: np.concatenate([c["arrays"][name] for c in chunks])
                  for name in bschema.names}
        valids = {name: np.concatenate([c["valids"][name] for c in chunks])
                  for name in bschema.names}
        n = sum(c["n"] for c in chunks)
        self._hot_build = from_host(bschema, arrays, valids,
                                    capacity=_pow2(n))
        self._hot_index = self._hindex_fn(self._hot_build)
        self._hot_bytes = batch_bytes(self._hot_build)
        self._alloc.reserve(self._hot_bytes, force=True)
        log.info(log.SQL_EXEC, "grace join skew: heavy hitters pinned",
                 hot_keys=len(hot_list), rows=n)
        return hot

    @staticmethod
    def _rows_per(nbytes: int, rows: int, budget: int) -> int:
        """Rows per bounded reload so one run/chunk stays inside the
        budget (floored: tiny budgets still make progress tile-at-a-time)."""
        if rows == 0:
            return 1
        per_row = max(1, nbytes // rows)
        return max(1024, int(budget // per_row))

    def _probe_stream(self, pid, rows_per, build, index):
        """Probe one partition in bounded chunks against a COMPLETE build
        (resident partition or the pinned hot table): every chunk's match
        set is fully present, so all join types are exact per chunk."""
        from .memory import batch_bytes

        jt = self.spec.join_type
        out_cap = 0
        for chunk in self._pparts.reload_runs(pid, rows_per):
            nb = batch_bytes(chunk)
            self._alloc.reserve(nb, force=True)
            try:
                out_cap = max(out_cap, _pow2(chunk.capacity))
                while True:
                    out, total = self._hj_fn(chunk, build, index,
                                             out_cap=out_cap, jt=jt)
                    if int(total) <= out_cap:
                        break
                    out_cap = _pow2(int(total) + 1)
                yield out
            finally:
                self._alloc.release(nb)

    def _probe_hot(self, budget):
        hot_pid = self.nparts
        try:
            rows_per = self._rows_per(self._pparts.charged(hot_pid),
                                      self._pparts.rows[hot_pid], budget)
            yield from self._probe_stream(hot_pid, rows_per,
                                          self._hot_build, self._hot_index)
        finally:
            self._pparts.free(hot_pid)
            self._alloc.release(self._hot_bytes)
            self._hot_bytes = 0
            self._hot_build = self._hot_index = None

    def _probe_resident(self, pid, budget):
        from ..coldata.batch import empty_batch

        from .memory import batch_bytes

        build = self._bparts.reload(pid)
        if build is None:
            build = empty_batch(self.build.output_schema, 1024)
        nb = batch_bytes(build)
        self._alloc.reserve(nb, force=True)
        try:
            index = self._hindex_fn(build)
            rows_per = self._rows_per(self._pparts.charged(pid),
                                      self._pparts.rows[pid], budget)
            yield from self._probe_stream(pid, rows_per, build, index)
        finally:
            self._alloc.release(nb)

    def _probe_runs(self, pid, budget):
        """Oversized partition: the budget (not an OOM retry) says the
        build side can't be resident, so it reloads as budget-sized sorted
        runs and each probe chunk binary-searches every run. Inner/left
        matches emit per run (runs are disjoint build rows — no dedup);
        probe-aligned verdicts (semi/anti/left-unmatched) OR-accumulate a
        per-chunk found mask across runs and resolve in a final pass."""
        from ..utils import log, metric

        from .memory import batch_bytes

        metric.GRACE_JOIN_MERGE_PARTS.inc()
        jt = self.spec.join_type
        rows_run = self._rows_per(self._bparts.charged(pid),
                                  self._bparts.rows[pid], budget)
        rows_chunk = self._rows_per(self._pparts.charged(pid),
                                    self._pparts.rows[pid], budget)
        log.info(log.SQL_EXEC,
                 "grace join partition exceeds workmem; merge-probing runs",
                 partition=pid, build_rows=self._bparts.rows[pid],
                 run_rows=rows_run)
        found: dict[int, jax.Array] = {}
        out_cap = 0
        for run in self._bparts.reload_runs(pid, rows_run):
            faults.fire("flow.spill.merge_probe")
            rb = batch_bytes(run)
            self._alloc.reserve(rb, force=True)
            try:
                index = self._mindex_fn(run)
                for ci, chunk in enumerate(
                        self._pparts.reload_runs(pid, rows_chunk)):
                    cb = batch_bytes(chunk)
                    self._alloc.reserve(cb, force=True)
                    try:
                        if jt in ("inner", "left"):
                            out_cap = max(out_cap, _pow2(chunk.capacity))
                            while True:
                                out, total = self._mj_fn(
                                    chunk, run, index, out_cap=out_cap,
                                    jt="inner")
                                if int(total) <= out_cap:
                                    break
                                out_cap = _pow2(int(total) + 1)
                            yield out
                        if jt != "inner":
                            m, _ = self._mj_fn(chunk, run, index,
                                               out_cap=chunk.capacity,
                                               jt="semi")
                            f = m.mask
                            found[ci] = (f if ci not in found
                                         else found[ci] | f)
                    finally:
                        self._alloc.release(cb)
            finally:
                self._alloc.release(rb)
        if jt == "inner":
            return
        # final probe-aligned pass over the same (deterministic) chunking
        from ..coldata.batch import empty_batch

        for ci, chunk in enumerate(
                self._pparts.reload_runs(pid, rows_chunk)):
            cb = batch_bytes(chunk)
            self._alloc.reserve(cb, force=True)
            try:
                f = found.get(ci)
                if f is None:
                    f = jnp.zeros((chunk.capacity,), jnp.bool_)
                if jt == "semi":
                    yield chunk.with_mask(f)
                elif jt == "anti":
                    yield chunk.with_mask(chunk.mask & ~f)
                else:  # left: unmatched rows null-extend via an empty run
                    unm = chunk.mask & ~f
                    empty = empty_batch(self.build.output_schema, 1024)
                    eidx = self._mindex_fn(empty)
                    out, _ = self._mj_fn(chunk.with_mask(unm), empty, eidx,
                                         out_cap=_pow2(chunk.capacity),
                                         jt="left")
                    yield out
            finally:
                self._alloc.release(cb)

    def _emit(self):
        from ..utils import settings

        from . import memory as flowmem

        if self._alloc is not None:
            self._alloc.release()
            self._alloc.close()
        self._alloc = flowmem.Allocator("grace join partition",
                                        stats=self.stats)
        self._partition_all()
        budget = int(settings.get("sql.distsql.workmem_bytes"))
        if self._hot_build is not None:
            yield from self._probe_hot(budget)
        for pid in range(self.nparts):
            try:
                if self._pparts.rows[pid] == 0:
                    continue
                if self._bparts.charged(pid) <= budget:
                    yield from self._probe_resident(pid, budget)
                else:
                    yield from self._probe_runs(pid, budget)
            finally:
                # free as we go: peak staging tracks the live partitions
                self._pparts.free(pid)
                self._bparts.free(pid)

    def _next(self):
        if self._gen is None:
            self._gen = self._emit()
        return next(self._gen, None)

    def close(self):
        super().close()
        self.build.close()
        self._gen = None
        self._hot_build = self._hot_index = None
        if getattr(self, "_alloc", None) is not None:
            self._alloc.release()
            self._alloc.close()
            self._alloc = None


# ---------------------------------------------------------------------------
# External sort


# crlint: allow-mem-accounting(tile-width device temp for order-preserving key packing; the owning batch is charged by its operator account)
def _primary_u64(batch: Batch, schema: Schema, key: sort_ops.SortKey,
                 rank_table=None) -> jax.Array:
    """Order-preserving uint64 of the primary sort key (NULL ordering
    folded in: null_key gets the top bit band)."""
    c = batch.cols[key.col]
    ops = sort_ops.order_keys(c.data, c.valid, key, schema.types[key.col],
                              rank_table)
    # order_keys returns leading 1-bit bool bands (null ordering, NaN
    # ordering) followed by the payload word(s). Fold the bands into the top
    # bits and range-partition on the FIRST payload word only — for
    # multi-word keys (BYTES wider than 8) this is order-preserving at
    # partition granularity: rows equal in the leading word stay in one
    # bucket, and the within-bucket sort uses the full key list.
    bands, payload = [], None
    for op in ops:
        if op.dtype == jnp.bool_:
            bands.append(op)
        else:
            payload = op
            break
    if payload is None:  # BOOL key: its one bool band IS the payload —
        # promote the bit to the top so the band right-shift below keeps it
        payload = bands.pop().astype(jnp.uint64) << np.uint64(63)
    u = jnp.zeros((batch.capacity,), jnp.uint64)
    shift = np.uint64(62)
    for op in bands:
        u = u | (op.astype(jnp.uint64) << shift)
        shift -= np.uint64(1)
    if payload.dtype in (jnp.float64, jnp.float32):
        f = payload.astype(jnp.float64)
        parts = jax.lax.bitcast_convert_type(f, jnp.uint32)
        p = (parts[..., 1].astype(jnp.uint64) << np.uint64(32)) | parts[
            ..., 0
        ].astype(jnp.uint64)
        neg = (p >> np.uint64(63)) != 0
        p = jnp.where(neg, ~p, p | np.uint64(1 << 63))
    elif payload.dtype == jnp.uint64:
        p = payload
    else:
        p = payload.astype(jnp.int64).astype(jnp.uint64) ^ np.uint64(1 << 63)
    # drop low bits to make room for the null/nan bands (ordering within
    # equal top bands preserved; only boundary granularity is affected)
    return u | (p >> np.uint64(64 - int(shift) - 1))


class ExternalSortOp(OneInputOperator):
    """External sort: range-partition rows by a uint64 of the primary key
    (quantile boundaries over staged samples), then sort each bucket with
    the full key list and emit buckets in order (external_sort.go role; the
    merge phase is bucket-ordered emission instead of a loser tree)."""

    def __init__(self, child: Operator, keys, budget_rows: int = 1 << 20,
                 nparts: int = 8):
        super().__init__(child)
        self.output_schema = child.output_schema
        self.keys = tuple(keys)
        self.budget_rows = budget_rows
        self.nparts = nparts
        self._staged = False

    def init(self):
        super().init()
        self._staged = False
        self._pid = 0
        if hasattr(self, "_u64_fn"):
            return
        schema = self.output_schema
        key = self.keys[0]
        rank_table = None
        if key.col in self.child.dictionaries:
            rank_table = self.child.dictionaries[key.col].ranks
        self._u64_fn = dispatch.jit(
            lambda b: _primary_u64(b, schema, key, rank_table),
            key=dispatch.kernel_key("extsort_u64", schema, key,
                                    _array_key(rank_table)),
        )
        rank_tables = {
            k.col: self.child.dictionaries[k.col].ranks
            for k in self.keys
            if k.col in self.child.dictionaries
        }
        keys = self.keys

        def sort_fn(b):
            return sort_ops.sort_batch(b, schema, keys, rank_tables)

        self._sort_fn = dispatch.jit(sort_fn, key=dispatch.kernel_key(
            "extsort_sort", schema, keys,
            tuple(sorted((c, _array_key(t))
                         for c, t in rank_tables.items())),
        ))

    def _stage_all(self):
        # pass 1: stage all rows + their primary u64 on the host
        chunks = []
        while True:
            b = self.child.next_batch()
            if b is None:
                break
            u = np.asarray(self._u64_fn(b))
            mask = np.asarray(b.mask)
            arrays = {
                name: np.asarray(c.data)[mask]
                for name, c in zip(self.output_schema.names, b.cols)
            }
            valids = {
                name: np.asarray(c.valid)[mask]
                for name, c in zip(self.output_schema.names, b.cols)
            }
            chunks.append((arrays, valids, u[mask]))
        total = sum(len(c[2]) for c in chunks)
        if total == 0:
            self._parts = None
            self._staged = True
            return
        from . import memory as flowmem

        # quantile boundaries over the staged u64s: the transient key
        # vector is 8 B/row over the whole staged input — charge it for
        # the split computation's lifetime
        with flowmem.staged("flow/spill-staging", 8 * total):
            allu = np.concatenate([c[2] for c in chunks])
            P = min(self.nparts, max(1, (total + self.budget_rows - 1)
                                     // self.budget_rows * 2))
            qs = np.quantile(allu, np.linspace(0, 1, P + 1)[1:-1])
            bounds = np.unique(qs.astype(np.uint64))
        parts = HostPartitions(self.output_schema, len(bounds) + 1)
        for arrays, valids, u in chunks:
            pids = np.searchsorted(bounds, u, side="right")
            for pid in range(parts.nparts):
                sel = pids == pid
                n = int(sel.sum())
                if n:
                    parts.append_host(
                        pid,
                        {k: v[sel] for k, v in arrays.items()},
                        {k: v[sel] for k, v in valids.items()},
                        n,
                    )
        self._parts = parts
        self._staged = True

    def _next(self):
        if not self._staged:
            self._stage_all()
        if self._parts is None:
            return None
        while self._pid < self._parts.nparts:
            b = self._parts.reload(self._pid)
            self._pid += 1
            if b is not None:
                return self._sort_fn(b)
        return None


# ---------------------------------------------------------------------------
# Grace external aggregation (external_hash_aggregator.go role) — also the
# external DISTINCT, which is aggregation with no aggregate functions


class GraceAggregateOp(Operator):
    """External aggregation over partial-STATE tiles: rows partition by
    group-key hash, so partitions are GROUP-DISJOINT — each merges and
    finalizes independently and streams out one batch at a time, bounding
    memory by the largest partition instead of the full group count
    (hash_based_partitioner.go recursion is unnecessary here because the
    merge stage re-aggregates: a skewed partition still shrinks to its
    distinct groups).

    Built by AggregateOp's spill handoff: `child` replays the spooled
    state tiles then continues the live partial stream (ChainOp)."""

    def __init__(self, child: Operator, agg_op, nparts: int = 8):
        super().__init__()
        # zero group keys never reach here (no-GROUP-BY plans use
        # ScalarAggregateOp); partitioning without keys would duplicate
        # every row into all partitions
        assert agg_op.num_keys > 0, "Grace aggregation needs group keys"
        self.child = child
        self.agg = agg_op  # the spilling AggregateOp (owns merge/finalize)
        self.nparts = nparts
        self.output_schema = agg_op.output_schema
        self.dictionaries = dict(agg_op.dictionaries)
        self.col_stats = dict(agg_op.col_stats)

    def children(self):
        return [self.child]

    def init(self):
        self._parts = None
        self._pid = 0
        self._initialized = True
        if hasattr(self, "_bucket"):
            return
        schema = self.agg.state_schema
        keys = tuple(range(self.agg.num_keys))
        tables = {
            pos: d.hashes
            for pos, d in self.agg.dictionaries.items()
            if pos < self.agg.num_keys
        }
        self._bucket = make_bucket_fn(schema, keys, tables, self.nparts)

    def _stage_all(self):
        from ..utils import log, metric

        parts = HostPartitions(self.agg.state_schema, self.nparts)
        n_tiles = 0
        while True:
            b = self.child.next_batch()
            if b is None:
                break
            n_tiles += 1
            pids = np.asarray(self._bucket(b))
            stage_batch(b, self.agg.state_schema, pids, parts)
        metric.EXTERNAL_AGG_SPILLS.inc()
        log.info(log.SQL_EXEC, "aggregation spilled to Grace partitions",
                 tiles=n_tiles, partitions=self.nparts,
                 rows=sum(parts.rows))
        self._parts = parts

    def _next(self):
        if self._parts is None:
            self._stage_all()
        while self._pid < self.nparts:
            pid = self._pid
            self._pid += 1
            batch = self._parts.reload(pid)
            self._parts.free(pid)  # free as we go (releases the staging charge)
            if batch is None:
                continue
            cap = batch.capacity
            merged, ng = self.agg._merge_fn((batch,), cap=cap)
            while int(ng) > cap:
                cap = _pow2(int(ng) + 1)
                merged, ng = self.agg._merge_fn((batch,), cap=cap)
            if self.agg.mode == "partial":
                return merged
            return self.agg._finalize_fn(merged)
        return None

    def close(self):
        self.child.close()
        self._parts = None
