"""Cross-host DistSQL — one query spanning processes over the DCN lane.

Reference shape (pkg/sql/distsql/server.go:616 SetupFlow +
pkg/sql/flowinfra/flow_registry.go:164): the gateway ships FlowSpecs to
remote nodes, each remote registers its flow under a FlowID, and stream
connections attach to registered flows by (flow_id, stream_id). Here:

- ``HostFlowServer`` extends the one-shot FlowServer with that registry:
  a SETUP_FLOW request carries serialized plan fragments (flow/wire.py),
  which build operators against the server's catalog and wait in the
  registry; a FLOW_STREAM request attaches to one (flow_id, stream_id)
  and streams its batches back (Arrow IPC framing from flow/dcn.py).
  Either arrival order works — streams wait for their setup briefly, the
  registry's ConnectInboundStream timeout discipline. A CANCEL_FLOW
  request tears down every registered entry of a flow (the gateway's
  CancelDeadFlows reduction) and poisons the flow id so late setups and
  stream-waits for it fail instead of lingering to TTL expiry.
- ``run_distributed_hosts`` is the gateway half (DistSQLPlanner.PlanAndRun
  reduction): split an aggregation plan into per-host partial fragments
  over table shards, SetupFlow each, attach the streams, and run the
  final aggregation locally over the inboxes' union. Both gateway
  runners execute under an end-to-end flow deadline
  (sql.distsql.flow_deadline_s): the first fragment failure cancels the
  flow on every reachable host and the query DEGRADES — re-planned onto
  the surviving hosts, or run single-host locally when none survive
  (distsql_degraded_queries counts these; EXPLAIN surfaces the policy).

The in-process SPMD mesh (parallel/planner.py) remains the intra-slice
plane; this module is the ACROSS-hosts plane stacked above it.
"""

from __future__ import annotations

import json
import socket
import threading
import time
import uuid

from ..coldata.types import Schema
from ..plan import spec as S
from ..utils import faults, locks, metric, retry
from ..utils.faults import InjectedFault
from . import wire
from .dcn import FlowInbox, FlowOutbox, _recv_msg, _send_msg
from .operator import Operator


class HostFlowServer:
    """SetupFlow + FlowStream + CancelFlow service over one socket."""

    def __init__(self, catalog, host: str = "127.0.0.1", port: int = 0,
                 stream_wait_s: float = 10.0, flow_ttl_s: float = 60.0):
        self.catalog = catalog
        # SO_REUSEADDR so back-to-back restarts rebind the port while the
        # previous incarnation's conns sit in TIME_WAIT
        self._srv = socket.create_server((host, port))
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.addr = self._srv.getsockname()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._conns: set = set()
        self._conns_lock = locks.lock("flow.host.conns")
        self._handlers: list[threading.Thread] = []
        # the flow registry: (flow_id, stream_id) -> (operator, expiry)
        # waiting for its stream connection (flow_registry.go:164); flows
        # no stream attaches to within flow_ttl_s are purged
        self._registry: dict[tuple[str, int], tuple[Operator, float]] = {}
        # flow_id -> poison expiry: cancelled flows reject late setups and
        # wake stream-waiters immediately instead of timing out
        self._cancelled: dict[str, float] = {}
        self._reg_lock = locks.condition("flow.host.registry")
        self.stream_wait_s = stream_wait_s
        self.flow_ttl_s = flow_ttl_s

    def registry_size(self) -> int:
        """Live registered streams (leak checks in chaos tests)."""
        with self._reg_lock:
            self._purge_expired_locked()
            return len(self._registry)

    def serve_background(self) -> "HostFlowServer":
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name="host-flow-server")
        self._thread.start()
        return self

    def _serve(self) -> None:
        try:
            self._srv.settimeout(0.2)
        except OSError:
            return  # close() raced serve_background

        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # close() raced the accept
            with self._conns_lock:
                if self._stop.is_set():
                    conn.close()
                    return
                self._conns.add(conn)
                t = threading.Thread(target=self._handle, args=(conn,),
                                     daemon=True)
                self._handlers.append(t)
            t.start()

    def _handle(self, conn: socket.socket) -> None:
        from ..utils import log

        try:
            msg = _recv_msg(conn)
            if msg is None:
                return
            req = json.loads(msg.decode("utf-8"))
            op = req.get("op")
            if op == "setup_flow":
                try:
                    self._setup_flow(req)
                except InjectedFault as e:
                    if e.kind == "drop":
                        raise  # sever: the gateway sees a dead host
                    _send_msg(conn, json.dumps({
                        "error": str(e)}).encode("utf-8"))
                    return
                except Exception as e:  # crlint: allow-broad-except(rejection reason is reported to the gateway over the wire)
                    # the gateway must learn WHY its fragment was rejected
                    # (unknown table, undecodable spec), not just see a
                    # closed socket
                    _send_msg(conn, json.dumps({
                        "error": f"{type(e).__name__}: {e}"
                    }).encode("utf-8"))
                    return
                _send_msg(conn, b'{"ok": true}')
            elif op == "flow_stream":
                self._flow_stream(conn, req)
            elif op == "cancel_flow":
                self._cancel_flow(conn, req)
            else:
                _send_msg(conn, b'{"error": "unknown op"}')
        except Exception as e:  # crlint: allow-broad-except(connection handler: error logged, socket severed below)
            log.warning(log.OPS, "host flow connection failed",
                        error=f"{type(e).__name__}: {e}")
        finally:
            conn.close()
            with self._conns_lock:
                self._conns.discard(conn)

    def _setup_flow(self, req: dict) -> None:
        from ..plan import builder as plan_builder

        faults.fire("flow.host.setup")
        flow_id = str(req["flow_id"])
        # build EVERY stream before registering ANY: a failure mid-request
        # must not leave half a flow in the registry
        built = {}
        for sid, spec in req["streams"].items():
            plan = wire.dec_plan(spec)
            built[(flow_id, int(sid))] = plan_builder.build(
                plan, self.catalog)
        deadline = time.time() + self.flow_ttl_s
        with self._reg_lock:
            self._purge_expired_locked()
            if flow_id in self._cancelled:
                # the gateway already gave up on this flow: registering now
                # would pin operators nothing will ever drain
                raise RuntimeError(f"flow {flow_id} was cancelled")
            for key, op in built.items():
                self._registry[key] = (op, deadline)
            self._reg_lock.notify_all()

    def _purge_expired_locked(self) -> None:
        """Drop flows no stream ever attached to (a crashed gateway must
        not pin operators forever — flow_registry.go's timeout on the
        setup side), and expire cancellation poison entries so a reused
        flow id eventually works again."""
        now = time.time()
        for key in [k for k, (_, dl) in self._registry.items() if dl < now]:
            del self._registry[key]
        for fid in [f for f, dl in self._cancelled.items() if dl < now]:
            del self._cancelled[fid]

    def _flow_stream(self, conn: socket.socket, req: dict) -> None:
        faults.fire("flow.host.stream")
        key = (str(req["flow_id"]), int(req["stream_id"]))
        deadline = time.time() + self.stream_wait_s
        with self._reg_lock:
            self._purge_expired_locked()
            while key not in self._registry:
                if key[0] in self._cancelled:
                    _send_msg(conn, b'{"error": "flow cancelled"}')
                    return
                left = deadline - time.time()
                if left <= 0:
                    _send_msg(conn, b'{"error": "no such flow"}')
                    return
                self._reg_lock.wait(timeout=left)
            op, _ = self._registry.pop(key)
        _send_msg(conn, b'{"ok": true}')
        FlowOutbox(op, conn).run()

    def _cancel_flow(self, conn: socket.socket, req: dict) -> None:
        flow_id = str(req["flow_id"])
        with self._reg_lock:
            self._purge_expired_locked()
            doomed = [k for k in self._registry if k[0] == flow_id]
            for k in doomed:
                del self._registry[k]
            self._cancelled[flow_id] = time.time() + self.flow_ttl_s
            # wake stream-waiters parked on this flow so they fail NOW
            self._reg_lock.notify_all()
        _send_msg(conn, json.dumps(
            {"ok": True, "removed": len(doomed)}).encode("utf-8"))

    def close(self) -> None:
        """Idempotent full teardown: stop accepting, sever accepted conns
        (unblocking handlers parked in recv or mid-stream), join the
        accept + handler threads, drop the registry. A closed server
        holds no port, no fd, and no thread."""
        self._stop.set()
        self._srv.close()
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
            handlers = list(self._handlers)
            self._handlers.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if (self._thread is not None
                and self._thread is not threading.current_thread()):
            self._thread.join(timeout=5)
        for t in handlers:
            if t is not threading.current_thread():
                t.join(timeout=5)
        with self._reg_lock:
            self._registry.clear()
            self._cancelled.clear()
            self._reg_lock.notify_all()


def _rpc_timeout_s() -> float:
    from ..utils import settings

    return settings.get("rpc.batch.deadline_s")


def setup_flow(addr, flow_id: str, streams: dict[int, S.PlanNode]) -> None:
    """Ship plan fragments to a host's registry (SetupFlowRequest).

    Transport failures retry with backoff under the RPC deadline —
    re-registering the same (flow_id, stream_id) keys is idempotent
    (the registry overwrites). Typed rejections surface immediately."""
    payload = json.dumps({
        "op": "setup_flow", "flow_id": flow_id,
        "streams": {sid: wire.enc_plan(p) for sid, p in streams.items()},
    }).encode("utf-8")

    def once():
        sock = socket.create_connection(tuple(addr),
                                        timeout=_rpc_timeout_s())
        try:
            _send_msg(sock, payload)
            msg = _recv_msg(sock)
            if msg is None:
                raise ConnectionError(f"setup_flow: {addr} severed stream")
            resp = json.loads(msg.decode("utf-8"))
            if not resp.get("ok"):
                raise RuntimeError(f"setup_flow rejected: {resp}")
        finally:
            sock.close()

    retry.call(once, retry.Backoff(max_attempts=3),
               retryable=_transport_error)


def attach_stream(addr, flow_id: str, stream_id: int,
                  schema: Schema) -> FlowInbox:
    """Attach to a registered flow's stream (FlowStream RPC). The
    handshake retries past transport failures; the returned inbox socket
    keeps its read timeout so a wedged host surfaces as socket.timeout
    in the puller instead of hanging the query forever."""

    def once():
        sock = socket.create_connection(tuple(addr),
                                        timeout=_rpc_timeout_s())
        try:
            _send_msg(sock, json.dumps({
                "op": "flow_stream", "flow_id": flow_id,
                "stream_id": stream_id,
            }).encode("utf-8"))
            msg = _recv_msg(sock)
            if msg is None:
                raise ConnectionError(f"flow_stream: {addr} severed stream")
            resp = json.loads(msg.decode("utf-8"))
            if not resp.get("ok"):
                raise RuntimeError(f"flow_stream rejected: {resp}")
        except BaseException:
            sock.close()
            raise
        return FlowInbox(sock, schema)

    return retry.call(once, retry.Backoff(max_attempts=3),
                      retryable=_transport_error)


def cancel_flow(addr, flow_id: str) -> int:
    """Tear down every registered entry of flow_id on one host (the
    CancelDeadFlows RPC reduction). Best-effort single attempt — the
    host may be the one that died. Returns entries removed (0 when the
    host is unreachable)."""
    try:
        sock = socket.create_connection(tuple(addr), timeout=1.0)
    except OSError:
        return 0
    try:
        _send_msg(sock, json.dumps(
            {"op": "cancel_flow", "flow_id": flow_id}).encode("utf-8"))
        msg = _recv_msg(sock)
        if msg is None:
            return 0
        resp = json.loads(msg.decode("utf-8"))
        removed = int(resp.get("removed", 0))
        if removed:
            metric.DIST_FLOWS_CANCELLED.inc(removed)
        return removed
    except (OSError, ConnectionError, ValueError):
        return 0
    finally:
        sock.close()


def _transport_error(e: BaseException) -> bool:
    """Wire-level failures only; typed rejections (RuntimeError) surface."""
    return isinstance(e, (ConnectionError, socket.timeout, TimeoutError,
                          OSError))


def probe_host(addr, timeout_s: float = 0.5) -> bool:
    """Is anything listening at addr? (the gateway's liveness check when
    deciding which hosts survive a mid-flow failure)."""
    try:
        sock = socket.create_connection(tuple(addr), timeout=timeout_s)
    except OSError:
        return False
    sock.close()
    return True


def _retryable_failure(e: BaseException | None) -> bool:
    """Walk the cause chain: did this query die of a TRANSIENT distributed
    failure (drop/timeout/injected fault) rather than a planning or data
    error? QueryError wraps the operator failure with __cause__ intact."""
    seen: set[int] = set()
    while e is not None and id(e) not in seen:
        seen.add(id(e))
        if retry.is_retryable(e):
            return True
        e = e.__cause__ if e.__cause__ is not None else e.__context__
    return False


def _cancel_everywhere(host_addrs: list, flow_id: str) -> None:
    for addr in host_addrs:
        cancel_flow(addr, flow_id)


def plan_host_fragments(plan: S.PlanNode, n_hosts: int):
    """Split an Aggregate(complete) over a scan chain into per-host partial
    fragments + the gateway's final-stage recipe.

    Returns (fragments, (group_cols, aggs)) where fragments[i] is the plan
    to ship to host i; the caller derives the final stage's base schema
    from plan.input. Raises TypeError for plans the host distributor does
    not cover (the caller falls back to local execution, exactly like the
    reference's distSQL support checks)."""
    if not isinstance(plan, S.Aggregate) or plan.mode != "complete":
        raise TypeError("host distribution covers Aggregate(complete) roots")
    frags = [
        S.Aggregate(
            _shard_scans(plan.input, i, n_hosts), plan.group_cols,
            plan.aggs, mode="partial",
        )
        for i in range(n_hosts)
    ]
    return frags, (plan.group_cols, plan.aggs)


def _shard_scans(p: S.PlanNode, i: int, n: int) -> S.PlanNode:
    if isinstance(p, S.TableScan):
        if p.shard is not None:
            raise TypeError("scan already sharded")
        return S.TableScan(p.table, p.columns, shard=(i, n))
    if isinstance(p, S.Filter):
        return S.Filter(_shard_scans(p.input, i, n), p.predicate)
    if isinstance(p, S.Project):
        return S.Project(_shard_scans(p.input, i, n), p.exprs, p.names,
                         p.dict_overrides)
    raise TypeError(
        f"host distribution cannot shard through {type(p).__name__}"
    )


def run_distributed_hosts(plan: S.PlanNode, catalog, host_addrs: list,
                          deadline_s: float | None = None):
    """Gateway execution: one partial fragment per host, final agg here.

    The fragment count equals the host count; stream ids are 0..n-1 under
    one fresh flow id (the FlowID/StreamID pairing of api.proto). Runs
    under the flow deadline with cancel-on-failure + degradation: a
    transient fragment failure cancels the flow everywhere, probes which
    hosts still answer, and re-plans onto the survivors — or runs the
    whole plan locally when none do."""
    from ..utils import log, settings

    if deadline_s is None:
        deadline_s = settings.get("sql.distsql.flow_deadline_s")
    try:
        return _run_hosts_once(plan, catalog, host_addrs, deadline_s)
    except Exception as e:
        if not _retryable_failure(e):
            raise
        survivors = [a for a in host_addrs if probe_host(a)]
        metric.DIST_DEGRADED.inc()
        if survivors and len(survivors) < len(host_addrs):
            log.warning(log.OPS, "distributed agg degraded to survivors",
                        hosts=len(host_addrs), survivors=len(survivors),
                        error=f"{type(e).__name__}: {e}")
            return _run_hosts_once(plan, catalog, survivors, deadline_s)
        # every host still answers (a transient blip we already retried
        # through) or none do: the local plan is the only safe harbor
        log.warning(log.OPS, "distributed agg degraded to local execution",
                    hosts=len(host_addrs),
                    error=f"{type(e).__name__}: {e}")
        return _run_local(plan, catalog)


def _run_local(plan: S.PlanNode, catalog):
    from ..plan import builder as plan_builder
    from .runtime import run_operator

    return run_operator(plan_builder.build(plan, catalog))


def _run_hosts_once(plan: S.PlanNode, catalog, host_addrs: list,
                    deadline_s: float):
    from ..flow import operators as ops
    from ..plan import builder as plan_builder
    from .runtime import run_operator

    frags, (group_cols, aggs) = plan_host_fragments(plan, len(host_addrs))
    flow_id = uuid.uuid4().hex[:12]
    # the partial fragments' OUTPUT schema (the state layout) — build one
    # locally to learn it; also the base schema the final stage needs
    probe_op = plan_builder.build(frags[0], catalog)
    state_schema = probe_op.output_schema
    base_schema = plan_builder.build(plan.input, catalog).output_schema

    inboxes: list[FlowInbox] = []
    try:
        for i, (addr, frag) in enumerate(zip(host_addrs, frags)):
            setup_flow(addr, flow_id, {i: frag})
        for i, addr in enumerate(host_addrs):
            inbox = attach_stream(addr, flow_id, i, state_schema)
            inbox.sock.settimeout(deadline_s)
            inboxes.append(inbox)
        # unordered fan-in with one puller thread per host: remote hosts
        # stream concurrently instead of draining one at a time
        sync = ops.ParallelUnorderedSyncOp(tuple(inboxes))
        final = ops.AggregateOp(sync, group_cols, aggs, mode="final",
                                input_schema=base_schema)
        return run_operator(final)
    except Exception:
        # first fragment failure: tear down the whole flow — no remote
        # registry entry may outlive the query it belonged to
        _cancel_everywhere(host_addrs, flow_id)
        raise
    finally:
        for inbox in inboxes:
            try:
                inbox.sock.close()
            except OSError:
                pass


# -- cross-host hash-repartitioned joins ------------------------------------
#
# The HashRouter-over-DCN step (colflow/routers.go:420 + colrpc): every
# host scans its shard of BOTH join sides and hash-partitions rows to P
# consumer streams; peer p joins partition p and streams the joined rows
# to the gateway. Co-partitioning makes each partition's join exact.
#
# stream-id layout under one flow_id (execinfrapb StreamEndpointSpec):
#   scatter probe h->p : 1000 + h*P + p
#   scatter build h->p : 2000 + h*P + p
#   joined partition p : 3000 + p


def _sid_scatter(side: str, h: int, p: int, n: int) -> int:
    return (1000 if side == "probe" else 2000) + h * n + p


def _sid_join(p: int) -> int:
    return 3000 + p


def plan_host_join(plan: S.HashJoin, addrs: list, flow_id: str, catalog):
    """Fragments for a hash-repartitioned cross-host join.

    Returns (scatter_frags, join_frags): scatter_frags[h] is the
    {stream_id: plan} dict to register on host h (2*P bucket streams over
    its shards); join_frags[p] is host p's join fragment — a HashJoin
    whose inputs are StreamUnions of RemoteStreams from every host."""
    from ..plan.distribute import schema_of

    n = len(addrs)
    if not isinstance(plan, S.HashJoin):
        raise TypeError("plan_host_join covers HashJoin roots")
    probe_schema = schema_of(plan.probe, catalog)
    build_schema = schema_of(plan.build, catalog)
    scatter_frags: list[dict[int, S.PlanNode]] = []
    for h in range(n):
        streams: dict[int, S.PlanNode] = {}
        probe_shard = _shard_scans(plan.probe, h, n)
        build_shard = _shard_scans(plan.build, h, n)
        for p in range(n):
            streams[_sid_scatter("probe", h, p, n)] = S.HashBucket(
                probe_shard, plan.probe_keys, n, p)
            streams[_sid_scatter("build", h, p, n)] = S.HashBucket(
                build_shard, plan.build_keys, n, p)
        scatter_frags.append(streams)
    join_frags: list[S.PlanNode] = []
    for p in range(n):
        probe_in = S.StreamUnion(tuple(
            S.RemoteStream(tuple(addrs[h]), flow_id,
                           _sid_scatter("probe", h, p, n), probe_schema)
            for h in range(n)))
        build_in = S.StreamUnion(tuple(
            S.RemoteStream(tuple(addrs[h]), flow_id,
                           _sid_scatter("build", h, p, n), build_schema)
            for h in range(n)))
        join_frags.append(S.HashJoin(probe_in, build_in, plan.probe_keys,
                                     plan.build_keys, plan.spec))
    return scatter_frags, join_frags


def run_distributed_join(plan: S.HashJoin, catalog, host_addrs: list,
                         deadline_s: float | None = None):
    """Gateway execution of a hash-repartitioned cross-host join, under
    the same deadline + cancel + degradation discipline as
    run_distributed_hosts: a transient failure cancels the flow on every
    reachable host, then the join re-plans onto the surviving hosts (the
    shard/bucket layout re-derives from the new host count) or falls
    back to local single-host execution."""
    from ..utils import log, settings

    if deadline_s is None:
        deadline_s = settings.get("sql.distsql.flow_deadline_s")
    try:
        return _run_join_once(plan, catalog, host_addrs, deadline_s)
    except Exception as e:
        if not _retryable_failure(e):
            raise
        survivors = [a for a in host_addrs if probe_host(a)]
        metric.DIST_DEGRADED.inc()
        if survivors and len(survivors) < len(host_addrs):
            log.warning(log.OPS, "distributed join degraded to survivors",
                        hosts=len(host_addrs), survivors=len(survivors),
                        error=f"{type(e).__name__}: {e}")
            return _run_join_once(plan, catalog, survivors, deadline_s)
        log.warning(log.OPS, "distributed join degraded to local execution",
                    hosts=len(host_addrs),
                    error=f"{type(e).__name__}: {e}")
        return _run_local(plan, catalog)


def _run_join_once(plan: S.HashJoin, catalog, host_addrs: list,
                   deadline_s: float):
    """Setup order matters: every scatter fragment registers before any
    join fragment's streams attach (the registry's stream-wait covers
    races). The gateway unions the P joined-partition streams."""
    from ..flow import operators as ops
    from ..plan import builder as plan_builder
    from .runtime import run_operator

    flow_id = uuid.uuid4().hex[:12]
    scatter_frags, join_frags = plan_host_join(
        plan, host_addrs, flow_id, catalog)
    inboxes: list[FlowInbox] = []
    try:
        for addr, streams in zip(host_addrs, scatter_frags):
            setup_flow(addr, flow_id, streams)
        # learn the joined schema without initializing (RemoteStream
        # attaches only at init)
        out_schema = plan_builder.build(join_frags[0],
                                        catalog).output_schema
        for p, addr in enumerate(host_addrs):
            setup_flow(addr, flow_id, {_sid_join(p): join_frags[p]})
        for p, addr in enumerate(host_addrs):
            inbox = attach_stream(addr, flow_id, _sid_join(p), out_schema)
            inbox.sock.settimeout(deadline_s)
            inboxes.append(inbox)
        sync = ops.ParallelUnorderedSyncOp(tuple(inboxes))
        return run_operator(sync)
    except Exception:
        _cancel_everywhere(host_addrs, flow_id)
        raise
    finally:
        for inbox in inboxes:
            try:
                inbox.sock.close()
            except OSError:
                pass


def _explain_degradation(n_hosts: int) -> str:
    from ..utils import settings

    return (
        f"fault policy: flow deadline "
        f"{settings.get('sql.distsql.flow_deadline_s'):g}s; on fragment "
        f"failure cancel flow on all {n_hosts} hosts, re-plan onto "
        f"survivors or run locally (distsql_degraded_queries)"
    )


def explain_host_join(plan: S.HashJoin, n_hosts: int) -> list[str]:
    """EXPLAIN (DISTSQL) lines for the repartitioned join stages."""
    out = []
    for h in range(n_hosts):
        out.append(
            f"host {h}: scan shard {h}/{n_hosts} of both sides, "
            f"hash-repartition into {n_hosts} bucket streams per side "
            f"(HashRouter over DCN)"
        )
    for p in range(n_hosts):
        out.append(
            f"host {p}: join partition {p} over {n_hosts} probe + "
            f"{n_hosts} build inbound streams"
        )
    out.append(f"gateway: union {n_hosts} joined-partition streams")
    out.append(_explain_degradation(n_hosts))
    return out


def explain_hosts(plan: S.PlanNode, n_hosts: int) -> list[str]:
    """EXPLAIN (DISTSQL) lines for the cross-host stages."""
    frags, (group_cols, aggs) = plan_host_fragments(plan, n_hosts)
    out = []
    for i, f in enumerate(frags):
        out.append(
            f"remote host {i}: partial aggregation over shard {i}/{n_hosts}"
            f" (streams via FlowStream id {i})"
        )
    out.append(
        f"gateway: final aggregation over {n_hosts} inbound streams"
    )
    out.append(_explain_degradation(n_hosts))
    return out
