"""Interactive SQL shell — the `cockroach sql` / demo analog (layer 1).

Reference: pkg/cli wires cobra commands over a server connection
(`cockroach sql`, `cockroach demo` boots an in-memory cluster). Here the
shell runs an in-process Session over the KV engine — the demo shape:

    python -m cockroach_tpu.cli                 # REPL
    python -m cockroach_tpu.cli -e "select 1"   # one-shot
    python -m cockroach_tpu.cli -f script.sql   # file
    python -m cockroach_tpu.cli --demo-tpch 0.01  # preload TPC-H tables

Meta commands: \\d (tables), \\timing, \\q. Statements end with ';'.
"""

from __future__ import annotations

import argparse
import sys
import time


def _fmt_value(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def render_table(res: dict) -> str:
    """psql-style table of a result dict."""
    if not isinstance(res, dict):
        return str(res)
    if not res:
        return "(no columns)"
    first = next(iter(res.values()))
    if not hasattr(first, "__len__"):
        return str(res)
    names = list(res.keys())
    nrows = len(first)
    cells = [[_fmt_value(res[n][r]) for n in names] for r in range(nrows)]
    widths = [
        max(len(n), *(len(row[i]) for row in cells)) if cells else len(n)
        for i, n in enumerate(names)
    ]
    sep = "-+-".join("-" * w for w in widths)
    out = [" | ".join(n.ljust(w) for n, w in zip(names, widths)), sep]
    for row in cells:
        out.append(" | ".join(c.ljust(w) for c, w in zip(row, widths)))
    out.append(f"({nrows} row{'s' if nrows != 1 else ''})")
    return "\n".join(out)


def execute_and_render(sess, stmt: str, timing: bool = False) -> str:
    from .sql import BindError
    from .utils.errors import QueryError

    t0 = time.time()
    try:
        if stmt.strip().lower().startswith("explain"):
            from .sql import explain

            out = explain(sess.catalog, stmt)
        else:
            res = sess.execute(stmt)
            if isinstance(res, dict) and ("rows_affected" in res
                                          or "created" in res):
                if "created" in res:
                    out = f"CREATE TABLE {res['created']}"
                else:
                    out = f"OK, {res['rows_affected']} row(s) affected"
            else:
                out = render_table(res)
    except (BindError, QueryError, SyntaxError, ValueError) as e:
        return f"ERROR: {e}"
    if timing:
        out += f"\n\nTime: {(time.time() - t0) * 1e3:.1f} ms"
    return out


def _load_demo_tpch(sess, sf: float) -> None:
    from .bench import tpch

    cat = tpch.gen_tpch(sf=sf)
    for name, table in cat.tables.items():
        sess.catalog.tables[name] = table
    print(f"-- TPC-H sf={sf:g} loaded: "
          f"{', '.join(sorted(cat.tables))}", file=sys.stderr)


def repl(sess) -> None:
    timing = False
    buf: list[str] = []
    prompt = "tpu-sql> "
    while True:
        try:
            line = input(prompt if not buf else "    ...> ")
        except (EOFError, KeyboardInterrupt):
            print()
            return
        stripped = line.strip()
        if not buf and stripped.startswith("\\"):
            if stripped in ("\\q", "\\quit"):
                return
            if stripped == "\\timing":
                timing = not timing
                print(f"Timing is {'on' if timing else 'off'}.")
            elif stripped == "\\d":
                for name in sorted(sess.catalog.tables):
                    t = sess.catalog.tables[name]
                    cols = ", ".join(
                        f"{n} {ty}" for n, ty in
                        zip(t.schema.names, t.schema.types)
                    )
                    print(f"  {name}({cols})")
            else:
                print(f"unknown meta command {stripped!r}")
            continue
        buf.append(line)
        joined = "\n".join(buf)
        if joined.rstrip().endswith(";"):
            buf = []
            stmt = joined.rstrip().rstrip(";")
            if stmt.strip():
                print(execute_and_render(sess, stmt, timing))


def hot_ranges_cmd(argv) -> int:
    """`cockroach_tpu.cli hot-ranges [--url]` — the `cockroach node
    status --ranges`-flavored verb: fetch /hot_ranges from a running
    node's admin API and render it psql-style, hottest range first."""
    import json as _json
    from urllib.request import urlopen

    ap = argparse.ArgumentParser(prog="cockroach_tpu.cli hot-ranges")
    ap.add_argument("--url", default="http://127.0.0.1:8080",
                    help="admin API base URL of a running node")
    args = ap.parse_args(argv)
    with urlopen(args.url.rstrip("/") + "/hot_ranges", timeout=5) as r:
        payload = _json.load(r)
    rows = payload.get("hotRanges", [])
    cols = ["rangeId", "startKey", "endKey", "storeId", "qps",
            "writeBytesRate", "sizeBytes", "leaseholder"]
    print(render_table({c: [row.get(c) for row in rows] for c in cols}))
    return 0


def debug_zip_cmd(argv) -> int:
    """`cockroach_tpu.cli debug zip [out.zip] [--url]` — the `cockroach
    debug zip` verb: pack metrics, settings, statement stats, hot ranges,
    in-flight spans, and statement diagnostics bundles into one archive.
    With --url the endpoints of a running node are pulled over HTTP;
    without it the current process's registries are snapshotted."""
    ap = argparse.ArgumentParser(prog="cockroach_tpu.cli debug zip")
    ap.add_argument("output", nargs="?", default="debug.zip",
                    help="archive path (default debug.zip)")
    ap.add_argument("--url", default=None,
                    help="admin API base URL of a running node; omitted "
                         "collects from the current process")
    args = ap.parse_args(argv)
    from .server import debugzip

    files = debugzip.collect(url=args.url)
    path = debugzip.write_zip(args.output, files)
    print(f"wrote {path} ({len(files)} files)")
    return 0


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "hot-ranges":
        return hot_ranges_cmd(argv[1:])
    if argv[:2] == ["debug", "zip"]:
        return debug_zip_cmd(argv[2:])
    ap = argparse.ArgumentParser(prog="cockroach_tpu.cli",
                                 description=__doc__)
    ap.add_argument("-e", "--execute", action="append", default=[],
                    help="run a statement and exit (repeatable)")
    ap.add_argument("-f", "--file", help="run statements from a file")
    ap.add_argument("--demo-tpch", type=float, metavar="SF",
                    help="preload TPC-H tables at this scale factor")
    ap.add_argument("--cpu", action="store_true",
                    help="force the CPU backend (skip the TPU tunnel)")
    ap.add_argument("--start", action="store_true",
                    help="server mode (the `cockroach start` analog): run a "
                         "Node serving pgwire + the HTTP admin API until "
                         "interrupted")
    ap.add_argument("--pg-port", type=int, default=26257,
                    help="pgwire listen port for --start (0 = ephemeral)")
    ap.add_argument("--http-port", type=int, default=8080,
                    help="HTTP admin port for --start (0 = ephemeral)")
    args = ap.parse_args(argv)

    if args.cpu:
        from .utils.backend import force_cpu_backend

        force_cpu_backend()

    if args.start:
        import time as _time

        from .server.node import Node

        node = Node().start(pg_port=args.pg_port, http_port=args.http_port)
        print(f"node {node.node_id} serving: "
              f"pgwire 127.0.0.1:{node.pg.addr[1]} "
              f"http 127.0.0.1:{node.admin.port}", flush=True)
        try:
            while True:
                _time.sleep(1)
        except KeyboardInterrupt:
            node.stop()
        return 0

    from .sql import Session

    sess = Session()
    if args.demo_tpch:
        _load_demo_tpch(sess, args.demo_tpch)

    stmts: list[str] = list(args.execute)
    if args.file:
        with open(args.file) as f:
            stmts.extend(s for s in f.read().split(";") if s.strip())
    if stmts:
        for s in stmts:
            print(execute_and_render(sess, s))
        return 0
    repl(sess)
    return 0


if __name__ == "__main__":
    sys.exit(main())
