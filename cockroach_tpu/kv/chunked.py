"""Chunked record persistence — blobs across fixed-width engine values.

One shared discipline for every system record that can outgrow a single
engine value (table descriptors, job records, table statistics): the blob
is prefixed with an 8-hex-char length header and split into
value-width-sized chunks under consecutive chunk keys. The header makes
STALE TRAILING CHUNKS harmless: a shorter rewrite leaves the old tail in
place, and readers truncate to the declared length instead of choking on
extra bytes (the bug class this module exists to kill — a 13-column
descriptor shrunk by DROP COLUMN used to corrupt catalog bootstrap).
"""

from __future__ import annotations

_HEADER = 8  # ascii hex length prefix


def chunk_blob(blob: bytes, step: int) -> list[bytes]:
    """Split header+blob into <=step-sized chunks (at least one)."""
    assert step > _HEADER, f"chunk step {step} too small"
    b = b"%08x" % len(blob) + blob
    return [b[i:i + step] for i in range(0, len(b), step)] or [b]


def unchunk(values: list[bytes]) -> bytes:
    """Reassemble chunks (in key order) -> original blob, ignoring any
    stale tail bytes past the declared length."""
    b = b"".join(values)
    total = int(b[:_HEADER], 16)
    return b[_HEADER:_HEADER + total]
