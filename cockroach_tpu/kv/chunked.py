"""Chunked record persistence — blobs across fixed-width engine values.

One shared discipline for every system record that can outgrow a single
engine value (table descriptors, job records, table statistics): the blob
is prefixed with an 8-hex-char length header and split into
value-width-sized chunks under consecutive chunk keys. The header makes
STALE TRAILING CHUNKS harmless: a shorter rewrite leaves the old tail in
place, and readers truncate to the declared length instead of choking on
extra bytes (the bug class this module exists to kill — a 13-column
descriptor shrunk by DROP COLUMN used to corrupt catalog bootstrap).
"""

from __future__ import annotations

_HEADER = 8  # ascii hex length prefix


def chunk_blob(blob: bytes, step: int) -> list[bytes]:
    """Split header+blob into <=step-sized chunks (at least one)."""
    assert step > _HEADER, f"chunk step {step} too small"
    b = b"%08x" % len(blob) + blob
    return [b[i:i + step] for i in range(0, len(b), step)] or [b]


def unchunk(values: list[bytes]) -> bytes:
    """Reassemble chunks (in key order) -> original blob, ignoring any
    stale tail bytes past the declared length.

    Legacy records (written before the header existed) reassemble as the
    raw concatenation: every caller stores JSON, whose first byte ('{')
    can never appear in the hex header, so the formats self-discriminate
    — a checkpoint from an older build stays restorable."""
    b = b"".join(values)
    head = b[:_HEADER]
    if len(head) == _HEADER and all(c in b"0123456789abcdef" for c in head):
        total = int(head, 16)
        return b[_HEADER:_HEADER + total]
    return b  # pre-header legacy record
