"""Node liveness — the kvserver/liveness analog.

Reference: liveness.go:241 NodeLiveness heartbeats an epoch-stamped record
into the KV store; a record whose expiration passed marks the node dead,
and INCREMENTING ITS EPOCH (by another node) fences any leases the dead
node held — the failure-detection primitive leases and the allocator build
on. Here the same record/epoch/fencing state machine runs over the engine's
KV surface (records in a reserved system keyspace), sized for the current
single-process topology: multiple NodeLiveness instances sharing one DB
behave like nodes sharing the liveness range, and the DCN flow server can
carry heartbeats when multi-host lands.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from .txn import DB, TransactionRetryError

# system keyspace: table id 0's prefix byte (0x01) + a NUL-free tag — the
# engine's zero-padded fixed-width keys reject 0x00 bytes, so node ids
# encode as fixed-width decimal ASCII (order-preserving, NUL-free)
_PREFIX = b"\x01liv"
_REC = struct.Struct("<qqq")  # epoch, expiration_ts, node_id


class StillLiveError(Exception):
    """increment_epoch refused: the target's record has not expired."""


class EpochFencedError(Exception):
    """The node's epoch was incremented by a peer (it was declared dead):
    every lease it held under the old epoch is invalid and it must not
    heartbeat the old epoch back to life."""


@dataclass(frozen=True)
class LivenessRecord:
    node_id: int
    epoch: int
    expiration: int  # hlc timestamp

    def live_at(self, ts: int) -> bool:
        return ts < self.expiration


class NodeLiveness:
    """One node's view of the shared liveness records."""

    def __init__(self, db: DB, node_id: int,
                 heartbeat_interval_ms: int = 4500,
                 ttl_ms: int = 9000):
        self.db = db
        self.node_id = int(node_id)
        self.ttl_ms = ttl_ms
        self.heartbeat_interval_ms = heartbeat_interval_ms
        self._my_epoch: int | None = None  # epoch this node believes it owns

    @staticmethod
    def _key(node_id: int) -> bytes:
        return _PREFIX + b"%05d" % node_id

    def _read(self, node_id: int, reader=None) -> LivenessRecord | None:
        """reader: pass the open Txn inside txn closures so the read lands
        in the txn's read spans (commit-time refresh validates it) and a
        concurrent writer's intent converts to TransactionRetryError rather
        than surfacing WriteIntentError out of db.get.

        Non-transactional status reads (is_live from the admin API or the
        jobs adoption loop) instead retry briefly past a concurrent
        heartbeat's intent: a status probe must never fail just because a
        heartbeat is mid-commit (the reference's liveness cache serves such
        reads from gossiped state for the same reason)."""
        if reader is not None:
            v = reader.get(self._key(node_id))
        else:
            from ..utils.errors import retry_past_intents

            v = retry_past_intents(lambda: self.db.get(self._key(node_id)))
        if v is None:
            return None
        epoch, exp, nid = _REC.unpack(v)
        return LivenessRecord(nid, epoch, exp)

    # -- the node's own record ---------------------------------------------

    def heartbeat(self) -> LivenessRecord:
        """Extend this node's expiration under the epoch it believes it
        owns. Raises EpochFencedError if a peer incremented the epoch (the
        node was declared dead; its old leases are invalid)."""
        def op(t):
            cur = self._read(self.node_id, t)
            now = self.db.clock.now()
            from . import hlc

            wall, _ = hlc.unpack(now)
            exp = hlc.pack(wall + self.ttl_ms, 0)
            if cur is None:
                rec = LivenessRecord(self.node_id, 1, exp)
            elif (self._my_epoch is not None
                    and cur.epoch != self._my_epoch):
                raise EpochFencedError(
                    f"node {self.node_id}: epoch {self._my_epoch} fenced "
                    f"(record at {cur.epoch})"
                )
            else:
                rec = LivenessRecord(self.node_id, cur.epoch, exp)
            t.put(self._key(self.node_id),
                  _REC.pack(rec.epoch, rec.expiration, rec.node_id))
            return rec

        rec = self.db.txn(op)
        self._my_epoch = rec.epoch
        return rec

    # -- other nodes --------------------------------------------------------

    def is_live(self, node_id: int) -> bool:
        rec = self._read(node_id)
        return rec is not None and rec.live_at(self.db.clock.now())

    def increment_epoch(self, node_id: int) -> LivenessRecord:
        """Declare a non-live node dead by bumping its epoch — the fencing
        write that invalidates its epoch-based leases. Refuses while the
        record is still live (liveness.go IncrementEpoch contract)."""
        def op(t):
            cur = self._read(node_id, t)
            if cur is None:
                raise ValueError(f"no liveness record for node {node_id}")
            if cur.live_at(self.db.clock.now()):
                raise StillLiveError(
                    f"node {node_id} is still live; cannot increment epoch"
                )
            rec = LivenessRecord(node_id, cur.epoch + 1, cur.expiration)
            t.put(self._key(node_id),
                  _REC.pack(rec.epoch, rec.expiration, rec.node_id))
            return rec

        return self.db.txn(op)

    def livenesses(self) -> list[LivenessRecord]:
        from ..utils.errors import retry_past_intents

        # a peer's heartbeat may be mid-commit; status reads wait it out
        rows = retry_past_intents(
            lambda: self.db.scan(_PREFIX, _PREFIX + b"\xff"))
        out = []
        for _, v in rows:
            epoch, exp, nid = _REC.unpack(v)
            out.append(LivenessRecord(nid, epoch, exp))
        return out
