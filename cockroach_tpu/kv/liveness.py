"""Node liveness + epoch leases — the kvserver/liveness analog.

Reference: liveness.go:241 NodeLiveness heartbeats an epoch-stamped record
into the KV store; a record whose expiration passed marks the node dead,
and INCREMENTING ITS EPOCH (by another node) fences any leases the dead
node held — the failure-detection primitive leases and the allocator build
on. Here the same record/epoch/fencing state machine runs over the engine's
KV surface (records in a reserved system keyspace), sized for the current
single-process topology: multiple NodeLiveness instances sharing one DB
behave like nodes sharing the liveness range, and the DCN flow server can
carry heartbeats when multi-host lands.

LeaseManager adds the epoch-lease half (replica_range_lease.go reduced):
a range lease names (holder node, holder's liveness epoch); it is valid
exactly while the holder's liveness record still carries that epoch.
Failover = expire -> a peer bumps the epoch (the fencing write) -> the
peer writes itself in as holder. A resurrected holder fails the epoch
equality check and must re-acquire, never serve stale.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from .txn import DB

# system keyspace: table id 0's prefix byte (0x01) + a NUL-free tag — the
# engine's zero-padded fixed-width keys reject 0x00 bytes, so node ids
# encode as fixed-width decimal ASCII (order-preserving, NUL-free)
_PREFIX = b"\x01liv"
_REC = struct.Struct("<qqq")  # epoch, expiration_ts, node_id

_LEASE_PREFIX = b"\x01lse"
_LEASE_REC = struct.Struct("<qqq")  # node_id, epoch, range_id


class StillLiveError(Exception):
    """increment_epoch refused: the target's record has not expired."""


class EpochFencedError(Exception):
    """The node's epoch was incremented by a peer (it was declared dead):
    every lease it held under the old epoch is invalid and it must not
    heartbeat the old epoch back to life."""


class NotLeaseHolderError(Exception):
    """The addressed node does not hold the range's lease (kvpb's
    NotLeaseHolderError): `holder` carries the current holder's node id
    when known, so the client can reroute instead of guessing."""

    def __init__(self, msg: str, holder: int | None = None):
        super().__init__(msg)
        self.holder = holder


# fencing/routing errors cross the query error boundary unwrapped so
# callers can key on the type (colexecerror.ExpectedError discipline)
from ..utils.errors import register_passthrough as _rp  # noqa: E402

_rp(StillLiveError)
_rp(EpochFencedError)
_rp(NotLeaseHolderError)


@dataclass(frozen=True)
class LivenessRecord:
    node_id: int
    epoch: int
    expiration: int  # hlc timestamp

    def live_at(self, ts: int) -> bool:
        return ts < self.expiration


class NodeLiveness:
    """One node's view of the shared liveness records."""

    def __init__(self, db: DB, node_id: int,
                 heartbeat_interval_ms: int = 4500,
                 ttl_ms: int = 9000):
        self.db = db
        self.node_id = int(node_id)
        self.ttl_ms = ttl_ms
        self.heartbeat_interval_ms = heartbeat_interval_ms
        self._my_epoch: int | None = None  # epoch this node believes it owns

    @staticmethod
    def _key(node_id: int) -> bytes:
        return _PREFIX + b"%05d" % node_id

    def _read(self, node_id: int, reader=None) -> LivenessRecord | None:
        """reader: pass the open Txn inside txn closures so the read lands
        in the txn's read spans (commit-time refresh validates it) and a
        concurrent writer's intent converts to TransactionRetryError rather
        than surfacing WriteIntentError out of db.get.

        Non-transactional status reads (is_live from the admin API or the
        jobs adoption loop) instead retry briefly past a concurrent
        heartbeat's intent: a status probe must never fail just because a
        heartbeat is mid-commit (the reference's liveness cache serves such
        reads from gossiped state for the same reason)."""
        if reader is not None:
            v = reader.get(self._key(node_id))
        else:
            from ..utils.errors import retry_past_intents

            v = retry_past_intents(lambda: self.db.get(self._key(node_id)))
        if v is None:
            return None
        epoch, exp, nid = _REC.unpack(v)
        return LivenessRecord(nid, epoch, exp)

    # -- the node's own record ---------------------------------------------

    def heartbeat(self) -> LivenessRecord:
        """Extend this node's expiration under the epoch it believes it
        owns. Raises EpochFencedError if a peer incremented the epoch (the
        node was declared dead; its old leases are invalid)."""
        from ..utils import faults

        # chaos site: a blackholed heartbeat models the node losing its
        # liveness range (network partition / stalled disk). Fires the
        # node-scoped variant too so a test can kill ONE node's
        # heartbeats while its peers keep renewing.
        faults.fire_scoped("liveness.heartbeat", self.node_id)

        def op(t):
            cur = self._read(self.node_id, t)
            now = self.db.clock.now()
            from . import hlc

            wall, _ = hlc.unpack(now)
            exp = hlc.pack(wall + self.ttl_ms, 0)
            if cur is None:
                rec = LivenessRecord(self.node_id, 1, exp)
            elif (self._my_epoch is not None
                    and cur.epoch != self._my_epoch):
                raise EpochFencedError(
                    f"node {self.node_id}: epoch {self._my_epoch} fenced "
                    f"(record at {cur.epoch})"
                )
            else:
                rec = LivenessRecord(self.node_id, cur.epoch, exp)
            t.put(self._key(self.node_id),
                  _REC.pack(rec.epoch, rec.expiration, rec.node_id))
            return rec

        rec = self.db.txn(op)
        self._my_epoch = rec.epoch
        return rec

    # -- other nodes --------------------------------------------------------

    def is_live(self, node_id: int) -> bool:
        rec = self._read(node_id)
        return rec is not None and rec.live_at(self.db.clock.now())

    def increment_epoch(self, node_id: int) -> LivenessRecord:
        """Declare a non-live node dead by bumping its epoch — the fencing
        write that invalidates its epoch-based leases. Refuses while the
        record is still live (liveness.go IncrementEpoch contract)."""
        from ..utils import faults

        # chaos site, scoped by the node DOING the bump (the fencer):
        # models IncrementEpoch's CPut losing a race / failing transport
        faults.fire_scoped("liveness.epoch_bump", self.node_id)

        def op(t):
            cur = self._read(node_id, t)
            if cur is None:
                raise ValueError(f"no liveness record for node {node_id}")
            if cur.live_at(self.db.clock.now()):
                raise StillLiveError(
                    f"node {node_id} is still live; cannot increment epoch"
                )
            rec = LivenessRecord(node_id, cur.epoch + 1, cur.expiration)
            t.put(self._key(node_id),
                  _REC.pack(rec.epoch, rec.expiration, rec.node_id))
            return rec

        return self.db.txn(op)

    def livenesses(self) -> list[LivenessRecord]:
        from ..utils.errors import retry_past_intents

        # a peer's heartbeat may be mid-commit; status reads wait it out
        rows = retry_past_intents(
            lambda: self.db.scan(_PREFIX, _PREFIX + b"\xff"))
        out = []
        for _, v in rows:
            epoch, exp, nid = _REC.unpack(v)
            out.append(LivenessRecord(nid, epoch, exp))
        return out


@dataclass(frozen=True)
class LeaseRecord:
    range_id: int
    node_id: int
    epoch: int  # the holder's liveness epoch when the lease was written


class LeaseManager:
    """Epoch-based range leases over the liveness state machine
    (replica_range_lease.go reduced to the epoch-lease case).

    Invariant: a lease (holder, epoch) is valid exactly while the
    holder's liveness record still carries `epoch`. Nobody ever checks
    wall-clock expiration on the LEASE — fencing the liveness epoch is
    the single source of truth, so clock skew between nodes can't let
    two leaseholders coexist."""

    def __init__(self, liveness: NodeLiveness):
        self.liveness = liveness
        self.db = liveness.db
        self.node_id = liveness.node_id

    @staticmethod
    def _key(range_id: int) -> bytes:
        return _LEASE_PREFIX + b"%05d" % range_id

    def holder(self, range_id: int) -> LeaseRecord | None:
        from ..utils.errors import retry_past_intents

        v = retry_past_intents(lambda: self.db.get(self._key(range_id)))
        if v is None:
            return None
        nid, epoch, rid = _LEASE_REC.unpack(v)
        return LeaseRecord(rid, nid, epoch)

    def acquire(self, range_id: int) -> LeaseRecord:
        """Take (or renew) the range's lease for this node.

        - vacant lease: write ourselves in under our current epoch;
        - we already hold it: renew (rewrite under our current epoch);
        - a LIVE peer holds it: NotLeaseHolderError (reroute, don't
          steal);
        - a dead/fenced peer holds it: bump its liveness epoch first —
          the fencing write, so a resurrection can't serve under the
          old lease — then write ourselves in (kv_lease_failovers
          counts it)."""
        from ..utils import metric

        if self.liveness._my_epoch is None:
            self.liveness.heartbeat()  # allocates/learns our epoch
        my_epoch = self.liveness._my_epoch
        cur = self.holder(range_id)
        if cur is not None and cur.node_id != self.node_id:
            rec = self.liveness._read(cur.node_id)
            if (rec is not None and rec.epoch == cur.epoch
                    and rec.live_at(self.db.clock.now())):
                raise NotLeaseHolderError(
                    f"r{range_id} lease held by live node {cur.node_id}",
                    holder=cur.node_id)
            if rec is not None and rec.epoch == cur.epoch:
                # expired but not yet fenced: the epoch bump IS the
                # fencing write (StillLiveError surfaces if the holder
                # heartbeated between our check and the bump — callers
                # treat that as "lost the failover race")
                self.liveness.increment_epoch(cur.node_id)
            metric.LEASE_FAILOVERS.inc()

        def op(t):
            # re-validate under the txn so a racing acquirer's write
            # invalidates our read spans and retries/loses cleanly
            v = t.get(self._key(range_id))
            if v is not None:
                nid, epoch, _ = _LEASE_REC.unpack(v)
                if nid != self.node_id:
                    rec = self.liveness._read(nid, t)
                    if (rec is not None and rec.epoch == epoch
                            and rec.live_at(self.db.clock.now())):
                        raise NotLeaseHolderError(
                            f"r{range_id} lease held by live node {nid}",
                            holder=nid)
            t.put(self._key(range_id),
                  _LEASE_REC.pack(self.node_id, my_epoch, range_id))
            return LeaseRecord(range_id, self.node_id, my_epoch)

        return self.db.txn(op)

    def carry(self, parent_id: int, child_id: int) -> LeaseRecord | None:
        """Copy the parent range's (holder, epoch) onto a freshly split
        child — the reference's split trigger derives the RHS lease from
        the LHS so the new range is immediately servable by the same
        holder instead of starting a lease race. No-op when the parent's
        lease is vacant or the child already has one."""
        cur = self.holder(parent_id)
        if cur is None:
            return None

        def op(t):
            if t.get(self._key(child_id)) is not None:
                return None  # raced with an acquire; keep theirs
            t.put(self._key(child_id),
                  _LEASE_REC.pack(cur.node_id, cur.epoch, child_id))
            return LeaseRecord(child_id, cur.node_id, cur.epoch)

        return self.db.txn(op)

    def transfer(self, range_id: int, to_node: int) -> LeaseRecord:
        """Cooperative lease transfer (the AdminTransferLease reduction):
        stamp the target as holder under the TARGET's current liveness
        epoch. Only the current holder (or anyone, for a vacant lease)
        may transfer; the target must be live — a lease named under a
        dead node's epoch would be born fenced."""
        target = self.liveness._read(to_node)
        if target is None or not target.live_at(self.db.clock.now()):
            raise ValueError(f"lease transfer target node {to_node} not live")
        cur = self.holder(range_id)
        if (cur is not None and cur.node_id != self.node_id
                and to_node != self.node_id):
            raise NotLeaseHolderError(
                f"r{range_id}: node {self.node_id} cannot transfer a lease "
                f"held by node {cur.node_id}", holder=cur.node_id)

        def op(t):
            t.put(self._key(range_id),
                  _LEASE_REC.pack(to_node, target.epoch, range_id))
            return LeaseRecord(range_id, to_node, target.epoch)

        return self.db.txn(op)

    def release(self, range_id: int) -> None:
        """Drop the lease record (merge cleanup: the absorbed range id
        stops existing, so its lease must not linger and confuse a later
        id reuse)."""
        self.db.delete(self._key(range_id))

    def check(self, range_id: int) -> None:
        """Server-side serve guard: raises unless THIS node holds the
        lease under its CURRENT liveness epoch. A fenced node (epoch
        bumped while it was dark) fails the equality check no matter
        what its local state claims — the resurrect-after-fence case."""
        cur = self.holder(range_id)
        if cur is None or cur.node_id != self.node_id:
            raise NotLeaseHolderError(
                f"r{range_id} not leased to node {self.node_id}",
                holder=None if cur is None else cur.node_id)
        rec = self.liveness._read(self.node_id)
        if rec is None or rec.epoch != cur.epoch:
            raise EpochFencedError(
                f"node {self.node_id} serving r{range_id} under epoch "
                f"{cur.epoch} but liveness is at "
                f"{None if rec is None else rec.epoch}")
