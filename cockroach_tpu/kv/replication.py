"""Cross-cluster physical replication — the pkg/crosscluster reduction.

Reference: physical cluster replication streams one cluster's rangefeed
into another, applying KVs at their ORIGINAL MVCC timestamps so the
standby holds a time-travel-consistent copy; a span frontier tracks the
replicated-up-to timestamp, and cutover finalizes the standby at (or
below) that frontier (pkg/crosscluster/physical).

Reduction: ``ReplicationStream`` subscribes to a source cluster's
RangefeedServer in byte-exact (raw) mode over the DCN socket plane and
applies every committed version into the destination engine verbatim —
keys, values, tombstones and timestamps unchanged — so historical reads
on the standby return exactly what the source returned at the same
timestamp. The frontier advances with the source's resolved checkpoints
(which already respect the closed-timestamp discipline: never past an
unresolved intent). ``cutover()`` stops the stream and returns the
frontier: the standby is consistent as of that timestamp.
"""

from __future__ import annotations

import base64
import threading

from .changefeed import subscribe_rangefeed
from .txn import DB


class ReplicationStream:
    """Reconnect discipline: a severed stream re-subscribes from the
    FRONTIER with exponential backoff through the shared retry policy
    (utils/retry.py) instead of dying on the first transport error — the
    reference's rangefeed restarts the same way. Events between the
    frontier and the cut may re-deliver; applies are byte-exact at their
    original (key, ts), so a re-apply lays an identical version and
    reads are unchanged (MVCC idempotence). Only retry exhaustion or a
    non-transport error parks in ``self.error``."""

    def __init__(self, src_addr, dst_db: DB,
                 start: bytes | None = None, end: bytes | None = None,
                 since: int = 0, reconnect_attempts: int = 6):
        self.src_addr = tuple(src_addr)
        self.start = start
        self.end = end
        self.dst = dst_db
        # crlint: allow-race-coverage(frontier is single-writer: only the stream thread RMWs it — see the allow-shared-state note at the apply site; wait_for_frontier/cutover poll a GIL-atomic int snapshot. racesan's Eraser lockset model has no single-writer exemption, so instrumenting this field would raise false DataRaceError under chaos)
        self.frontier = int(since)
        self.applied = 0
        self.reconnects = 0
        self.reconnect_attempts = int(reconnect_attempts)
        self._stop = threading.Event()
        self._sock, self._frames = subscribe_rangefeed(
            src_addr, start=start, end=end, since=since, raw=True)
        self._thread: threading.Thread | None = None
        # a failed apply must not vanish with the daemon thread: it parks
        # here and re-raises at the next consumer interaction
        self.error: BaseException | None = None

    # -- apply loop ----------------------------------------------------------

    def _apply(self, ev: dict) -> None:
        key = base64.b64decode(ev["k64"])
        ts = int(ev["ts"])
        eng = self.dst.engine
        if ev["v64"] is None:
            eng.delete(key, ts=ts)
        else:
            eng.put(key, base64.b64decode(ev["v64"]), ts=ts)
        # the destination's clock must not issue timestamps below
        # replicated data (reads at now() must see it)
        self.dst.clock.update(ts)
        self.applied += 1

    def _resubscribe(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock, self._frames = subscribe_rangefeed(
            self.src_addr, start=self.start, end=self.end,
            since=self.frontier, raw=True)

    def run(self) -> None:
        """Consume frames until stopped; reconnect through severed
        streams (see class docstring)."""
        from ..utils import metric, retry

        try:
            while not self._stop.is_set():
                for frame in self._frames:
                    if self._stop.is_set():
                        return
                    if "resolved" in frame:
                        # crlint: allow-shared-state(single-writer RMW on the stream thread; readers tolerate a stale frontier — resubscribe just replays)
                        self.frontier = max(self.frontier,
                                            int(frame["resolved"]))
                    else:
                        self._apply(frame)
                # the frame iterator ended: cutover closing our socket
                # (clean stop) or the source died mid-stream. Re-dial
                # from the frontier under backoff; exhaustion parks the
                # last transport error for the consumer to see.
                if self._stop.is_set():
                    return
                retry.call(
                    self._resubscribe,
                    retry.Backoff(max_attempts=self.reconnect_attempts,
                                  initial_s=0.05),
                    retryable=retry.is_retryable,
                )
                self.reconnects += 1
                metric.REPLICATION_RECONNECTS.inc()
        except BaseException as e:
            if not self._stop.is_set():
                self.error = e
                raise
            # stopping raced a reconnect attempt: a transport error here
            # is teardown noise, not a stream failure
        finally:
            if self._stop.is_set():
                # a resubscribe may have raced cutover's socket close and
                # opened a fresh connection — never leak it
                try:
                    self._sock.close()
                except OSError:
                    pass

    def run_background(self) -> "ReplicationStream":
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="replication-stream")
        self._thread.start()
        return self

    def wait_for_frontier(self, ts: int, timeout_s: float = 10.0) -> bool:
        import time

        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if self.error is not None:
                raise RuntimeError("replication stream failed") \
                    from self.error
            if self.frontier >= ts:
                return True
            time.sleep(0.01)
        return False

    def cutover(self) -> int:
        """Stop replicating; the standby is consistent as of the returned
        frontier (writes the source commits after this never arrive).
        Raises if the stream died on an apply error — a silent dead
        stream must not masquerade as a successful cutover."""
        self._stop.set()
        try:
            self._sock.close()  # unblocks the frame reader
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self.error is not None:
            raise RuntimeError(
                "replication stream failed before cutover"
            ) from self.error
        return self.frontier
