"""Cross-cluster physical replication — the pkg/crosscluster reduction.

Reference: physical cluster replication streams one cluster's rangefeed
into another, applying KVs at their ORIGINAL MVCC timestamps so the
standby holds a time-travel-consistent copy; a span frontier tracks the
replicated-up-to timestamp, and cutover finalizes the standby at (or
below) that frontier (pkg/crosscluster/physical).

Reduction: ``ReplicationStream`` subscribes to a source cluster's
RangefeedServer in byte-exact (raw) mode over the DCN socket plane and
applies every committed version into the destination engine verbatim —
keys, values, tombstones and timestamps unchanged — so historical reads
on the standby return exactly what the source returned at the same
timestamp. The frontier advances with the source's resolved checkpoints
(which already respect the closed-timestamp discipline: never past an
unresolved intent). ``cutover()`` stops the stream and returns the
frontier: the standby is consistent as of that timestamp.
"""

from __future__ import annotations

import base64
import threading

from .changefeed import subscribe_rangefeed
from .txn import DB


class ReplicationStream:
    def __init__(self, src_addr, dst_db: DB,
                 start: bytes | None = None, end: bytes | None = None,
                 since: int = 0):
        self.dst = dst_db
        self.frontier = int(since)
        self.applied = 0
        self._stop = threading.Event()
        self._sock, self._frames = subscribe_rangefeed(
            src_addr, start=start, end=end, since=since, raw=True)
        self._thread: threading.Thread | None = None
        # a failed apply must not vanish with the daemon thread: it parks
        # here and re-raises at the next consumer interaction
        self.error: BaseException | None = None

    # -- apply loop ----------------------------------------------------------

    def _apply(self, ev: dict) -> None:
        key = base64.b64decode(ev["k64"])
        ts = int(ev["ts"])
        eng = self.dst.engine
        if ev["v64"] is None:
            eng.delete(key, ts=ts)
        else:
            eng.put(key, base64.b64decode(ev["v64"]), ts=ts)
        # the destination's clock must not issue timestamps below
        # replicated data (reads at now() must see it)
        self.dst.clock.update(ts)
        self.applied += 1

    def run(self) -> None:
        """Consume frames until stopped (or the source closes)."""
        try:
            for frame in self._frames:
                if self._stop.is_set():
                    return
                if "resolved" in frame:
                    self.frontier = max(self.frontier,
                                        int(frame["resolved"]))
                else:
                    self._apply(frame)
        except BaseException as e:
            self.error = e
            raise

    def run_background(self) -> "ReplicationStream":
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="replication-stream")
        self._thread.start()
        return self

    def wait_for_frontier(self, ts: int, timeout_s: float = 10.0) -> bool:
        import time

        deadline = time.time() + timeout_s
        while time.time() < deadline:
            if self.error is not None:
                raise RuntimeError("replication stream failed") \
                    from self.error
            if self.frontier >= ts:
                return True
            time.sleep(0.01)
        return False

    def cutover(self) -> int:
        """Stop replicating; the standby is consistent as of the returned
        frontier (writes the source commits after this never arrive).
        Raises if the stream died on an apply error — a silent dead
        stream must not masquerade as a successful cutover."""
        self._stop.set()
        try:
            self._sock.close()  # unblocks the frame reader
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=5)
        if self.error is not None:
            raise RuntimeError(
                "replication stream failed before cutover"
            ) from self.error
        return self.frontier
