"""Transactional KV — the pkg/kv surface (kv.DB / kv.Txn) over the LSM
engine's MVCC intents.

Reference mapping:
- ``DB.txn(fn)``   <- kv.DB.Txn closure-with-retries (pkg/kv/db.go); retries
  on retryable errors with a bumped timestamp, like TxnCoordSender's retry
  loop around serializability failures.
- intents          <- provisional values owned by a txn id; reads of other
  txns' visible intents fail (WriteIntentError), writes check the lock
  before laying an intent (concurrency manager's lock table role).
- commit           <- read-span refresh validation (span refresher
  interceptor semantics) then intent resolution at the commit timestamp
  (MVCCResolveWriteIntent); abort drops the intents.
- WriteTooOld      <- a newer committed version above the txn's read_ts
  forces a retry, as in the reference's WriteTooOldError.

Single-process scope: latching is the GIL (flows are single-threaded);
distribution of this layer rides the same control plane as DistSQL when
multi-host lands.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field

from ..storage.lsm import Engine, WriteIntentError
from ..utils import settings
from . import hlc


class TransactionRetryError(Exception):
    """Retryable: the txn must restart at a higher timestamp."""


class TransactionAbortedError(Exception):
    """Non-retryable inside the closure: the txn was aborted."""

# txn control-flow errors cross the query error boundary unwrapped (the
# colexecerror.ExpectedError discipline)
from ..utils.errors import register_passthrough as _rp  # noqa: E402

_rp(TransactionRetryError)
_rp(TransactionAbortedError)



_txn_ids = itertools.count(1)


@dataclass
class Txn:
    db: "DB"
    txn_id: int
    read_ts: int
    _finished: bool = False
    # read spans for commit-time refresh validation: (start, end, is_point);
    # point spans cover exactly their key, end=None means unbounded
    _read_spans: list[tuple[bytes, bytes | None, bool]] = field(
        default_factory=list)
    _write_keys: list[bytes] = field(default_factory=list)
    # callbacks fired once after a SUCCESSFUL commit (discarded on
    # rollback/retry): side effects that must be atomic with the txn
    # (e.g. KVTable's in-memory dictionary additions)
    _commit_hooks: list = field(default_factory=list)

    def on_commit(self, cb) -> None:
        self._commit_hooks.append(cb)

    def note_read_span(self, start: bytes, end: bytes | None,
                       point: bool = False) -> None:
        """Record an externally-performed read (e.g. a columnar table scan
        executed at this txn's snapshot) so commit-time refresh validation
        covers it — the span-refresher contract for reads that bypass
        Txn.get/scan."""
        self._check_open()
        self._read_spans.append((start, end, point))

    # -- reads --------------------------------------------------------------

    def get(self, key: bytes | str) -> bytes | None:
        self._check_open()
        k = _b(key)
        self._read_spans.append((k, None, True))
        try:
            return self.db.engine.get(k, ts=self.read_ts, txn=self.txn_id)
        except WriteIntentError as e:
            _record_contention(e, self.txn_id)
            raise TransactionRetryError(
                f"conflicting intent on {e.keys}"
            ) from e

    def scan(self, start: bytes | str | None, end: bytes | str | None,
             max_keys: int | None = None) -> list[tuple[bytes, bytes]]:
        self._check_open()
        s = _b(start) if start is not None else None
        e = _b(end) if end is not None else None
        self._read_spans.append((s or b"", e, False))
        try:
            return self.db.engine.scan(
                s, e, ts=self.read_ts, txn=self.txn_id, max_keys=max_keys
            )
        except WriteIntentError as err:
            _record_contention(err, self.txn_id)
            raise TransactionRetryError(
                f"conflicting intent on {err.keys}"
            ) from err

    # -- writes -------------------------------------------------------------

    def put(self, key: bytes | str, value: bytes | str) -> None:
        self._write(_b(key), value, tomb=False)

    def delete(self, key: bytes | str) -> None:
        self._write(_b(key), b"", tomb=True)

    def _write(self, key: bytes, value, tomb: bool) -> None:
        self._check_open()
        # the lock-check + write pair holds the engine mutex so a concurrent
        # txn can't interleave between the check and the intent landing
        # (latch-acquisition atomicity, concurrency_manager.SequenceReq)
        with self.db.engine.mu:
            other = self.db.engine.other_intent(key, self.txn_id)
            if other is not None:
                _record_contention(
                    WriteIntentError([key], [other]), self.txn_id
                )
                raise TransactionRetryError(
                    f"key {key!r} locked by txn {other}"
                )
            if self.db.engine.newest_committed_ts(key) > self.read_ts:
                # WriteTooOld: someone committed above our snapshot
                raise TransactionRetryError(f"write too old on {key!r}")
            if tomb:
                self.db.engine.delete(key, ts=self.read_ts, txn=self.txn_id)
            else:
                self.db.engine.put(key, value, ts=self.read_ts,
                                   txn=self.txn_id)
        self._write_keys.append(key)

    # -- lifecycle ----------------------------------------------------------

    def commit(self) -> int:
        self._check_open()
        commit_ts = self.db.clock.now()
        # refresh + resolve are one atomic section under the engine mutex:
        # a write landing between a validated refresh and the intent
        # resolution would invalidate the just-checked read spans
        with self.db.engine.mu:
            # refresh: reads must still be valid at commit_ts
            for s, e, is_point in self._read_spans:
                if self.db.engine.has_committed_writes_in(
                    s, e, self.read_ts, commit_ts, point=is_point
                ):
                    self.rollback()
                    raise TransactionRetryError(
                        f"read span {s!r} invalidated before commit"
                    )
            self.db.engine.resolve_intents(
                self.txn_id, commit_ts, commit=True
            )
        self._finished = True
        from ..utils import metric

        metric.TXN_COMMITS.inc()
        for cb in self._commit_hooks:
            cb()
        return commit_ts

    def rollback(self) -> None:
        if self._finished:
            return
        self.db.engine.resolve_intents(self.txn_id, 0, commit=False)
        self._finished = True

    def _check_open(self):
        if self._finished:
            raise TransactionAbortedError("txn already finished")


def _b(x: bytes | str) -> bytes:
    return x.encode() if isinstance(x, str) else bytes(x)


def _record_contention(e: WriteIntentError, waiting_txn: int) -> None:
    """Feed the contention registry (pkg/sql/contention role); never let
    observability break the conflict path."""
    try:
        from .contention import DEFAULT

        DEFAULT.record(e.keys, e.txns, waiting_txn)
    # crlint: allow-broad-except(conflict path must not fail on observability; logged + counted)
    except Exception as rec_err:  # pragma: no cover - registry must not mask errors
        from ..utils import log, metric

        metric.CONTENTION_RECORD_ERRORS.inc()
        log.warning(log.OPS, "contention record failed",
                    error=f"{type(rec_err).__name__}: {rec_err}")


class DB:
    """kv.DB analog: non-transactional ops commit immediately; ``txn`` runs
    a closure with automatic retries."""

    def __init__(self, engine: Engine | None = None,
                 clock: hlc.Clock | None = None):
        self.engine = engine or Engine()
        self.clock = clock or hlc.Clock()

    # non-transactional (auto-committed) ops. Like the reference, non-txn
    # requests still sequence through concurrency control: a write under
    # another txn's intent conflicts (WriteIntentError) instead of silently
    # laying a committed version beneath the intent; non-txn reads surface
    # the same WriteIntentError (callers retry after the owner resolves).
    # When kv.batch.coalesce.enabled, concurrent point ops from different
    # sessions merge into one stamped batch (kv/coalesce.py commit train)
    # — gate checked BEFORE any engine lock so riders park lock-free.
    def put(self, key, value) -> int:
        if settings.get("kv.batch.coalesce.enabled"):
            from .coalesce import for_db

            return for_db(self).put(key, value)
        return self._put_solo(_b(key), value)

    def _put_solo(self, key, value) -> int:
        k = _b(key)
        with self.engine.mu:
            self._check_lock(k)
            ts = self.clock.now()
            self.engine.put(k, value, ts=ts)
        return ts

    def delete(self, key) -> int:
        if settings.get("kv.batch.coalesce.enabled"):
            from .coalesce import for_db

            return for_db(self).delete(key)
        return self._delete_solo(_b(key))

    def _delete_solo(self, key) -> int:
        k = _b(key)
        with self.engine.mu:
            self._check_lock(k)
            ts = self.clock.now()
            self.engine.delete(k, ts=ts)
        return ts

    def _check_lock(self, key: bytes) -> None:
        other = self.engine.other_intent(key, 0)
        if other is not None:
            raise WriteIntentError([key], [other])

    def get(self, key, ts: int | None = None) -> bytes | None:
        if settings.get("kv.batch.coalesce.enabled"):
            from .coalesce import for_db

            return for_db(self).get(key, ts)
        return self._get_solo(_b(key), ts)

    def _get_solo(self, key, ts: int | None = None) -> bytes | None:
        return self.engine.get(_b(key), ts=ts if ts is not None
                               else self.clock.now())

    def scan(self, start, end, ts: int | None = None, max_keys=None):
        return self.engine.scan(
            _b(start) if start is not None else None,
            _b(end) if end is not None else None,
            ts=ts if ts is not None else self.clock.now(),
            max_keys=max_keys,
        )

    def new_txn(self) -> Txn:
        return Txn(self, next(_txn_ids), self.clock.now())

    def txn(self, fn, max_retries: int = 16):
        """Run fn(txn) with commit; retry on TransactionRetryError with a
        fresh timestamp (the kv.DB.Txn closure contract: fn must be
        idempotent across retries).

        AmbiguousResultError (kv/rpc.py) is deliberately NOT retried:
        when a remote mutation's apply state is unknowable, re-running
        the closure could commit it twice. It rolls back local intents
        and surfaces — the application decides whether to read-verify
        and resume (TxnCoordSender surfaces ambiguity the same way)."""
        for _ in range(max_retries):
            t = self.new_txn()
            try:
                out = fn(t)
                t.commit()
                return out
            except TransactionRetryError:
                from ..utils import metric

                metric.TXN_RETRIES.inc()
                t.rollback()
                continue
            except BaseException:
                # any other error: roll back so the intents don't wedge the
                # keys forever, then surface the error (kv.DB.Txn contract)
                t.rollback()
                raise
        raise TransactionRetryError(f"txn gave up after {max_retries} retries")
