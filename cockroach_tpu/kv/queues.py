"""Background replica queues — the kvserver baseQueue/purgatory analog.

Reference: pkg/kv/kvserver/queue.go runs each maintenance concern
(splitQueue, mergeQueue, replicateQueue, ...) as a baseQueue: a priority
heap of replicas fed by scanners, a paced processing loop, and a
*purgatory* for replicas whose processing failed with an error the queue
recognizes as temporary (purgatoryError) — those retry on a slow timer
instead of hot-looping or being dropped.

`ReplicaQueue` here is the generic engine: callers hand it a `process`
callable and which exception types are purgatory-worthy. Everything is
also drivable synchronously (`drain`) so tests exercise queue semantics
without threads; `start`/`stop` add the paced background loop, joined by
`Node.close()`.
"""

from __future__ import annotations

import heapq
import threading
import time

from ..utils import locks, log, metric


class ReplicaQueue:
    """Priority queue of work items with typed-error purgatory.

    - `maybe_add(item, priority)` dedups by item (highest priority wins).
    - `process_one()` pops the top item and runs `process(item)`. A
      purgatory-typed failure parks the item for retry with exponential
      backoff; any other exception counts a failure and drops the item
      (the queue must never die to one bad range).
    - `drain()` processes everything currently queued; with
      `force_purgatory=True` it also retries parked items regardless of
      their backoff deadline (deterministic tests).
    """

    def __init__(self, name: str, process, interval_s: float = 1.0,
                 purgatory_errors: tuple = (),
                 purgatory_interval_s: float = 5.0,
                 max_backoff_s: float = 60.0,
                 registry: metric.Registry = metric.DEFAULT,
                 clock=time.monotonic):
        self.name = name
        self.process = process
        self.interval_s = float(interval_s)
        self.purgatory_errors = tuple(purgatory_errors)
        self.purgatory_interval_s = float(purgatory_interval_s)
        self.max_backoff_s = float(max_backoff_s)
        self._clock = clock
        self._mu = locks.lock(f"kv.queue.{name}")
        self._heap: list[tuple[float, int, object]] = []  # (-prio, seq, item)
        self._queued: dict[object, float] = {}            # item -> priority
        self._purgatory: dict[object, tuple[int, float]] = {}  # (tries, due)
        self._seq = 0
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.processed = registry.counter(
            f"queue_{name}_processed", f"{name} queue items processed")
        self.failures = registry.counter(
            f"queue_{name}_failures", f"{name} queue items failed and dropped")
        self.purgatory_size = registry.gauge(
            f"queue_{name}_purgatory", f"{name} queue items parked for retry")
        self.pending = registry.gauge(
            f"queue_{name}_pending", f"{name} queue items awaiting processing")

    # -- enqueue ------------------------------------------------------------

    def maybe_add(self, item, priority: float = 0.0) -> bool:
        """Queue item unless already queued at >= priority or in purgatory
        (purgatory owns retries; re-adding would double-process)."""
        with self._mu:
            if item in self._purgatory:
                return False
            prev = self._queued.get(item)
            if prev is not None and prev >= priority:
                return False
            self._queued[item] = priority
            self._seq += 1
            heapq.heappush(self._heap, (-priority, self._seq, item))
            self.pending.set(len(self._queued))
            return True

    def __len__(self) -> int:
        with self._mu:
            return len(self._queued)

    def purgatory_len(self) -> int:
        with self._mu:
            return len(self._purgatory)

    # -- processing ---------------------------------------------------------

    def _pop(self):
        with self._mu:
            while self._heap:
                neg_prio, _, item = heapq.heappop(self._heap)
                # stale heap entry: item was re-added at a higher priority
                if self._queued.get(item) == -neg_prio:
                    del self._queued[item]
                    self.pending.set(len(self._queued))
                    return item
            return None

    def _run(self, item) -> None:
        try:
            self.process(item)
        except self.purgatory_errors as e:
            with self._mu:
                tries = self._purgatory.get(item, (0, 0.0))[0] + 1
                backoff = min(self.purgatory_interval_s * (2 ** (tries - 1)),
                              self.max_backoff_s)
                self._purgatory[item] = (tries, self._clock() + backoff)
                self.purgatory_size.set(len(self._purgatory))
            log.warning(log.OPS, "queue item sent to purgatory",
                        queue=self.name, item=str(item), tries=tries,
                        error=str(e))
        except Exception as e:  # crlint: allow-broad-except(queue processor drops the item with a failure metric + log)
            self.failures.inc()
            log.warning(log.OPS, "queue item dropped", queue=self.name,
                        item=str(item), error=str(e))
        else:
            self.processed.inc()
            with self._mu:
                self._purgatory.pop(item, None)
                self.purgatory_size.set(len(self._purgatory))

    def process_one(self) -> bool:
        item = self._pop()
        if item is None:
            return False
        self._run(item)
        return True

    def _retry_purgatory(self, force: bool = False) -> int:
        now = self._clock()
        with self._mu:
            due = [i for i, (_, when) in self._purgatory.items()
                   if force or when <= now]
        for item in due:
            self._run(item)
        return len(due)

    def drain(self, force_purgatory: bool = False) -> int:
        """Synchronously process everything queued (and, optionally, all
        of purgatory). Returns how many items were attempted."""
        n = 0
        while self.process_one():
            n += 1
        n += self._retry_purgatory(force=force_purgatory)
        return n

    # -- background loop ----------------------------------------------------

    def _loop(self) -> None:
        next_purgatory = self._clock() + self.purgatory_interval_s
        while not self._stop.is_set():
            if not self.process_one():
                self._stop.wait(self.interval_s)
            if self._clock() >= next_purgatory:
                self._retry_purgatory()
                next_purgatory = self._clock() + self.purgatory_interval_s

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name=f"queue-{self.name}", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
