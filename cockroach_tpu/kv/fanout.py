"""Changefeed fan-out plane — bounded subscriber tree with backpressure.

Reference: kvserver/rangefeed's processor + BufferedSender design. One
raft-apply stream (here: one hub poll loop over the engine's MVCC
history) demuxes to N registrations, each with its OWN bounded buffer,
so a slow or dead consumer can never wedge the emit path or starve its
peers. The CockroachDB discipline this module reduces:

- **node→changefeed→subscriber accounting**: every buffered event frame
  is charged to a per-subscriber BytesMonitor under the node's
  ``changefeed`` staging account (flow/memory.py's cache-level tree) —
  fan-out memory is visible and bounded, never ambient;
- **backpressure ladder** (the WeChat-style graceful degradation the
  admission plane applies at the SQL front door, applied per-consumer):
  buffer high-water → coalesce duplicate-key events to
  newest-version-per-key → shed the buffer entirely and re-feed the
  subscriber from a catch-up scan at its frontier → evict with a typed
  :class:`~..utils.errors.SlowConsumerError` carrying the frontier;
- **reconnect-from-frontier**: the per-subscriber resolved frontier only
  advances past events already on the wire, so a dropped client that
  re-dials with ``since=frontier`` resumes without loss; events between
  the frontier and the cut may re-deliver and deduplicate by (ts, key)
  — bit-identical to a direct ``changes_between`` scan after dedup;
- **liveness**: sends carry a deadline and idle connections heartbeat a
  resolved checkpoint, so a dead socket is detected within
  heartbeat + deadline and its sender thread reaped — never leaked.

Eviction never blocks the emit path: the poll loop only flags the
subscriber, drops its buffered (not in-flight) bytes and, for wedged
sockets, shuts the fd down — the sender thread observes the flag,
best-effort delivers a final ``{"error": "slow_consumer", "frontier"}``
frame, and cleans up after itself.

Same-process consumers (the materialized-view maintainer,
flow/viewmaint.py) register a :class:`LocalSubscriber` instead: no
socket and no sender thread, the poll loop buffers RAW ``(ts, key,
value|None)`` tuples under the same monitor accounting and backpressure
ladder, and the consumer drains with a ``peek()``/``ack()`` two-phase
protocol so a consumer that crashes mid-apply re-reads the identical
delta — the reconnect-from-frontier discipline without a wire.
"""

from __future__ import annotations

import bisect
import itertools
import json
import threading
import time
import weakref

from ..flow import memory as flowmem
from ..flow.dcn import _send_msg
from ..utils import faults, locks, log, metric, racesan, settings
from ..utils.errors import SlowConsumerError

# states of one registration in the tree
LIVE = "live"          # events flow through the bounded buffer
CATCHUP = "catchup"    # buffer was shed; next sender pass rescans the
                       # engine from the frontier instead
EVICTED = "evicted"    # terminal: SlowConsumerError recorded


class Subscriber:
    """One registration in the fan-out tree. All mutable state shared
    between the hub poll loop and this subscriber's sender thread is
    guarded by the hub's ``kv.fanout.state`` lock; the frontier and the
    hub's subscriber map are additionally racesan-instrumented."""

    def __init__(self, hub: "FanoutHub", sub_id: int, conn,
                 start: bytes | None, end: bytes | None, since: int,
                 raw: bool, on_close=None):
        self.hub = hub
        self.id = sub_id
        self.conn = conn
        self.start = start
        self.end = end
        self.raw = raw
        # frontier: the last resolved timestamp CHECKPOINTED to the
        # client — its exact reconnect point. Written by the sender,
        # read by the reaper/vtable, always under the hub state lock.
        self.frontier = int(since)
        # enq_frontier: span-local resolved timestamp up to which events
        # are either in the buffer (live) or recoverable by an engine
        # scan from `frontier` (catchup). Never advances past an
        # unresolved intent in the span.
        self.enq_frontier = int(since)
        self.state = CATCHUP  # first sender pass serves the catch-up scan
        self.evict_error: SlowConsumerError | None = None
        self.buf: list = []       # [(ts, key, payload, nbytes, t_enq)]
        self.queued_bytes = 0     # bytes in self.buf
        self.inflight_bytes = 0   # bytes taken by the sender, not yet sent
        self.sheds_run = 0        # consecutive sheds without a full drain
        self.sent_events = 0
        self.coalesced = 0
        self.sheds = 0
        self.created_s = time.time()
        self.last_send_s = time.time()
        self.wake = threading.Event()
        self.on_close = on_close
        self.thread: threading.Thread | None = None
        self.mon = hub.mon.child(
            f"subscriber-{sub_id}",
            budget=int(settings.get("changefeed.fanout.buffer_bytes")),
            level="cache")

    def _in_span(self, key: bytes) -> bool:
        if self.start is not None and key < self.start:
            return False
        if self.end is not None and key >= self.end:
            return False
        return True

    # -- sender thread --------------------------------------------------

    def _run(self):
        hub = self.hub
        try:
            self.conn.settimeout(
                float(settings.get("changefeed.fanout.send_deadline_s")))
            while True:
                self.wake.wait(timeout=float(
                    settings.get("changefeed.fanout.heartbeat_s")))
                self.wake.clear()
                with hub._mu:
                    if self.state == EVICTED or hub._stop.is_set():
                        break
                    scan_lo = scan_hi = None
                    if self.state == CATCHUP:
                        racesan.note_read(self, "frontier")
                        scan_lo, scan_hi = self.frontier, self.enq_frontier
                        self.state = LIVE
                    batch, self.buf = self.buf, []
                    self.inflight_bytes += self.queued_bytes
                    self.queued_bytes = 0
                    resolved = self.enq_frontier
                if scan_hi is not None and scan_hi > scan_lo:
                    actual = self._send_catchup(scan_lo, scan_hi)
                    if actual < scan_hi:
                        # defensive: the rescan saw an intent below the
                        # watermark — pull the watermark back so the poll
                        # loop re-delivers rather than skips
                        with hub._mu:
                            self.enq_frontier = min(self.enq_frontier,
                                                    actual)
                        resolved = min(resolved, actual)
                self._send_batch(batch)
                self._maybe_checkpoint(resolved)
                with hub._mu:
                    if not self.buf and self.state == LIVE:
                        self.sheds_run = 0  # fully drained: ladder resets
        except OSError as e:
            # covers real socket errors, send-deadline timeouts, and
            # injected ConnectionError faults alike
            with hub._mu:
                hub._evict_locked(self, f"send failed: {e}")
        finally:
            err = self.evict_error
            if err is not None:
                # best-effort typed goodbye: a still-healthy-but-slow
                # consumer learns its exact resume point
                try:
                    self.conn.settimeout(1.0)
                    _send_msg(self.conn, json.dumps({
                        "error": "slow_consumer", "reason": err.reason,
                        "frontier": err.frontier}).encode("utf-8"))
                except OSError:
                    pass  # peer already gone; reconnect resumes anyway
            try:
                self.conn.close()
            except OSError:
                pass  # already severed by the reaper
            self.mon.close()  # releases any straggler bytes up the tree
            hub._remove(self)
            if self.on_close is not None:
                self.on_close()

    def _send_catchup(self, lo: int, hi: int) -> int:
        """Re-feed (lo, hi] from the engine — the shed consumer's path
        back to live. Returns the scan's actual resolved timestamp."""
        from .changefeed import changes_between

        events, resolved = changes_between(
            self.hub.db, lo, hi, self.start, self.end, raw=self.raw)
        if not events:
            return resolved
        payloads = [json.dumps(ev).encode("utf-8") for ev in events]
        total = sum(len(p) for p in payloads)
        # the rescan trades buffer residency for a transiently
        # re-materialized batch: charge it for the send's lifetime
        with flowmem.staged("changefeed", total):
            faults.fire("changefeed.subscriber.send")
            for p in payloads:
                _send_msg(self.conn, p)
        metric.CHANGEFEED_EVENTS_EMITTED.inc(len(payloads))
        with self.hub._mu:
            self.sent_events += len(payloads)
            self.last_send_s = time.time()
        return resolved

    def _send_batch(self, batch: list) -> None:
        if not batch:
            return
        total = sum(e[3] for e in batch)
        try:
            faults.fire("changefeed.subscriber.send")
            for _ts, _key, payload, _nb, _t0 in batch:
                _send_msg(self.conn, payload)
            done = time.monotonic()
            for *_rest, t0 in batch:
                metric.CHANGEFEED_SEND_LAG_SECONDS.observe(
                    max(0.0, done - t0))
            metric.CHANGEFEED_EVENTS_EMITTED.inc(len(batch))
            with self.hub._mu:
                self.sent_events += len(batch)
                self.last_send_s = time.time()
        finally:
            # exact accounting even when a send dies mid-batch: the
            # in-flight reservation is returned either way
            with self.hub._mu:
                self.inflight_bytes -= total
            self.mon.release(total)

    def _maybe_checkpoint(self, resolved: int) -> None:
        with self.hub._mu:
            racesan.note_read(self, "frontier")
            fr = self.frontier
            last = self.last_send_s
        hb = float(settings.get("changefeed.fanout.heartbeat_s"))
        if resolved <= fr and time.time() - last < hb:
            return
        faults.fire("changefeed.frontier.checkpoint")
        _send_msg(self.conn, json.dumps(
            {"resolved": max(resolved, fr)}).encode("utf-8"))
        with self.hub._mu:
            racesan.note_write(self, "frontier")
            self.frontier = max(resolved, fr)
            self.last_send_s = time.time()


class LocalSubscriber(Subscriber):
    """An in-process registration: no socket, no sender thread. The poll
    loop buffers raw ``(ts, key, value|None, nbytes, t_enq)`` tuples
    (monitor-charged like any frame) and the consumer drains them with
    :meth:`peek` / :meth:`ack` — two-phase so nothing is consumed until
    the consumer has durably applied it. Joins in CATCHUP like a socket
    subscriber: the first drain is the consumer's own engine scan from
    its frontier, after which the buffer takes over."""

    def __init__(self, hub: "FanoutHub", sub_id: int,
                 start: bytes | None, end: bytes | None, since: int):
        super().__init__(hub, sub_id, conn=None, start=start, end=end,
                         since=since, raw=True)

    def peek(self) -> tuple[list | None, int, float | None]:
        """Snapshot the buffered delta WITHOUT consuming it.

        Returns ``(events, resolved, oldest)`` where events is a list of
        ``(ts, key, value|None)`` in (ts, key) order, resolved is the
        span-local watermark they run up to, and oldest is the earliest
        buffered enqueue wall-time (monotonic) — the consumer's freshness
        lag anchor — or None when the buffer is empty. ``events is None``
        means the buffer was shed (or never primed): the engine holds the
        data, scan ``(frontier, resolved]`` yourself, then :meth:`ack`.
        """
        with self.hub._mu:
            racesan.note_read(self, "frontier")
            resolved = int(self.enq_frontier)
            if self.state == LIVE:
                oldest = self.buf[0][4] if self.buf else None
                return ([(e[0], e[1], e[2]) for e in self.buf],
                        resolved, oldest)
            return None, resolved, None

    def ack(self, upto: int) -> None:
        """Consume through ``upto`` after the delta has been applied.
        Buffered events at or below ``upto`` drop (bytes released); a
        shed/evicted registration rejoins LIVE with its watermark pulled
        back to exactly ``upto`` so the poll loop re-delivers everything
        past what was actually applied — never a gap."""
        with self.hub._mu:
            racesan.note_write(self, "frontier")
            self.frontier = max(self.frontier, int(upto))
            if self.state == LIVE:
                keep = [e for e in self.buf if e[0] > upto]
                kept_bytes = sum(e[3] for e in keep)
                released = self.queued_bytes - kept_bytes
                if released > 0:
                    self.mon.release(released)
                self.buf = keep
                self.queued_bytes = kept_bytes
            else:
                self.state = LIVE
                self.evict_error = None
                self.enq_frontier = int(upto)
            self.sheds_run = 0
            self.last_send_s = time.time()

    def close(self) -> None:
        """Deregister: drop buffered bytes, close the monitor, leave the
        tree. The senderless analog of the sender thread's finally."""
        with self.hub._mu:
            self.state = EVICTED
            self.mon.release(self.queued_bytes)
            self.buf = []
            self.queued_bytes = 0
        self.mon.close()
        self.hub._remove(self)


class FanoutHub:
    """The subscriber tree: ONE poll loop over the engine demuxes
    committed versions to every registration; per-subscriber sender
    threads drain the bounded buffers. See the module docstring for the
    backpressure ladder and eviction semantics."""

    def __init__(self, db, poll_interval_s: float = 0.05,
                 name: str = "rangefeed"):
        self.db = db
        self.name = name
        self.poll_interval_s = poll_interval_s
        self.mon = flowmem.staging_monitor("changefeed")
        # hub frontier: GLOBAL resolved timestamp (below every unresolved
        # intent anywhere) — the join watermark for new subscribers
        self.frontier = 0
        self._subs: dict[int, Subscriber] = {}
        self._ids = itertools.count(1)
        self._mu = locks.lock("kv.fanout.state")
        self._stop = threading.Event()
        with _hubs_mu:
            _HUBS.add(self)
        self._poller = threading.Thread(target=self._poll_loop,
                                        name="fanout-poller", daemon=True)
        self._poller.start()

    # -- registration ----------------------------------------------------

    def add_subscriber(self, conn, start: bytes | None = None,
                       end: bytes | None = None, since: int = 0,
                       raw: bool = False, on_close=None,
                       start_sender: bool = True) -> Subscriber | None:
        """Register a connection in the tree; returns None when the tree
        is at ``changefeed.fanout.max_subscribers`` (bounded: refuse the
        newcomer rather than degrade everyone) or the hub is closing.
        ``start_sender=False`` is a test seam: the registration exists
        but nothing drains it."""
        with self._mu:
            racesan.note_read(self, "_subs")
            limit = int(settings.get("changefeed.fanout.max_subscribers"))
            if self._stop.is_set() or len(self._subs) >= limit:
                return None
            sub = Subscriber(self, next(self._ids), conn, start, end,
                             since, raw, on_close=on_close)
            # join at the hub frontier: the catch-up scan covers
            # (since, frontier]; the poll loop covers everything after
            racesan.note_read(self, "frontier")
            sub.enq_frontier = max(sub.enq_frontier, self.frontier)
            racesan.note_write(self, "_subs")
            self._subs[sub.id] = sub
            metric.CHANGEFEED_SUBSCRIBERS.set(len(self._subs))
        if start_sender:
            t = threading.Thread(target=sub._run, daemon=True,
                                 name=f"fanout-sender-{sub.id}")
            sub.thread = t
            t.start()
        sub.wake.set()  # serve the catch-up scan promptly
        return sub

    def add_local(self, start: bytes | None = None,
                  end: bytes | None = None,
                  since: int = 0) -> LocalSubscriber | None:
        """Register an in-process consumer (no socket, no sender). Same
        admission bound as wire subscribers; None when full/closing."""
        with self._mu:
            racesan.note_read(self, "_subs")
            limit = int(settings.get("changefeed.fanout.max_subscribers"))
            if self._stop.is_set() or len(self._subs) >= limit:
                return None
            sub = LocalSubscriber(self, next(self._ids), start, end, since)
            racesan.note_read(self, "frontier")
            sub.enq_frontier = max(sub.enq_frontier, self.frontier)
            racesan.note_write(self, "_subs")
            self._subs[sub.id] = sub
            metric.CHANGEFEED_SUBSCRIBERS.set(len(self._subs))
        return sub

    def _remove(self, sub: Subscriber) -> None:
        with self._mu:
            racesan.note_write(self, "_subs")
            self._subs.pop(sub.id, None)
            metric.CHANGEFEED_SUBSCRIBERS.set(len(self._subs))

    # -- the emit path ---------------------------------------------------

    def _poll_loop(self):
        while not self._stop.is_set():
            try:
                self._poll_once()
            except Exception as e:  # crlint: allow-broad-except(one bad poll must not kill every subscriber; logged)
                log.warning(log.OPS, "fanout poll failed", error=str(e))
            self._stop.wait(self.poll_interval_s)

    def _poll_once(self):
        from .changefeed import _scan, encode_event

        with self._mu:
            racesan.note_read(self, "_subs")
            subs = [s for s in self._subs.values() if s.state != EVICTED]
            lo = self.frontier
            for s in subs:
                lo = min(lo, s.enq_frontier)
        if not subs:
            return  # idle hub: don't scan, don't advance the frontier
        now = self.db.clock.now()
        versions, intents = _scan(self.db, lo, now)
        g_resolved = int(now)
        for its, _ikey in intents:
            g_resolved = min(g_resolved, int(its) - 1)
        ts_order = [v[0] for v in versions]  # sorted by (ts, key)
        enc_cache: dict[tuple[int, bool], bytes] = {}
        t_enq = time.monotonic()
        deadline = float(settings.get("changefeed.fanout.send_deadline_s"))
        tnow = time.time()
        wake: list[Subscriber] = []
        dead: list[Subscriber] = []
        with self._mu:
            racesan.note_write(self, "frontier")
            self.frontier = max(self.frontier, g_resolved)
            for sub in subs:
                if sub.state == EVICTED:
                    continue
                # span-local resolved: only intents INSIDE the span hold
                # this subscriber's frontier back
                sub_resolved = int(now)
                for its, ikey in intents:
                    if sub._in_span(ikey):
                        sub_resolved = min(sub_resolved, int(its) - 1)
                sub_resolved = max(sub_resolved, sub.enq_frontier)
                if sub.state == CATCHUP:
                    # shed subscriber: the engine holds its data — just
                    # advance the watermark the rescan will cover
                    sub.enq_frontier = sub_resolved
                    wake.append(sub)
                    continue
                batch = []
                i = bisect.bisect_right(ts_order, sub.enq_frontier)
                j = bisect.bisect_right(ts_order, sub_resolved)
                for k in range(i, j):
                    ts, key, _val = versions[k]
                    if not sub._in_span(key):
                        continue
                    if sub.conn is None:
                        # local consumer: raw tuple, no JSON frame; the
                        # charge approximates the buffered tuple footprint
                        val = versions[k][2]
                        nb = (len(key) + (0 if val is None else len(val))
                              + 48)
                        batch.append((ts, key, val, nb, t_enq))
                        continue
                    ck = (k, sub.raw)
                    payload = enc_cache.get(ck)
                    if payload is None:
                        ev = encode_event(ts, key, versions[k][2], sub.raw)
                        payload = json.dumps(ev).encode("utf-8")
                        enc_cache[ck] = payload
                    batch.append((ts, key, payload, len(payload), t_enq))
                advanced = sub_resolved > sub.enq_frontier
                sub.enq_frontier = sub_resolved
                if batch:
                    self._enqueue_locked(sub, batch)
                if batch or advanced:
                    wake.append(sub)
            # liveness reaper: pending-or-idle makes no difference — a
            # healthy sender heartbeats, so a stale last_send means a
            # dead socket or a wedged consumer
            for sub in subs:
                if sub.state == EVICTED or sub.conn is None:
                    # local consumers have no socket to go dead; their
                    # ladder ends at shed->catch-up, never the reaper
                    continue
                racesan.note_read(sub, "frontier")
                if tnow - sub.last_send_s > deadline:
                    self._evict_locked(
                        sub, f"no successful send in {deadline:.1f}s")
                    dead.append(sub)
        for sub in dead:
            # unstick a sender blocked inside send(): shutdown is
            # non-blocking, the blocked call returns with an error
            try:
                import socket as _socket
                sub.conn.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass  # already closed
        for sub in wake:
            sub.wake.set()
        metric.CHANGEFEED_BUFFER_BYTES.set(self.mon.used)

    # the backpressure ladder (all rungs run under self._mu; none of them
    # touches the subscriber's socket — eviction never blocks the emit path)

    def _enqueue_locked(self, sub: Subscriber, batch: list) -> None:
        try:
            faults.fire("changefeed.fanout.enqueue")
        except faults.InjectedFault:
            # the batch never reached the buffer: shed so the rescan
            # re-covers it from the engine — no gap, no leaked bytes
            self._shed_locked(sub)
            return
        budget = int(settings.get("changefeed.fanout.buffer_bytes"))
        high = budget * float(
            settings.get("changefeed.fanout.highwater_frac"))
        incoming = sum(e[3] for e in batch)
        if sub.queued_bytes + sub.inflight_bytes + incoming > high:
            batch = self._coalesce_locked(sub, batch)
            incoming = 0  # batch absorbed into the coalesced queue
        if sub.queued_bytes + sub.inflight_bytes + incoming > budget:
            max_sheds = int(
                settings.get("changefeed.fanout.max_consecutive_sheds"))
            if sub.sheds_run >= max_sheds:
                self._evict_locked(
                    sub, f"{sub.sheds_run} consecutive sheds "
                         "without draining")
            else:
                self._shed_locked(sub)
            return
        if batch:
            sub.buf.extend(batch)
            sub.queued_bytes += incoming
            # force=True: the ladder is the bound; accounting must never
            # raise inside the emit path
            sub.mon.reserve(incoming, force=True)

    def _coalesce_locked(self, sub: Subscriber, batch: list) -> list:
        """Rung one: newest-version-per-key over queue + incoming batch.
        The subscriber still observes the latest value of every key (and
        every checkpoint); superseded intermediate versions drop."""
        combined = sub.buf + batch
        seen: set[bytes] = set()
        kept: list = []
        for e in reversed(combined):
            if e[1] in seen:
                continue
            seen.add(e[1])
            kept.append(e)
        kept.reverse()
        dropped = len(combined) - len(kept)
        if dropped:
            sub.coalesced += dropped
            metric.CHANGEFEED_EVENTS_COALESCED.inc(dropped)
        kept_bytes = sum(e[3] for e in kept)
        delta = kept_bytes - sub.queued_bytes
        if delta > 0:
            sub.mon.reserve(delta, force=True)
        elif delta < 0:
            sub.mon.release(-delta)
        sub.buf = kept
        sub.queued_bytes = kept_bytes
        return []

    def _shed_locked(self, sub: Subscriber) -> None:
        """Rung two: drop the buffer, re-feed from the engine. The
        client re-receives events since its last checkpoint (dedup by
        (ts, key)) — never a gap."""
        sub.mon.release(sub.queued_bytes)
        sub.buf = []
        sub.queued_bytes = 0
        sub.state = CATCHUP
        sub.sheds += 1
        sub.sheds_run += 1
        metric.CHANGEFEED_SHEDS.inc()

    def _evict_locked(self, sub: Subscriber, reason: str) -> None:
        """Terminal rung: typed eviction. Only flags + drops queued
        bytes — the sender thread does the socket goodbye and cleanup."""
        if sub.state == EVICTED:
            return
        racesan.note_read(sub, "frontier")
        sub.evict_error = SlowConsumerError(sub.id, reason,
                                            frontier=sub.frontier)
        sub.state = EVICTED
        sub.mon.release(sub.queued_bytes)
        sub.buf = []
        sub.queued_bytes = 0
        metric.CHANGEFEED_EVICTIONS.inc()
        sub.wake.set()

    # -- introspection / shutdown ---------------------------------------

    def rows(self) -> list[dict]:
        """Snapshot of every registration (vtable / admin endpoint)."""
        out = []
        tnow = time.time()
        with self._mu:
            racesan.note_read(self, "_subs")
            for sub in self._subs.values():
                racesan.note_read(sub, "frontier")
                out.append({
                    "hub": self.name,
                    "subscriber_id": sub.id,
                    "state": sub.state,
                    "span_start": (sub.start or b"").decode("utf-8",
                                                            "replace"),
                    "span_end": (sub.end or b"").decode("utf-8",
                                                        "replace"),
                    "frontier": int(sub.frontier),
                    "buffered_bytes": int(sub.queued_bytes
                                          + sub.inflight_bytes),
                    "buffered_events": len(sub.buf),
                    "sent_events": int(sub.sent_events),
                    "coalesced": int(sub.coalesced),
                    "sheds": int(sub.sheds),
                    "age_s": tnow - sub.created_s,
                })
        return out

    def close(self) -> None:
        """Stop the poll loop, sever every subscriber, join the sender
        threads — after this the no-leak census sees neither threads nor
        sockets nor retained monitor bytes."""
        import socket as _socket

        self._stop.set()
        if self._poller is not threading.current_thread():
            self._poller.join(timeout=5)
        with self._mu:
            racesan.note_read(self, "_subs")
            subs = list(self._subs.values())
        for sub in subs:
            sub.wake.set()
            if sub.conn is None:
                continue  # local registration: no socket to sever
            try:
                sub.conn.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass  # never connected or already gone
        for sub in subs:
            t = sub.thread
            if t is not None and t is not threading.current_thread():
                t.join(timeout=5)
            else:
                # test-seam registration without a sender: clean up here
                sub.mon.close()
                self._remove(sub)
        with _hubs_mu:
            _HUBS.discard(self)


# -- process-global hub registry (vtable / admin endpoint / gauges) ---------

_hubs_mu = locks.lock("kv.fanout.hubs")
_HUBS: "weakref.WeakSet[FanoutHub]" = weakref.WeakSet()


def hubs() -> list[FanoutHub]:
    with _hubs_mu:
        return [h for h in _HUBS if not h._stop.is_set()]


def subscriber_rows() -> list[dict]:
    """All registrations across every live hub on this node."""
    out: list[dict] = []
    for h in hubs():
        out.extend(h.rows())
    return out


def refresh_gauges() -> None:
    """Re-publish fan-out gauges (the background metrics scraper calls
    this so a quiet node still exports truthful values)."""
    total = 0
    for h in hubs():
        with h._mu:
            racesan.note_read(h, "_subs")
            total += len(h._subs)
    metric.CHANGEFEED_SUBSCRIBERS.set(total)
    metric.CHANGEFEED_BUFFER_BYTES.set(
        flowmem.staging_monitor("changefeed").used)
