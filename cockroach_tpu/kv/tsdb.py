"""Internal timeseries DB — the pkg/ts reduction.

Reference: ts/db.go:69 stores 10s-resolution metric samples in the KV
keyspace under per-(name, resolution, slab) keys, downsamples on read, and
feeds the admin UI charts. Here the same store-metrics-in-KV discipline:

- ``record`` snapshots a metric Registry's counters/gauges into one KV row
  per (metric, timestamp-slab);
- ``query`` returns the per-sample series for a metric over a wall-clock
  range, with optional downsampling (avg/max per bucket);
- retention trims slabs older than a cutoff (the ts maintenance queue's
  pruning role).

Keys are NUL-free ASCII: \\x01ts<name>\\x00-free|<slab millis %013d>.
"""

from __future__ import annotations

import struct

from . import hlc
from .txn import DB

_PREFIX = b"\x01ts"
_SAMPLE = struct.Struct("<qd")  # wall_ms, value


def _key(name: str, wall_ms: int) -> bytes:
    safe = name.replace("|", "_").encode("utf-8")
    # clamp to the fixed 13-digit field: a wider timestamp (e.g. the 1<<60
    # open-interval default) would render as more digits and sort BELOW
    # real samples, silently emptying range scans
    wall_ms = min(max(wall_ms, 0), 10 ** 13 - 1)
    return _PREFIX + safe + b"|" + b"%013d" % wall_ms


class TimeSeriesDB:
    """Metric samples in the KV store (one sample per row; slab packing
    arrives with volume)."""

    def __init__(self, db: DB):
        self.db = db

    def record(self, registry, names: list[str] | None = None) -> int:
        """Snapshot counters/gauges (and histogram _count/_sum series —
        enough to chart rates and means) from a metric.Registry at now()."""
        from ..utils import metric as metric_mod

        wall, _ = hlc.unpack(self.db.clock.now())
        n = 0
        for mname, m in registry._metrics.items():
            if names is not None and mname not in names:
                continue
            if isinstance(m, (metric_mod.Counter, metric_mod.Gauge)):
                self.db.put(_key(mname, wall),
                            _SAMPLE.pack(wall, float(m.value)))
                n += 1
            elif isinstance(m, metric_mod.Histogram):
                self.db.put(_key(mname + "_count", wall),
                            _SAMPLE.pack(wall, float(m.n)))
                self.db.put(_key(mname + "_sum", wall),
                            _SAMPLE.pack(wall, float(m.sum)))
                n += 2
            elif isinstance(m, (metric_mod.LabeledCounter,
                                metric_mod.LabeledGauge)):
                # one series per observed label value (per-tenant tokens,
                # per-lane queue depth): name.<label_value>, charted like
                # any scalar series
                for k, v in m.items():
                    self.db.put(_key(f"{mname}.{k}", wall),
                                _SAMPLE.pack(wall, float(v)))
                    n += 1
        return n

    def query(self, name: str, start_ms: int = 0,
              end_ms: int = 1 << 60) -> list[tuple[int, float]]:
        rows = self.db.scan(_key(name, start_ms), _key(name, end_ms))
        out = []
        for _, v in rows:
            wall, val = _SAMPLE.unpack(v[:_SAMPLE.size])
            out.append((wall, val))
        return out

    def downsample(self, name: str, bucket_ms: int, agg: str = "avg",
                   start_ms: int = 0, end_ms: int = 1 << 60
                   ) -> list[tuple[int, float]]:
        """Per-bucket avg/max/last (the read-side downsampler)."""
        buckets: dict[int, list[float]] = {}
        for wall, val in self.query(name, start_ms, end_ms):
            buckets.setdefault(wall // bucket_ms * bucket_ms, []).append(val)
        out = []
        for b in sorted(buckets):
            vals = buckets[b]
            if agg == "avg":
                out.append((b, sum(vals) / len(vals)))
            elif agg == "max":
                out.append((b, max(vals)))
            else:
                out.append((b, vals[-1]))
        return out

    def prune(self, name: str, keep_after_ms: int) -> int:
        """Drop samples older than the cutoff (retention maintenance)."""
        rows = self.db.scan(_key(name, 0), _key(name, keep_after_ms))
        for k, _ in rows:
            self.db.delete(k)
        return len(rows)

    def prune_all(self, keep_after_ms: int) -> int:
        """Retention sweep over EVERY series: drop samples below the
        cutoff. The background scraper (server/node.py _metrics_loop)
        calls this on a paced ticker driven by ``ts.retention_seconds``,
        so the timeseries keyspace stays bounded on long-lived nodes."""
        n = 0
        for k, _ in self.db.scan(_PREFIX, _PREFIX + b"\xff"):
            try:
                wall = int(k[-13:])  # key tail: "|<13-digit millis>"
            except ValueError:
                continue
            if wall < keep_after_ms:
                self.db.delete(k)
                n += 1
        return n
