"""Changefeeds — the changefeedccl reduction (CDC over MVCC history).

Reference: a changefeed is a job whose processors tail rangefeeds
(kvclient/rangefeed over MuxRangeFeed), encode changed rows, push them to a
sink (kafka/cloud/webhook), and checkpoint a RESOLVED timestamp frontier
into the job record so restarts resume without loss or duplication. Here
the same loop over the engine's retained MVCC versions:

- ``Engine`` history IS the feed source: ``changes_between(lo, hi)`` lists
  committed versions in (lo, hi] for a span (the catch-up scan shape,
  kvserver/rangefeed/catchup_scan.go — polling stands in for the push
  plumbing until the DCN server carries subscriptions);
- events encode as JSON lines {key, value|null, ts} (the wire envelope);
- the feed runs as a JOB: each poll emits events then checkpoints
  ``resolved`` — crash + re-adoption resumes from the frontier, exactly
  once per version (verified in tests).
"""

from __future__ import annotations

import base64
import json

import numpy as np

from ..storage import keys as K
from ..utils import locks
from .jobs import Job, Registry
from .txn import DB


def changes_between(db: DB, lo_ts: int, hi_ts: int,
                    start: bytes | None = None,
                    end: bytes | None = None,
                    raw: bool = False) -> tuple[list[dict], int]:
    """Committed versions with lo_ts < ts <= RESOLVED in [start, end),
    ordered by (ts, key), plus the RESOLVED frontier itself — the catch-up
    scan with the closed-timestamp discipline (kvserver/closedts): the
    frontier must not advance past an UNRESOLVED intent in the span, or its
    eventual commit timestamp would fall behind an already-emitted resolved
    checkpoint and the event would be skipped forever. Tombstones emit
    value None. Returns (events, resolved)."""
    eng = db.engine
    view = eng._merged_view()  # overlays the memtable; read-only
    if view is None:
        return [], hi_ts
    mask = np.asarray(view.mask)
    ts = np.asarray(view.ts)
    txn = np.asarray(view.txn)
    in_span = mask
    if start is not None or end is not None:
        # vectorized bound compare: pack key bytes into big-endian uint64
        # word lanes (the engine's own key-order encoding) and compare
        # lexicographically word by word — no per-row Python loop on the
        # hot poll path
        keys_np = np.ascontiguousarray(np.asarray(view.key))
        n, kw = keys_np.shape
        shifts = (np.arange(7, -1, -1, dtype=np.uint64)
                  * np.uint64(8))
        words = (keys_np.reshape(n, kw // 8, 8).astype(np.uint64)
                 << shifts).sum(axis=-1, dtype=np.uint64)

        def bound_words(b: bytes):
            bb = np.frombuffer(b.ljust(kw, b"\x00"), dtype=np.uint8)
            return (bb.reshape(kw // 8, 8).astype(np.uint64)
                    << shifts).sum(axis=-1, dtype=np.uint64)

        def cmp_ge(bw):
            ge = np.zeros(n, dtype=bool)
            eq = np.ones(n, dtype=bool)
            for j in range(words.shape[1]):
                ge |= eq & (words[:, j] > bw[j])
                eq &= words[:, j] == bw[j]
            return ge | eq

        if start is not None:
            in_span = in_span & cmp_ge(bound_words(bytes(start)))
        if end is not None:
            in_span = in_span & ~cmp_ge(bound_words(bytes(end)))
    # the resolved frontier holds below the oldest unresolved intent
    intents = in_span & (txn != 0)
    resolved = int(hi_ts)
    if intents.any():
        resolved = min(resolved, int(ts[intents].min()) - 1)
    sel = in_span & (txn == 0) & (ts > lo_ts) & (ts <= resolved)
    idx = np.nonzero(sel)[0]
    if len(idx) == 0:
        return [], resolved
    keys = K.decode_keys(np.asarray(view.key)[idx])
    vals = np.asarray(view.value)[idx]
    vlens = np.asarray(view.vlen)[idx]
    tombs = np.asarray(view.tomb)[idx]
    out = []
    for k, v, n, tomb, t in zip(keys, vals, vlens, tombs, ts[idx]):
        if raw:
            # byte-exact encoding (base64): physical replication must
            # reproduce keys/values verbatim, not a lossy utf-8 view
            ev = {
                "k64": base64.b64encode(k).decode("ascii"),
                "v64": (None if tomb
                        else base64.b64encode(bytes(v[:n])).decode("ascii")),
                "ts": int(t),
            }
        else:
            ev = {
                "key": k.decode("utf-8", "replace"),
                "value": None if tomb else bytes(v[:n]).decode("utf-8",
                                                               "replace"),
                "ts": int(t),
            }
        # sort on the ORIGINAL key bytes (base64's ascii order does not
        # preserve byte order, and a b"" key is falsy)
        out.append((int(t), bytes(k), ev))
    out.sort(key=lambda e: e[:2])
    return [ev for _, _, ev in out], resolved


class FileSink:
    """JSON-lines sink (the cloud-storage sink reduction)."""

    def __init__(self, path: str):
        self.path = path

    def emit(self, events: list[dict]) -> None:
        with open(self.path, "a") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")


def register_changefeed_job(registry: Registry, polls: int = 1) -> None:
    """Changefeed as a jobs.Resumer: each poll emits (resolved, now] events
    to the sink then checkpoints the new resolved frontier."""

    def resume(reg: Registry, job: Job):
        sink = FileSink(job.payload["sink"])
        start = job.payload.get("start")
        end = job.payload.get("end")
        s = start.encode() if isinstance(start, str) else start
        e = end.encode() if isinstance(end, str) else end
        for _ in range(job.payload.get("polls", polls)):
            resolved = job.progress.get("resolved", 0)
            now = reg.db.clock.now()
            events, new_resolved = changes_between(
                reg.db, resolved, now, s, e)
            if events:
                sink.emit(events)
            # the frontier never regresses: a txn that began before the
            # last checkpoint may lay intents below it, but re-emitting
            # (old_resolved, new_resolved] would duplicate events
            job.progress["resolved"] = max(resolved, new_resolved)
            reg.checkpoint(job)  # frontier checkpoint: resume point
        return {"resolved": job.progress["resolved"]}

    registry.register("changefeed", resume)


class RangefeedServer:
    """Push rangefeed events over the DCN framing — the MuxRangeFeed
    reduction (kvpb api.proto:3700): a subscriber names a span and a start
    timestamp; the server streams JSON event frames as new versions commit
    (poll-driven tailer standing in for the raft-apply hook), interleaved
    with resolved-timestamp checkpoints."""

    def __init__(self, db: DB, poll_interval_s: float = 0.05,
                 port: int = 0):
        import socket
        import threading

        self.db = db
        self.poll_interval_s = poll_interval_s
        # explicit port so a restarted source rebinds the SAME address —
        # the replication stream's reconnect contract needs a stable
        # endpoint to re-dial (create_server sets SO_REUSEADDR on POSIX)
        self._srv = socket.create_server(("127.0.0.1", port))
        self._srv.settimeout(0.2)
        self.addr = self._srv.getsockname()
        self._stop = threading.Event()
        # track accepted conns so close() severs them: a restart on the
        # same port must not collide with a previous incarnation's
        # still-established subscriber sockets
        self._conns: set = set()
        self._conns_lock = locks.lock("kv.changefeed.conns")
        self._accept_thread = threading.Thread(target=self._serve,
                                               daemon=True)
        self._accept_thread.start()

    def _serve(self):
        import socket
        import threading

        from ..flow.dcn import _recv_msg

        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # server socket closed
            with self._conns_lock:
                if self._stop.is_set():
                    conn.close()
                    return
                self._conns.add(conn)
            threading.Thread(target=self._handshake, args=(conn,),
                             daemon=True).start()

    def _handshake(self, conn):
        """Per-connection handshake off the accept loop: a slow, broken or
        malicious client can neither stall new subscriptions nor kill the
        server thread."""
        from ..flow.dcn import _recv_msg

        try:
            conn.settimeout(10.0)
            msg = _recv_msg(conn)
            if msg is None:
                raise ConnectionError("empty handshake")
            req = json.loads(msg.decode("utf-8"))
            conn.settimeout(None)
        except (OSError, ValueError, ConnectionError):
            conn.close()
            with self._conns_lock:
                self._conns.discard(conn)
            return
        self._tail(conn, req)

    def _tail(self, conn, req):
        from ..flow.dcn import _send_msg

        start = req.get("start")
        end = req.get("end")
        s = start.encode() if isinstance(start, str) else start
        e = end.encode() if isinstance(end, str) else end
        resolved = int(req.get("since", 0))
        raw = bool(req.get("raw", False))
        try:
            while not self._stop.is_set():
                now = self.db.clock.now()
                events, new_resolved = changes_between(
                    self.db, resolved, now, s, e, raw=raw)
                for ev in events:
                    _send_msg(conn, json.dumps(ev).encode("utf-8"))
                resolved = max(resolved, new_resolved)  # never regress
                _send_msg(conn, json.dumps(
                    {"resolved": resolved}).encode("utf-8"))
                self._stop.wait(self.poll_interval_s)
        except OSError:
            pass  # subscriber went away
        finally:
            conn.close()
            with self._conns_lock:
                self._conns.discard(conn)

    def close(self):
        import socket
        import threading

        self._stop.set()
        self._srv.close()
        # join the accept loop: the kernel holds the listening socket
        # open while a thread sits in accept()'s poll window, so a
        # restart on the same port would EADDRINUSE until it exits
        if self._accept_thread is not threading.current_thread():
            self._accept_thread.join(timeout=5)
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            c.close()


def subscribe_rangefeed(addr, start=None, end=None, since: int = 0,
                        raw: bool = False):
    """Dial a RangefeedServer; returns (socket, iterator of frames).
    Frames are events ({key, value, ts} — or byte-exact {k64, v64, ts}
    with raw=True) or checkpoints ({resolved})."""
    import socket

    from ..flow.dcn import _recv_msg, _send_msg
    from ..utils import faults

    # chaos site: a failed (re)subscription — the rangefeed restart path
    # consumers must retry through (kvclient/rangefeed restart-on-error)
    faults.fire("kv.rangefeed.subscribe")
    sock = socket.create_connection(tuple(addr))
    _send_msg(sock, json.dumps({
        "start": start.decode() if isinstance(start, bytes) else start,
        "end": end.decode() if isinstance(end, bytes) else end,
        "since": since,
        "raw": raw,
    }).encode("utf-8"))

    def frames():
        while True:
            try:
                msg = _recv_msg(sock)
            except (ConnectionError, OSError):
                return  # server closed the stream: end of feed
            if msg is None:
                return
            yield json.loads(msg.decode("utf-8"))

    return sock, frames()
