"""Changefeeds — the changefeedccl reduction (CDC over MVCC history).

Reference: a changefeed is a job whose processors tail rangefeeds
(kvclient/rangefeed over MuxRangeFeed), encode changed rows, push them to a
sink (kafka/cloud/webhook), and checkpoint a RESOLVED timestamp frontier
into the job record so restarts resume without loss or duplication. Here
the same loop over the engine's retained MVCC versions:

- ``Engine`` history IS the feed source: ``changes_between(lo, hi)`` lists
  committed versions in (lo, hi] for a span (the catch-up scan shape,
  kvserver/rangefeed/catchup_scan.go — polling stands in for the push
  plumbing until the DCN server carries subscriptions);
- events encode as JSON lines {key, value|null, ts} (the wire envelope);
- the feed runs as a JOB: each poll emits events then checkpoints
  ``resolved`` — crash + re-adoption resumes from the frontier, exactly
  once per version (verified in tests).
"""

from __future__ import annotations

import json

import numpy as np

from ..storage import keys as K
from .jobs import Job, Registry
from .txn import DB


def changes_between(db: DB, lo_ts: int, hi_ts: int,
                    start: bytes | None = None,
                    end: bytes | None = None) -> list[dict]:
    """Committed versions with lo_ts < ts <= hi_ts in [start, end), ordered
    by (ts, key) — the catch-up scan. Tombstones emit value None."""
    eng = db.engine
    eng.flush_mem_only()
    view = eng._merged_view()
    if view is None:
        return []
    mask = np.asarray(view.mask)
    ts = np.asarray(view.ts)
    txn = np.asarray(view.txn)
    sel = mask & (txn == 0) & (ts > lo_ts) & (ts <= hi_ts)
    if start is not None or end is not None:
        keys_np = np.asarray(view.key)
        raw = [bytes(k).rstrip(b"\x00") for k in keys_np]
        inr = np.array([
            (start is None or k >= start) and (end is None or k < end)
            for k in raw
        ])
        sel = sel & inr
    idx = np.nonzero(sel)[0]
    if len(idx) == 0:
        return []
    keys = K.decode_keys(np.asarray(view.key)[idx])
    vals = np.asarray(view.value)[idx]
    vlens = np.asarray(view.vlen)[idx]
    tombs = np.asarray(view.tomb)[idx]
    out = []
    for k, v, n, tomb, t in zip(keys, vals, vlens, tombs, ts[idx]):
        out.append({
            "key": k.decode("utf-8", "replace"),
            "value": None if tomb else bytes(v[:n]).decode("utf-8",
                                                           "replace"),
            "ts": int(t),
        })
    out.sort(key=lambda e: (e["ts"], e["key"]))
    return out


class FileSink:
    """JSON-lines sink (the cloud-storage sink reduction)."""

    def __init__(self, path: str):
        self.path = path

    def emit(self, events: list[dict]) -> None:
        with open(self.path, "a") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")


def register_changefeed_job(registry: Registry, polls: int = 1) -> None:
    """Changefeed as a jobs.Resumer: each poll emits (resolved, now] events
    to the sink then checkpoints the new resolved frontier."""

    def resume(reg: Registry, job: Job):
        sink = FileSink(job.payload["sink"])
        start = job.payload.get("start")
        end = job.payload.get("end")
        s = start.encode() if isinstance(start, str) else start
        e = end.encode() if isinstance(end, str) else end
        for _ in range(job.payload.get("polls", polls)):
            resolved = job.progress.get("resolved", 0)
            now = reg.db.clock.now()
            events = changes_between(reg.db, resolved, now, s, e)
            if events:
                sink.emit(events)
            job.progress["resolved"] = now
            reg.checkpoint(job)  # frontier checkpoint: resume point
        return {"resolved": job.progress["resolved"]}

    registry.register("changefeed", resume)
