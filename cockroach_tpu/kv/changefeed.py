"""Changefeeds — the changefeedccl reduction (CDC over MVCC history).

Reference: a changefeed is a job whose processors tail rangefeeds
(kvclient/rangefeed over MuxRangeFeed), encode changed rows, push them to a
sink (kafka/cloud/webhook), and checkpoint a RESOLVED timestamp frontier
into the job record so restarts resume without loss or duplication. Here
the same loop over the engine's retained MVCC versions:

- ``Engine`` history IS the feed source: ``_scan(lo, hi)`` lists committed
  versions in (lo, hi] for a span plus the unresolved intents that hold
  the resolved frontier back (the catch-up scan shape,
  kvserver/rangefeed/catchup_scan.go);
- events encode as JSON lines {key, value|null, ts} (the wire envelope);
- the feed runs as a JOB: each poll emits events then checkpoints
  ``resolved`` — crash + re-adoption resumes from the frontier, exactly
  once per version (verified in tests);
- ``RangefeedServer`` pushes events over the DCN framing, demuxed through
  the bounded fan-out plane in :mod:`.fanout`: one poll loop feeds every
  subscriber's budgeted buffer, slow consumers walk the backpressure
  ladder (coalesce → shed-to-catch-up-scan → typed eviction), and a
  dropped client reconnects from its resolved frontier without loss.
"""

from __future__ import annotations

import base64
import json

import numpy as np

from ..flow import memory as flowmem
from ..storage import keys as K
from ..utils import locks
from .jobs import Job, Registry
from .txn import DB


def _scan(db: DB, lo_ts: int, hi_ts: int,
          start: bytes | None = None,
          end: bytes | None = None,
          ) -> tuple[list[tuple[int, bytes, bytes | None]],
                     list[tuple[int, bytes]]]:
    """Committed versions with lo_ts < ts <= hi_ts in [start, end) as
    (ts, key, value|None) tuples ordered by (ts, key) — value None is a
    tombstone — plus the span's UNRESOLVED intents as (ts, key). This is
    the raw demux feed for the fan-out hub; :func:`changes_between` folds
    the intent list into the resolved frontier (kvserver/closedts): the
    frontier must not advance past an unresolved intent, or its eventual
    commit would fall behind an already-emitted resolved checkpoint and
    the event would be skipped forever."""
    eng = db.engine
    # Take the snapshot under the store mutex (reentrant), like every
    # public Engine reader: _merged_view() consults and REFILLS the
    # overlay cache, so building it against a concurrent memtable append
    # or resolve_intents run-set rewrite doesn't just return a torn view
    # — it poisons the cache for every later reader (observed as
    # committed versions vanishing and an orphaned intent pinning the
    # resolved frontier forever). The returned block is immutable once
    # built; the mutex is released before the numpy crunching below.
    with eng.mu:
        view = eng._merged_view()  # overlays the memtable; read-only
    if view is None:
        return [], []
    mask = np.asarray(view.mask)
    ts = np.asarray(view.ts)
    txn = np.asarray(view.txn)
    in_span = mask
    if start is not None or end is not None:
        # vectorized bound compare: pack key bytes into big-endian uint64
        # word lanes (the engine's own key-order encoding) and compare
        # lexicographically word by word — no per-row Python loop on the
        # hot poll path. The packed-word scratch is the scan's big
        # transient allocation; charge it to the changefeed staging
        # account for the computation's lifetime.
        keys_np = np.ascontiguousarray(np.asarray(view.key))
        n, kw = keys_np.shape
        with flowmem.staged("changefeed", int(keys_np.size)):
            shifts = (np.arange(7, -1, -1, dtype=np.uint64)
                      * np.uint64(8))
            words = (keys_np.reshape(n, kw // 8, 8).astype(np.uint64)
                     << shifts).sum(axis=-1, dtype=np.uint64)

            def bound_words(b: bytes):
                bb = np.frombuffer(b.ljust(kw, b"\x00"), dtype=np.uint8)
                return (bb.reshape(kw // 8, 8).astype(np.uint64)
                        << shifts).sum(axis=-1, dtype=np.uint64)

            def cmp_ge(bw):
                ge = np.zeros(n, dtype=bool)
                eq = np.ones(n, dtype=bool)
                for j in range(words.shape[1]):
                    ge |= eq & (words[:, j] > bw[j])
                    eq &= words[:, j] == bw[j]
                return ge | eq

            if start is not None:
                in_span = in_span & cmp_ge(bound_words(bytes(start)))
            if end is not None:
                in_span = in_span & ~cmp_ge(bound_words(bytes(end)))
    intent_sel = in_span & (txn != 0)
    intents: list[tuple[int, bytes]] = []
    if intent_sel.any():
        ikeys = K.decode_keys(np.asarray(view.key)[intent_sel])
        intents = [(int(t), bytes(k))
                   for t, k in zip(ts[intent_sel], ikeys)]
    sel = in_span & (txn == 0) & (ts > lo_ts) & (ts <= hi_ts)
    idx = np.nonzero(sel)[0]
    if len(idx) == 0:
        return [], intents
    keys = K.decode_keys(np.asarray(view.key)[idx])
    vals = np.asarray(view.value)[idx]
    vlens = np.asarray(view.vlen)[idx]
    tombs = np.asarray(view.tomb)[idx]
    out: list[tuple[int, bytes, bytes | None]] = []
    for k, v, n, tomb, t in zip(keys, vals, vlens, tombs, ts[idx]):
        out.append((int(t), bytes(k),
                    None if tomb else bytes(v[:n])))
    out.sort(key=lambda e: e[:2])
    return out, intents


def encode_event(ts: int, key: bytes, value: bytes | None,
                 raw: bool = False) -> dict:
    """The wire envelope for one committed version. raw=True gives the
    byte-exact base64 encoding (physical replication must reproduce
    keys/values verbatim, not a lossy utf-8 view)."""
    if raw:
        return {
            "k64": base64.b64encode(key).decode("ascii"),
            "v64": (None if value is None
                    else base64.b64encode(value).decode("ascii")),
            "ts": int(ts),
        }
    return {
        "key": key.decode("utf-8", "replace"),
        "value": (None if value is None
                  else value.decode("utf-8", "replace")),
        "ts": int(ts),
    }


def changes_between(db: DB, lo_ts: int, hi_ts: int,
                    start: bytes | None = None,
                    end: bytes | None = None,
                    raw: bool = False) -> tuple[list[dict], int]:
    """Committed versions with lo_ts < ts <= RESOLVED in [start, end),
    ordered by (ts, key), plus the RESOLVED frontier itself — the catch-up
    scan with the closed-timestamp discipline. Tombstones emit value None.
    Returns (events, resolved)."""
    versions, intents = _scan(db, lo_ts, hi_ts, start, end)
    # the resolved frontier holds below the oldest unresolved intent
    resolved = int(hi_ts)
    for its, _ikey in intents:
        resolved = min(resolved, int(its) - 1)
    events = [encode_event(t, k, v, raw)
              for t, k, v in versions if t <= resolved]
    return events, resolved


class FileSink:
    """JSON-lines sink (the cloud-storage sink reduction)."""

    def __init__(self, path: str):
        self.path = path

    def emit(self, events: list[dict]) -> None:
        with open(self.path, "a") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")


def register_changefeed_job(registry: Registry, polls: int = 1) -> None:
    """Changefeed as a jobs.Resumer: each poll emits (resolved, now] events
    to the sink then checkpoints the new resolved frontier."""

    def resume(reg: Registry, job: Job):
        from ..utils import faults

        sink = FileSink(job.payload["sink"])
        start = job.payload.get("start")
        end = job.payload.get("end")
        s = start.encode() if isinstance(start, str) else start
        e = end.encode() if isinstance(end, str) else end
        for _ in range(job.payload.get("polls", polls)):
            resolved = job.progress.get("resolved", 0)
            now = reg.db.clock.now()
            events, new_resolved = changes_between(
                reg.db, resolved, now, s, e)
            if events:
                sink.emit(events)
            # the frontier never regresses: a txn that began before the
            # last checkpoint may lay intents below it, but re-emitting
            # (old_resolved, new_resolved] would duplicate events
            job.progress["resolved"] = max(resolved, new_resolved)
            # chaos site: the frontier checkpoint write is lost — the
            # job fails here with events already emitted; re-adoption
            # resumes from the stale frontier and re-emits (the sink
            # dedups by (ts, key)), never skips
            faults.fire("changefeed.frontier.checkpoint")
            reg.checkpoint(job)  # frontier checkpoint: resume point
        return {"resolved": job.progress["resolved"]}

    registry.register("changefeed", resume)


class RangefeedServer:
    """Push rangefeed events over the DCN framing — the MuxRangeFeed
    reduction (kvpb api.proto:3700): a subscriber names a span and a start
    timestamp; the server streams JSON event frames as new versions commit,
    interleaved with resolved-timestamp checkpoints.

    Since the fan-out rebuild, connections are demuxed through ONE
    :class:`~.fanout.FanoutHub` poll loop instead of a per-connection
    tail thread: each subscriber gets a budgeted buffer charged to the
    node's changefeed staging account, slow consumers walk the
    backpressure ladder, dead sockets are heartbeat-reaped within the
    send deadline, and an evicted client receives a typed
    ``{"error": "slow_consumer", "frontier": N}`` frame naming its exact
    reconnect point."""

    def __init__(self, db: DB, poll_interval_s: float = 0.05,
                 port: int = 0):
        import socket
        import threading

        from .fanout import FanoutHub

        self.db = db
        self.poll_interval_s = poll_interval_s
        # explicit port so a restarted source rebinds the SAME address —
        # the replication stream's reconnect contract needs a stable
        # endpoint to re-dial (create_server sets SO_REUSEADDR on POSIX)
        self._srv = socket.create_server(("127.0.0.1", port))
        self._srv.settimeout(0.2)
        self.addr = self._srv.getsockname()
        self.hub = FanoutHub(db, poll_interval_s=poll_interval_s,
                             name=f"{self.addr[0]}:{self.addr[1]}")
        self._stop = threading.Event()
        # track accepted conns so close() severs them: a restart on the
        # same port must not collide with a previous incarnation's
        # still-established subscriber sockets
        self._conns: set = set()
        self._conns_lock = locks.lock("kv.changefeed.conns")
        self._accept_thread = threading.Thread(target=self._serve,
                                               daemon=True)
        self._accept_thread.start()

    def _serve(self):
        import socket
        import threading

        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # server socket closed
            with self._conns_lock:
                if self._stop.is_set():
                    conn.close()
                    return
                self._conns.add(conn)
            threading.Thread(target=self._handshake, args=(conn,),
                             daemon=True).start()

    def _handshake(self, conn):
        """Per-connection handshake off the accept loop: a slow, broken or
        malicious client can neither stall new subscriptions nor kill the
        server thread."""
        from ..flow.dcn import _recv_msg

        try:
            conn.settimeout(10.0)
            msg = _recv_msg(conn)
            if msg is None:
                raise ConnectionError("empty handshake")
            req = json.loads(msg.decode("utf-8"))
            conn.settimeout(None)
        except (OSError, ValueError, ConnectionError):
            conn.close()
            self._discard(conn)
            return
        self._register(conn, req)

    def _register(self, conn, req):
        """Hand the connection to the fan-out hub (replaces the old
        per-connection ``_tail`` poll loop, which had no liveness bound —
        a dead socket held its thread and poll budget forever)."""
        from ..flow.dcn import _send_msg

        start = req.get("start")
        end = req.get("end")
        s = start.encode() if isinstance(start, str) else start
        e = end.encode() if isinstance(end, str) else end
        sub = self.hub.add_subscriber(
            conn, start=s, end=e, since=int(req.get("since", 0)),
            raw=bool(req.get("raw", False)),
            on_close=lambda: self._discard(conn))
        if sub is None:
            # bounded subscriber tree: refuse the newcomer with a typed
            # frame rather than degrade every existing registration
            try:
                _send_msg(conn, json.dumps(
                    {"error": "subscriber_limit"}).encode("utf-8"))
            except OSError:
                pass  # client already gone
            conn.close()
            self._discard(conn)

    def _discard(self, conn):
        with self._conns_lock:
            self._conns.discard(conn)

    def close(self):
        import socket
        import threading

        self._stop.set()
        self._srv.close()
        # join the accept loop: the kernel holds the listening socket
        # open while a thread sits in accept()'s poll window, so a
        # restart on the same port would EADDRINUSE until it exits
        if self._accept_thread is not threading.current_thread():
            self._accept_thread.join(timeout=5)
        # the hub severs registered subscribers and joins their senders
        self.hub.close()
        # handshake-phase stragglers never reached the hub
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            c.close()


def subscribe_rangefeed(addr, start=None, end=None, since: int = 0,
                        raw: bool = False):
    """Dial a RangefeedServer; returns (socket, iterator of frames).
    Frames are events ({key, value, ts} — or byte-exact {k64, v64, ts}
    with raw=True), checkpoints ({resolved}), or a terminal typed error
    ({error, frontier} — e.g. a slow-consumer eviction naming the exact
    ``since`` to reconnect with)."""
    import socket

    from ..flow.dcn import _recv_msg, _send_msg
    from ..utils import faults, settings

    # chaos site: a failed (re)subscription — the rangefeed restart path
    # consumers must retry through (kvclient/rangefeed restart-on-error)
    faults.fire("kv.rangefeed.subscribe")
    # bounds the connect and persists as the per-frame read deadline. A
    # healthy feed ticks checkpoints well inside it; a server that goes
    # silent past the deadline reads as end-of-feed below, and the
    # consumer re-subscribes from its last checkpoint — the same
    # reconnect-from-frontier path a slow-consumer eviction takes
    sock = socket.create_connection(
        tuple(addr), timeout=settings.get("flow.dcn.io_timeout_s"))
    _send_msg(sock, json.dumps({
        "start": start.decode() if isinstance(start, bytes) else start,
        "end": end.decode() if isinstance(end, bytes) else end,
        "since": since,
        "raw": raw,
    }).encode("utf-8"))

    def frames():
        while True:
            try:
                msg = _recv_msg(sock)
            except (ConnectionError, OSError):
                return  # server closed the stream: end of feed
            if msg is None:
                return
            try:
                yield json.loads(msg.decode("utf-8"))
            except ValueError:
                # torn frame (the server's send deadline fired mid-write
                # before it evicted us): the stream is dead; resume by
                # reconnecting from the last checkpoint
                return

    return sock, frames()
