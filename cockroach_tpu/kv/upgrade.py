"""Cluster version + upgrade migrations — the pkg/clusterversion +
pkg/upgrade reduction.

Reference: every store persists the cluster version; on startup (and on
SET CLUSTER SETTING version = ...) the upgrade manager runs each
registered migration between the persisted version and the binary's
version, in order, idempotently, and only then bumps the persisted
version (pkg/upgrade/upgrademanager). Feature gates check
``clusterversion.Is Active`` before using new formats.

Reduction: versions are (major, minor) pairs persisted at a system key;
migrations register against the version that ACTIVATES them; ``run_
upgrades(db)`` applies pending ones transactionally (each migration runs,
then the version bumps — a crash between re-runs the migration, which
must therefore be idempotent, same contract as the reference). The
Node runs this at start.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass

from .txn import DB

_VERSION_KEY = b"\x01ver"
_VER = struct.Struct("<ii")

# the version this binary ships (bump when a migration is added)
BINARY_VERSION = (4, 2)


@dataclass(frozen=True)
class Migration:
    version: tuple[int, int]  # runs when persisted version is below this
    name: str
    fn: object  # fn(db) -> None, idempotent


_MIGRATIONS: list[Migration] = []


def register_migration(version: tuple[int, int], name: str):
    """Decorator: register fn(db) to run when upgrading past `version`."""
    def deco(fn):
        _MIGRATIONS.append(Migration(tuple(version), name, fn))
        _MIGRATIONS.sort(key=lambda m: m.version)
        return fn
    return deco


def active_version(db: DB) -> tuple[int, int]:
    v = db.get(_VERSION_KEY)
    if v is None:
        return (0, 0)
    return _VER.unpack(v[:_VER.size])


def is_active(db: DB, version: tuple[int, int]) -> bool:
    """Feature gate: has the cluster upgraded past `version`?"""
    return active_version(db) >= tuple(version)


def run_upgrades(db: DB, to_version: tuple[int, int] = BINARY_VERSION,
                 migrations: list[Migration] | None = None) -> list[str]:
    """Run every registered migration in (active, to_version], bumping the
    persisted version after EACH (so a crash mid-sequence resumes at the
    failed migration, not the start). Returns the names that ran."""
    from ..utils import log

    ran: list[str] = []
    cur = active_version(db)
    if cur == (0, 0):
        # no version record. A FRESH store bootstraps straight at the
        # target (nothing to migrate); a LEGACY store (data written by a
        # pre-versioning binary) must run EVERY migration from (0,0) —
        # the two are distinguished by whether any data exists at all
        probe = db.scan(None, None, max_keys=1)
        if not probe:
            db.put(_VERSION_KEY, _VER.pack(*to_version))
            return ran
    for m in (migrations if migrations is not None else _MIGRATIONS):
        if cur < m.version <= tuple(to_version):
            log.info(log.OPS, "running upgrade migration", name=m.name,
                     version=f"{m.version[0]}.{m.version[1]}")
            m.fn(db)
            db.put(_VERSION_KEY, _VER.pack(*m.version))
            cur = m.version
            ran.append(m.name)
    if cur < tuple(to_version):
        db.put(_VERSION_KEY, _VER.pack(*to_version))
    return ran
