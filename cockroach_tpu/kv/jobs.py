"""Jobs framework — the pkg/jobs analog.

Reference: jobs.Registry (registry.go:95) keeps durable job records in
system tables; a Resumer (registry.go:1417) drives each job type; adoption
claims unowned jobs (adopt.go) and resumes them from their persisted
progress — the mechanism every long-running operation (backup, import,
schema change, changefeed) rides so that a crash resumes instead of
restarting. Here the same shape over the KV engine:

- job records (id, type, state, payload, progress) persist in a system
  keyspace through kv transactions;
- Resumer implementations register per job type and receive (job, progress)
  on resume — they checkpoint by writing progress back;
- Registry.run_to_completion drives a job with crash-equivalent resume
  semantics (tested by killing the resumer mid-run and re-adopting).

States: pending -> running -> succeeded | failed (paused omitted until a
control surface exists).
"""

from __future__ import annotations

import json
from dataclasses import dataclass

from ..utils import locks, racesan
from .txn import DB

_PREFIX = b"\x01job"
# id-sequence key OUTSIDE the record prefix: create()'s allocation is a
# point read/write, so concurrent job-record writes (checkpoints) never
# invalidate a create's refresh span (and jobs() scans never parse it)
_SEQ_KEY = b"\x01jbsq"


@dataclass
class Job:
    job_id: int
    job_type: str
    state: str  # pending | running | succeeded | failed
    payload: dict
    progress: dict
    error: str = ""
    # adoption claim (jobs/adopt.go): which node runs it, at which liveness
    # epoch. A node whose epoch was incremented (fenced) must not write
    # checkpoints for claims made under the older epoch.
    claim_node: int = 0
    claim_epoch: int = 0


class Registry:
    """Durable job records + resumer dispatch (jobs.Registry reduction)."""

    def __init__(self, db: DB, node_id: int = 1, liveness=None):
        self.db = db
        self.node_id = node_id
        # NodeLiveness (kv/liveness.py): adoption claims are epoch-stamped
        # and checkpoints are fenced against epoch increments; None keeps
        # the single-registry behavior (claims recorded, never contested)
        self.liveness = liveness
        # guards _resumers/_running: register() runs on the main thread
        # while Node._adopt_loop adopts from its background thread
        self._mu = locks.lock("kv.jobs.registry")
        self._resumers: dict[str, object] = {}
        self._running: set[int] = set()  # in-process, guards self-re-adoption

    # -- resumer registration (RegisterConstructor analog) -------------------

    def register(self, job_type: str, resume_fn) -> None:
        """resume_fn(registry, job) runs/continues the job; it reads
        job.progress for its checkpoint and calls registry.checkpoint(job)
        after each unit of work. Return value = final result payload."""
        with self._mu:
            racesan.note_write(self, "_resumers")
            self._resumers[job_type] = resume_fn

    # -- record persistence --------------------------------------------------
    #
    # Records CHUNK across engine values via the shared kv/chunked.py
    # discipline (descriptors and table stats use it too): payloads like a
    # schema change's column definition outgrow one fixed-width value.
    # Legacy single-value records (pre-chunking stores: a dot-less key)
    # remain readable so restored checkpoints keep their job history.

    @staticmethod
    def _chunk_key(job_id: int, chunk: int) -> bytes:
        assert chunk < 100
        return _PREFIX + b"%08d.%02d" % (job_id, chunk)

    def _write(self, t, job: Job) -> None:
        from .chunked import chunk_blob

        rec = {
            "type": job.job_type, "state": job.state,
            "payload": job.payload, "progress": job.progress,
        }
        if job.error:
            rec["error"] = job.error
        if job.claim_node:
            rec["claim_node"] = job.claim_node
            rec["claim_epoch"] = job.claim_epoch
        blob = json.dumps(rec, separators=(",", ":")).encode("utf-8")
        step = max(16, self.db.engine.val_width)
        for ci, piece in enumerate(chunk_blob(blob, step)):
            t.put(self._chunk_key(job.job_id, ci), piece)

    @staticmethod
    def _parse(job_id: int, blob: bytes) -> Job:
        d = json.loads(blob.decode("utf-8"))
        return Job(job_id, d["type"], d["state"], d["payload"],
                   d["progress"], d.get("error", ""),
                   d.get("claim_node", 0), d.get("claim_epoch", 0))

    @classmethod
    def _from_chunks(cls, job_id: int,
                     chunks: list[tuple[bytes, bytes]]) -> Job:
        from .chunked import unchunk

        return cls._parse(job_id, unchunk([v for _, v in sorted(chunks)]))

    def load(self, job_id: int) -> Job | None:
        lo = self._chunk_key(job_id, 0)
        hi = _PREFIX + b"%08d.\xff" % job_id
        rows = self.db.scan(lo, hi)
        if rows:
            return self._from_chunks(job_id, rows)
        legacy = self.db.get(_PREFIX + b"%08d" % job_id)
        if legacy is not None:
            return self._parse(job_id, legacy)
        return None

    def jobs(self) -> list[Job]:
        by_id: dict[int, list[tuple[bytes, bytes]]] = {}
        legacy: dict[int, bytes] = {}
        for k, v in self.db.scan(_PREFIX, _PREFIX + b"\xff"):
            tail = k[len(_PREFIX):]
            if b"." in tail:
                jid = int(tail.split(b".")[0])
                by_id.setdefault(jid, []).append((k, v))
            else:
                legacy[int(tail)] = v  # pre-chunking single-value record
        out = {jid: self._from_chunks(jid, chunks)
               for jid, chunks in by_id.items()}
        for jid, v in legacy.items():
            # a chunked rewrite of the same job supersedes the legacy row
            out.setdefault(jid, self._parse(jid, v))
        return [out[jid] for jid in sorted(out)]

    # -- lifecycle -----------------------------------------------------------

    def create(self, job_type: str, payload: dict) -> Job:
        """CreateJob: a durable pending record (one txn). The id comes from
        a sequence key read/written INSIDE the txn — a point span, so two
        registries over the same DB cannot allocate the same id (the
        conflicting create retries) and concurrent job-record writes don't
        invalidate the allocation's refresh."""
        def op(t):
            v = t.get(_SEQ_KEY)
            if v is not None:
                top = int(v)
            else:
                # one-time migration from pre-sequence stores: seed from
                # the existing records' max id
                top = 0
                for k, _ in t.scan(_PREFIX, _PREFIX + b"\xff"):
                    top = max(top, int(k[len(_PREFIX):].split(b".")[0]))
            t.put(_SEQ_KEY, b"%d" % (top + 1))
            job = Job(top + 1, job_type, "pending", payload, {})
            self._write(t, job)
            return job

        return self.db.txn(op)

    def _my_epoch(self) -> int:
        """The liveness epoch this node BELIEVES it owns (set by its own
        successful heartbeats) — a fenced node must keep stamping its old
        epoch so its writes fail, not adopt the fencer's."""
        if self.liveness is None:
            return 0
        if self.liveness._my_epoch is not None:
            return self.liveness._my_epoch
        rec = self.liveness._read(self.node_id)
        return rec.epoch if rec is not None else 0

    def checkpoint(self, job: Job) -> None:
        """Persist progress mid-run (the backup-manifest-checkpoint shape:
        a crash after this point resumes from here, not from zero).

        Epoch fencing (jobs/adopt.go + liveness epochs): with liveness
        wired, the fence check and the record write share ONE txn — the
        liveness read lands in the txn's read spans, so a fencer's epoch
        increment between check and commit invalidates the write (refresh
        failure) instead of letting a stale node clobber the new owner."""
        from .liveness import EpochFencedError

        def op(t):
            if self.liveness is not None and job.claim_node == self.node_id:
                rec = self.liveness._read(self.node_id, t)
                if rec is not None and rec.epoch != job.claim_epoch:
                    raise EpochFencedError(
                        f"node {self.node_id} epoch {rec.epoch} != claim "
                        f"epoch {job.claim_epoch}; job {job.job_id} was "
                        "re-adopted"
                    )
            self._write(t, job)

        self.db.txn(op)

    def adopt_orphans(self) -> list[Job]:
        """Re-adopt running jobs whose claim is no longer valid: the
        claimant's liveness record expired (fence it — its late checkpoints
        must fail) or it is a stale self-claim from before our own epoch
        advanced (jobs/adopt.go's claim-expired loop). One failing job must
        not stall its siblings. Requires liveness."""
        if self.liveness is None:
            return []
        from ..utils import log
        from .liveness import StillLiveError

        out = []
        for job in self.jobs():
            with self._mu:
                racesan.note_read(self, "_running")
                in_flight = job.job_id in self._running
            if job.state != "running" or in_flight:
                continue
            if job.claim_node == 0:
                continue
            if job.claim_node == self.node_id:
                # our own claim: after a crash-and-restart the record is
                # live again but nothing is driving the job — resume it
                # (the _running guard keeps in-flight jobs untouched)
                pass
            else:
                if self.liveness.is_live(job.claim_node):
                    continue
                try:
                    self.liveness.increment_epoch(job.claim_node)
                except StillLiveError:
                    continue  # heartbeated between checks; leave it alone
            try:
                out.append(self.adopt_and_resume(job.job_id))
            except Exception as e:  # crlint: allow-broad-except(adoption failure is per-job; logged, loop continues)
                log.warning(log.OPS, "orphan adoption failed",
                            job=job.job_id, error=str(e))
        return out

    def _claim(self, job_id: int, observed: Job) -> Job | None:
        """Transactionally claim a job for this node. The read of the
        record is span-tracked, so two adopters racing on the same orphan
        conflict: the loser's retry re-reads the new claim and backs off
        (returns None) instead of double-running the job."""
        my_epoch = self._my_epoch()

        def op(t):
            # read through the txn so the chunk span lands in the read
            # spans (claim races conflict at commit)
            rows = t.scan(self._chunk_key(job_id, 0),
                          _PREFIX + b"%08d.\xff" % job_id)
            if rows:
                cur = self._from_chunks(job_id, rows)
            else:
                legacy = t.get(_PREFIX + b"%08d" % job_id)
                if legacy is None:
                    return None
                cur = self._parse(job_id, legacy)  # rewrite claims chunked
            if cur.state in ("succeeded", "failed"):
                return cur
            if ((cur.claim_node, cur.claim_epoch)
                    != (observed.claim_node, observed.claim_epoch)):
                return None  # someone else claimed since we looked
            cur.state = "running"
            cur.claim_node = self.node_id
            cur.claim_epoch = my_epoch
            self._write(t, cur)
            return cur

        return self.db.txn(op)

    def adopt_and_resume(self, job_id: int) -> Job:
        """Claim a pending/running job and drive its resumer to a terminal
        state. Re-entrant: called again after a crash, the resumer
        continues from the persisted progress."""
        observed = self.load(job_id)
        if observed is None:
            raise KeyError(f"no job {job_id}")
        if observed.state in ("succeeded", "failed"):
            return observed
        with self._mu:
            racesan.note_read(self, "_resumers")
            resume = self._resumers.get(observed.job_type)
        if resume is None:
            raise KeyError(f"no resumer for job type {observed.job_type!r}")
        job = self._claim(job_id, observed)
        if job is None:
            return self.load(job_id)  # lost the claim race: current state
        if job.state in ("succeeded", "failed"):
            return job
        with self._mu:
            racesan.note_write(self, "_running")
            self._running.add(job_id)
        try:
            try:
                result = resume(self, job)
            except Exception as e:
                job.state = "failed"
                job.error = f"{type(e).__name__}: {e}"
                self.checkpoint(job)
                raise
            job.state = "succeeded"
            if isinstance(result, dict):
                job.progress.update(result)
            self.checkpoint(job)
            return job
        finally:
            with self._mu:
                racesan.note_write(self, "_running")
                self._running.discard(job_id)


# -- built-in job types ------------------------------------------------------


def register_builtin_jobs(registry: Registry) -> None:
    """The reference runs BACKUP as a job (pkg/backup/backup_processor.go
    under jobs.Resumer); here the engine checkpoint rides the same frame:
    durable record -> run -> terminal state, resumable by re-adoption."""

    def backup_resume(reg: Registry, job: Job):
        from ..utils.external_storage import resolve_dir_uri

        # URI destinations (nodelocal://, file://; cloud schemes fail
        # with configuration guidance) — pkg/cloud ExternalStorage role
        path = resolve_dir_uri(job.payload["path"])
        reg.db.engine.checkpoint(path)
        return {"path": path}

    registry.register("backup", backup_resume)


def register_import_job(registry: Registry, catalog) -> None:
    """IMPORT INTO <table> CSV DATA (file) as a job: parse the CSV on the
    host, bulk-load through the AddSSTable path (KVTable.bulk_load), record
    row counts in progress — the pkg/sql/importer reduction."""
    import csv as _csv

    import numpy as np

    from ..coldata.types import Family

    def import_resume(reg: Registry, job: Job):
        import io

        from ..utils.external_storage import from_uri

        table = catalog.tables[job.payload["table"]]
        # URI destinations (nodelocal://, file://, plain paths) read
        # through the ExternalStorage registry (pkg/cloud role)
        storage, path = from_uri(job.payload["path"])
        data = storage.read_file(path).decode("utf-8")
        rows = list(_csv.DictReader(io.StringIO(data, newline="")))
        cols: dict[str, np.ndarray] = {}
        valids: dict[str, np.ndarray] = {}
        for name, t in zip(table.schema.names, table.schema.types):
            raw = [r.get(name, "") for r in rows]
            missing = np.array([x == "" for x in raw])
            if t.family is Family.STRING:
                cols[name] = np.array(
                    [x if x else "" for x in raw], dtype=object)
            elif t.family is Family.FLOAT:
                cols[name] = np.array(
                    [float(x) if x else 0.0 for x in raw])
            elif t.family is Family.DECIMAL:
                cols[name] = np.array([
                    int(round(float(x) * 10**t.scale)) if x else 0
                    for x in raw], dtype=np.int64)
            elif t.family is Family.BOOL:
                cols[name] = np.array(
                    [x.lower() == "true" for x in raw])
            else:
                cols[name] = np.array(
                    [int(x) if x else 0 for x in raw], dtype=np.int64)
            if missing.any():
                valids[name] = ~missing
        n = table.bulk_load(cols, valids)
        return {"rows": n}

    registry.register("import", import_resume)
