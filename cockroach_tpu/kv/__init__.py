"""KV layer — transactional key-value API over the MVCC LSM engine
(pkg/kv analog: kv.DB, kv.Txn, retries, intents, refresh validation)."""

from ..storage.lsm import WriteIntentError
from .hlc import Clock, ManualClock
from .txn import DB, TransactionAbortedError, TransactionRetryError, Txn

__all__ = [
    "Clock", "ManualClock", "DB", "Txn",
    "TransactionAbortedError", "TransactionRetryError", "WriteIntentError",
]
