"""Secondary indexes — key codec, write maintenance, Streamer fetch.

Reference: secondary-index keys are table/index-prefixed, order-preserving
encodings of the indexed columns with the primary key as suffix
(pkg/sql/rowenc/index_encoding.go); index joins read the matched primary
rows through batched, memory-budgeted KV reads
(pkg/sql/rowexec/joinreader.go driving
pkg/kv/kvclient/kvstreamer/streamer.go:517); CREATE INDEX backfills run as
chunked, checkpointed jobs (pkg/sql/backfill.go).

TPU-first divergences:

- The Streamer is not N parallel point RPCs: a request's primary keys
  upload once and membership resolves as ONE vectorized searchsorted over
  the engine's merged device view, followed by a gather that compacts the
  hits into a batch whose capacity is sized by the REQUEST, not the table
  — downstream kernels compile at lookup-result shape.
- Index entries are presence-only (empty value); the fetch always goes
  back to the primary (no covering indexes yet).
- Single indexed column, fixed-width families; STRING columns index their
  dictionary codes (equality-only semantics — codes are not ordered).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np

from ..coldata.types import Family
from ..storage import rowcodec

# index entry: 1 prefix byte + 10 value bytes + 10 pk bytes = 21 bytes.
# Fits the engine's 24-byte default key width (storage.keys.
# DEFAULT_KEY_WIDTH) — plan_create_index rejects engines provisioned
# narrower, since every entry write would fail mid-backfill otherwise.
ENTRY_BYTES = 1 + 2 * rowcodec.PK_BYTES


@dataclass(frozen=True)
class IndexDesc:
    name: str
    col: str
    index_id: int  # its own keyspace prefix, allocated like a table id


def _enc_val(v: int) -> bytes:
    """Order-preserving, NUL-free 10-byte encoding of one int64 (the same
    7-bit-group scheme as rowcodec.encode_pk, sans prefix)."""
    u = (int(v) & 0xFFFFFFFFFFFFFFFF) ^ (1 << 63)
    out = bytearray()
    for i in range(rowcodec.PK_BYTES - 1, -1, -1):
        out.append(0x01 + ((u >> (7 * i)) & 0x7F))
    return bytes(out)


def encode_entry(index_id: int, val: int, pk: int) -> bytes:
    assert 0 <= index_id <= rowcodec.MAX_TABLE_ID
    return bytes([0x01 + index_id]) + _enc_val(val) + _enc_val(pk)


def decode_entry(key: bytes) -> tuple[int, int]:
    """(value, pk) from an index entry key."""

    def dec(b: bytes) -> int:
        u = 0
        for x in b:
            u = (u << 7) | (x - 0x01)
        u ^= 1 << 63
        return u - (1 << 64) if u >= (1 << 63) else u

    n = rowcodec.PK_BYTES
    return dec(key[1:1 + n]), dec(key[1 + n:1 + 2 * n])


def value_span(index_id: int, lo: int | None, hi: int | None
               ) -> tuple[bytes, bytes]:
    """[start, end) covering entries with value in [lo, hi] (inclusive;
    None = unbounded on that side)."""
    assert 0 <= index_id <= rowcodec.MAX_TABLE_ID
    prefix = bytes([0x01 + index_id])
    start = prefix + _enc_val(lo) if lo is not None else prefix
    # entry bytes are in [0x01, 0x80], so 0x81 sorts after every pk suffix
    end = (prefix + _enc_val(hi) + b"\x81" if hi is not None
           else bytes([0x02 + index_id]))
    return start, end


def encode_entries(index_id: int, vals: np.ndarray,
                   pks: np.ndarray) -> np.ndarray:
    """Vectorized entry encode: [N] vals + [N] pks -> [N, ENTRY_BYTES]."""
    n = len(vals)
    out = np.empty((n, ENTRY_BYTES), dtype=np.uint8)
    out[:, 0] = 0x01 + index_id
    for src, off in ((vals, 1), (pks, 1 + rowcodec.PK_BYTES)):
        u = np.asarray(src, dtype=np.int64).astype(np.uint64) ^ np.uint64(
            1 << 63)
        for i in range(rowcodec.PK_BYTES):
            shift = np.uint64(7 * (rowcodec.PK_BYTES - 1 - i))
            out[:, off + i] = ((u >> shift) & np.uint64(0x7F)).astype(
                np.uint8) + 0x01
    return out


# -- write-path maintenance (called from KVTable inside the row's txn) ------


def entries_for_row(indexes, schema, row: dict, pk: int) -> list[bytes]:
    """Index entry keys for one encoded row (values already codes/ints;
    NULL indexed values produce no entry — filters are null-rejecting)."""
    out = []
    for ix in indexes:
        v = row.get(ix.col)
        if v is None:
            continue
        out.append(encode_entry(ix.index_id, int(v), pk))
    return out


def maintain_row(t, indexes, schema, new_row: dict | None,
                 old_row: dict | None, pk: int) -> None:
    """Delete stale + write fresh index entries for one primary row
    (new_row/old_row: value-encoded dicts; None = absent)."""
    old = set(entries_for_row(indexes, schema, old_row, pk)) if old_row else set()
    new = set(entries_for_row(indexes, schema, new_row, pk)) if new_row else set()
    for k in old - new:
        t.delete(k)
    for k in new - old:
        t.put(k, b"")


# -- the Streamer: batched primary-row fetch --------------------------------


class Streamer:
    """Vectorized out-of-order primary-row fetch (kvstreamer.Streamer:517 /
    joinreader role). Given the primary keys an index scan matched, resolve
    all of them in one device pass over the engine's merged view:
    searchsorted membership + compacting gather, output capacity sized by
    the request."""

    def __init__(self, table):
        self.table = table

    def fetch(self, pks: np.ndarray, names: tuple[str, ...]):
        """-> Batch of the requested columns for rows whose pk is in
        `pks`, at the table's read context. Output capacity = padded
        len(pks) (missing pks leave masked-off rows)."""
        from ..coldata.batch import Batch, Column, empty_batch
        from ..storage import keys as K
        from ..storage import mvcc
        from ..storage.lsm import WriteIntentError

        tbl = self.table
        idxs = tuple(tbl.schema.index(n) for n in names)
        schema = tbl.schema.select(idxs)
        cap_out = max(128, 1 << int(np.ceil(np.log2(max(1, len(pks))))))
        if len(pks) == 0:
            return empty_batch(schema, cap_out)
        eng = tbl.db.engine
        view = eng._merged_view()
        if view is None:
            return empty_batch(schema, cap_out)
        ts = tbl.read_ts if tbl.read_ts is not None else tbl.db.clock.now()
        spks = np.sort(np.asarray(pks, dtype=np.int64))
        lo, hi = int(spks[0]), int(spks[-1])
        sw = K.encode_bound(rowcodec.encode_pk(tbl.table_id, lo),
                            eng.key_width)
        ew = K.encode_bound(
            rowcodec.encode_pk(tbl.table_id, hi) + b"\x01", eng.key_width)
        sel, conflict = mvcc.mvcc_scan_filter(
            view, jnp.int64(ts), jnp.int64(tbl.reader_txn),
            jnp.asarray(sw), jnp.asarray(ew),
        )
        cnp = np.asarray(conflict)
        if cnp.any():
            hit = np.nonzero(cnp)[0]
            raise WriteIntentError(
                K.decode_keys(np.asarray(view.key)[hit]),
                [int(x) for x in np.asarray(view.txn)[hit]],
            )
        # vectorized membership: view pk in the sorted request set
        vpk = rowcodec.decode_pk_column(view.key)
        dpks = jnp.asarray(spks)
        pos = jnp.searchsorted(dpks, vpk)
        posc = jnp.clip(pos, 0, len(spks) - 1)
        sel = sel & (dpks[posc] == vpk)
        # compacting gather: hits land in [0, cap_out)
        dest = jnp.nonzero(sel, size=cap_out, fill_value=view.key.shape[0])[0]
        batch = rowcodec.decode_columns(view.value, sel, tbl.schema, idxs)

        def take(col):
            pad = jnp.zeros((1,) + col.shape[1:], dtype=col.dtype)
            return jnp.concatenate([col, pad])[dest]

        cols = []
        mask = take(sel)
        for pos_i, i in enumerate(idxs):
            c = batch.cols[pos_i]
            if i == tbl.pk_idx:
                cols.append(Column(data=take(vpk), valid=mask))
            else:
                cols.append(Column(data=take(c.data), valid=take(c.valid)))
        return Batch(cols=tuple(cols), mask=mask)


# -- index scan (host side of the read path) --------------------------------


def scan_pks(table, index: IndexDesc, lo: int | None, hi: int | None,
             max_keys: int | None = None) -> np.ndarray:
    """Primary keys whose indexed value falls in [lo, hi], read from the
    index keyspace at the table's read context (ts + txn visibility)."""
    start, end = value_span(index.index_id, lo, hi)
    ts = table.read_ts if table.read_ts is not None else table.db.clock.now()
    rows = table.db.engine.scan(start, end, ts=ts, txn=table.reader_txn,
                                max_keys=max_keys)
    return np.array([decode_entry(k)[1] for k, _ in rows], dtype=np.int64)


# -- CREATE INDEX backfill job ----------------------------------------------

CHUNK_ROWS = 512


def plan_create_index(catalog, db, stmt,
                      id_range: tuple[int, int] | None = None) -> dict:
    """Validate CREATE INDEX and build the job payload (the index id is
    allocated NOW so a crash-resume lands entries in the final span).
    id_range confines the id to a tenant's keyspace slice, the
    create_kv_table.alloc discipline — an index keyspace must never land
    inside a foreign tenant's reserved slice."""
    from ..sql.binder import BindError
    from .table import KVTable
    from .tenant import _SYSTEM_RANGE

    tbl = catalog.tables.get(stmt.table)
    if tbl is None:
        raise BindError(f"unknown table {stmt.table!r}")
    if not isinstance(tbl, KVTable):
        raise BindError("CREATE INDEX targets KV-backed tables")
    if db.engine.key_width < ENTRY_BYTES:
        raise BindError(
            f"engine key_width {db.engine.key_width} cannot hold "
            f"{ENTRY_BYTES}-byte index entries (provision the store with "
            f"key_width >= {ENTRY_BYTES})"
        )
    if any(ix.name == stmt.name for ix in tbl.indexes):
        raise BindError(f"index {stmt.name!r} already exists")
    if stmt.col not in tbl.schema.names:
        raise BindError(f"unknown column {stmt.col!r}")
    fam = tbl.schema.type_of(stmt.col).family
    if fam in (Family.FLOAT, Family.BYTES, Family.JSON):
        raise BindError(
            f"indexes on {fam.name} columns are not supported (order-"
            "preserving int encoding only)"
        )
    lo, hi = id_range if id_range is not None else _SYSTEM_RANGE
    used = set()
    for other in catalog.tables.values():
        if isinstance(other, KVTable):
            used.add(other.table_id)
            if other.dict_table_id is not None:
                used.add(other.dict_table_id)
            used.update(ix.index_id for ix in other.indexes)
    index_id = max([i for i in used if lo <= i <= hi], default=lo - 1) + 1
    if index_id > hi:
        raise BindError(f"tenant keyspace [{lo},{hi}] exhausted")
    return {"table": stmt.table, "index": stmt.name, "col": stmt.col,
            "index_id": index_id}


def backfill_index(reg, job, catalog) -> None:
    """The create_index resumer: chunked entry writes + checkpoint + a
    fenced descriptor swap that makes the index visible (the
    schemachange.py discipline; concurrent DML is out of scope, as there).

    With storage.bulk_ingest.enabled, each chunk's entries encode
    vectorized and land as a device-built run through the RunBuilder —
    the reference's backfiller writes AddSSTables, not per-row txn puts.
    The checkpoint/resume discipline is identical either way; re-running
    a chunk after a crash just re-lands the same entries at a newer
    timestamp."""
    from ..sql.schemachange import _fenced_job_read
    from ..storage import ingest as bulk
    from .table import KVTable, write_descriptor

    payload = job.payload
    durable = reg.load(job.job_id)
    if durable is not None:
        job.progress.update(durable.progress)
        if durable.progress.get("swapped"):
            return
    tbl: KVTable = catalog.tables[payload["table"]]
    ix = IndexDesc(payload["index"], payload["col"], payload["index_id"])
    db = reg.db
    use_bulk = (bulk.enabled()
                and db.engine.key_width >= ENTRY_BYTES)
    start, end = rowcodec.table_span(tbl.table_id)
    last_pk = job.progress.get("last_pk")
    while True:
        lo = (rowcodec.encode_pk(tbl.table_id, last_pk + 1)
              if last_pk is not None else start)
        rows = db.scan(lo, end, max_keys=CHUNK_ROWS)
        if not rows:
            break

        if use_bulk:
            pks_l, vals_l = [], []
            done = None
            for k, v in rows:
                pk = rowcodec.decode_pk(k)
                done = pk
                row = rowcodec.decode_row(tbl.schema, v)
                val = row.get(ix.col)
                if val is not None:
                    pks_l.append(pk)
                    vals_l.append(int(val))
            if vals_l:
                ik = encode_entries(ix.index_id,
                                    np.asarray(vals_l, np.int64),
                                    np.asarray(pks_l, np.int64))
                rb = bulk.RunBuilder(db.engine, db.clock.now())
                rb.add(ik, np.zeros((len(ik), 0), np.uint8))
                rb.finish()
            last_pk = done
        else:
            def write_chunk(t, rows=rows):
                done = None
                for k, v in rows:
                    pk = rowcodec.decode_pk(k)
                    done = pk
                    row = rowcodec.decode_row(tbl.schema, v)
                    val = row.get(ix.col)
                    if val is not None:
                        t.put(encode_entry(ix.index_id, int(val), pk), b"")
                return done

            last_pk = db.txn(write_chunk)
        job.progress["last_pk"] = int(last_pk)
        reg.checkpoint(job)

    def swap(t):
        _fenced_job_read(reg, job, t)
        tbl.indexes.append(ix)
        write_descriptor(db, tbl, writer=t)
        job.progress["swapped"] = True
        reg._write(t, job)

    try:
        db.txn(swap)
    except BaseException:
        if any(i.name == ix.name for i in tbl.indexes):
            tbl.indexes.remove(ix)
        raise


def drop_index(catalog, db, table_name: str, index_name: str) -> None:
    """DROP INDEX: remove from the descriptor first (readers stop routing
    through it), then delete the entry span in chunks."""
    from ..sql.binder import BindError
    from .table import write_descriptor

    tbl = catalog.tables[table_name]
    ix = next((i for i in tbl.indexes if i.name == index_name), None)
    if ix is None:
        raise BindError(f"unknown index {index_name!r}")
    tbl.indexes.remove(ix)
    write_descriptor(db, tbl)
    start, end = value_span(ix.index_id, None, None)
    while True:
        rows = db.scan(start, end, max_keys=1024)
        if not rows:
            break

        def rm(t, rows=rows):
            for k, _ in rows:
                t.delete(k)

        db.txn(rm)


def register_create_index_job(registry, catalog) -> None:
    registry.register(
        "create_index", lambda reg, job: backfill_index(reg, job, catalog))
