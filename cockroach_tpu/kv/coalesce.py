"""Inter-query KV batching — coalesce concurrent same-range point ops.

Reference: CockroachDB's DistSender merges the batchable requests of ONE
batch; under high session concurrency the per-request costs that
dominate a point op (mutex acquisition, WAL record + flush, admission
pacing) are paid once per SESSION even when eight sessions hammer the
same range with independent point reads/writes. This module adds the
missing cross-session axis: a :class:`BatchCoalescer` sits under the
``kv.DB`` non-transactional surface (the serving path for point DML and
row lookups) and merges concurrent ops from different sessions into one
stamped KV batch.

Design — commit train, not a timing window. The first submitter that
finds no flush in progress becomes the train leader and flushes
IMMEDIATELY (a sequential workload never waits on a timer); ops arriving
while that flush is on the wire queue up and the next leader takes them
all in one batch. Batching emerges exactly when there is concurrency to
batch, and adds zero latency when there is not — the group-commit
discipline WAL implementations converged on.

Exactly-once + atomicity ride PR 2's replay-cache machinery: a merged
write train applies through ``Engine.apply_rpc_batch`` — ops + (cid,
seq) dedup token + response in ONE atomic WAL record, one fsync, one
``governor.pace_write`` — instead of one WAL record per op. DistSender
backends get the same surface (``DistSender.apply_rpc_batch`` routes the
train by range, one stamped sub-batch per range, so a replay after a
split still dedups range-addressed).

Bit-identity with the solo path is the oracle (bench enforces it): each
rider's timestamp comes from the same ``clock.now()`` under the same
engine mutex, lock conflicts surface as the same per-key typed
``WriteIntentError`` demuxed to exactly the conflicting session, and a
single-op train takes the direct ``engine.put`` path a solo ``DB.put``
takes. Chaos site ``kv.batch.coalesce`` fires at flush start: an
injected fault degrades every rider to its own per-session solo batch —
same results, merging lost.
"""

from __future__ import annotations

import itertools
import threading
import time
import uuid

from ..storage.lsm import WriteIntentError
from ..utils import faults, locks, metric, racesan, settings

__all__ = ["BatchCoalescer", "for_db", "reset_db"]

# a follower whose leader vanishes without completing it can never know
# whether its op applied; surface that the way a severed RPC does
from .rpc import AmbiguousResultError  # noqa: E402

# queue-jump ceiling: a follower bounded-waits on its leader; leaders
# complete trains in milliseconds, so a full minute means the leader
# thread died mid-flush (only a killed thread can cause this)
_ABANDON_S = 60.0

# WAL batch records carry uint16 length fields (~64 KiB payload cap);
# chunk trains well under it so an oversized train degrades to more
# trains, never to a typed overflow error the solo path wouldn't raise
_CHUNK_BYTES = 48_000

# adaptive linger: after a train that actually merged ops, the riders it
# just released are racing back with their next op — pausing one beat
# before the next swap lets them board, roughly doubling train size
# under steady concurrency. A train of one (sequential caller) skips the
# linger entirely, so an uncontended workload never pays it.
_LINGER_S = 0.0002


def _b(x) -> bytes:
    return x.encode() if isinstance(x, str) else bytes(x)


class _Op:
    """One rider: a point op parked on the train with its result slot.
    Completion is signalled per TRAIN, not per op: every rider of one
    train shares its epoch event, so the leader wakes the whole train
    with one ``set()`` instead of one wake per rider — at train sizes in
    the tens the per-op Event allocations and wakes are measurable."""

    __slots__ = ("kind", "key", "value", "ts_arg", "filled", "result",
                 "error", "nbytes")

    def __init__(self, kind: str, key: bytes, value: bytes, ts_arg):
        self.kind = kind  # 'put' | 'delete' | 'get'
        self.key = key
        self.value = value
        self.ts_arg = ts_arg  # explicit read timestamp (get only)
        self.filled = False
        self.result = None
        self.error: BaseException | None = None
        self.nbytes = len(key) + len(value)


class BatchCoalescer:
    """Cross-session commit train over one ``kv.DB``.

    Works against either backend a DB can hold — a plain ``Engine`` or a
    ``DistSender`` — through the exact surface DB itself consumes:
    ``engine.mu``, ``put/delete/get``, and ``apply_rpc_batch``.
    """

    def __init__(self, db):
        self.db = db
        self.mu = locks.lock("kv.coalesce")
        # pending ops for the NEXT train; swapped out atomically by the
        # leader. racesan-annotated: this is the cross-session meeting
        # point, and an unlocked touch here is a lost op.
        self._pending: list[_Op] = []
        # completion event for the train currently FORMING; the leader
        # replaces it at swap, so every rider of one train shares one
        self._epoch = threading.Event()
        self._flushing = False
        # stamp identity for merged batches (PR 2 replay cache rides
        # along: the dedup entry makes the train's WAL record atomic)
        self.cid = f"coal-{uuid.uuid4().hex[:12]}"
        self._seq = itertools.count(1)
        # pending-value bytes are buffered server state: account them on
        # the cache-level staging ledger like every other standing buffer
        from ..flow import memory as flowmem

        self._staging = flowmem.staging_monitor("kv.coalesce")

    # -- public surface (mirrors DB's non-txn ops) --------------------------

    def put(self, key, value) -> int:
        return self._submit(_Op("put", _b(key), _b(value), None))

    def delete(self, key) -> int:
        return self._submit(_Op("delete", _b(key), b"", None))

    def get(self, key, ts: int | None = None):
        return self._submit(_Op("get", _b(key), b"", ts))

    # -- train mechanics ----------------------------------------------------

    def _submit(self, op: _Op):
        with self.mu:
            racesan.note_write(self, "_pending")
            self._pending.append(op)
            ev = self._epoch  # this op's train signal, fixed at boarding
            lead = not self._flushing
            if lead:
                self._flushing = True
        if lead:
            try:
                self._drive()
            except BaseException:
                # only a non-Exception escape (thread kill) reaches here:
                # un-wedge the train flag so the next submitter can lead
                with self.mu:
                    self._flushing = False
                raise
        elif not ev.wait(_ABANDON_S):
            raise AmbiguousResultError(
                f"coalesced {op.kind} abandoned by its train leader "
                f"(key={op.key!r})")
        if op.error is not None:
            raise op.error
        return op.result

    def _drive(self) -> None:
        """Leader loop: swap out everything pending, flush it as one
        train, repeat until the queue drains, then hand off leadership.
        The emptiness check and the flag drop are one atomic section so
        an op can never land unled."""
        merged = False
        while True:
            if merged:
                time.sleep(_LINGER_S)
            with self.mu:
                racesan.note_read(self, "_pending")
                ops = self._pending
                if not ops:
                    self._flushing = False
                    return
                self._pending = []
                racesan.note_write(self, "_pending")
                ev = self._epoch
                self._epoch = threading.Event()  # next train's signal
            merged = len(ops) >= 2
            # buffered rider payloads are server state for the train's
            # lifetime: charge the staging ledger once per train (a
            # per-op reserve would take the monitor-tree lock twice per
            # rider — measurable at train sizes in the tens)
            held = sum(op.nbytes for op in ops)
            self._staging.reserve(held, force=True)
            try:
                self._run_train(ops)
            finally:
                self._staging.release(held)
                for op in ops:
                    if not op.filled:
                        op.filled = True
                        if op.error is None and op.result is None:
                            op.error = AmbiguousResultError(
                                f"coalesced {op.kind} dropped by train "
                                f"(key={op.key!r})")
                # ONE wake for the whole train: every rider checks its
                # own slot on wakeup
                ev.set()

    def _run_train(self, ops: list[_Op]) -> None:
        try:
            # chaos site: a mid-coalesce fault degrades every rider to
            # its own per-session solo batch, bit-identically — nothing
            # is applied twice because nothing was applied yet
            faults.fire("kv.batch.coalesce")
        except faults.InjectedFault:
            for op in ops:
                self._finish_solo(op)
            return
        writes = [op for op in ops if op.kind != "get"]
        reads = [op for op in ops if op.kind == "get"]
        if len(ops) > 1:
            metric.KV_BATCH_COALESCED.inc(len(ops))
        for chunk in self._chunks(writes):
            self._flush_writes(chunk)
        if reads:
            self._flush_reads(reads)

    def _chunks(self, writes: list[_Op]):
        max_ops = settings.get("kv.batch.coalesce.max_ops")
        chunk: list[_Op] = []
        size = 0
        for op in writes:
            cost = 2 * op.nbytes + 64  # b64 + JSON framing, conservative
            if chunk and (len(chunk) >= max_ops
                          or size + cost > _CHUNK_BYTES):
                yield chunk
                chunk, size = [], 0
            chunk.append(op)
            size += cost
        if chunk:
            yield chunk

    def _flush_writes(self, chunk: list[_Op]) -> None:
        """One stamped batch for the chunk: per-key lock checks and
        per-op timestamps under the engine mutex exactly as the solo
        path orders them, then ONE atomic WAL record for all survivors.

        Group-commit pipelining: the batch appends its WAL record and
        applies with the fsync DEFERRED, the engine mutex is released,
        and the fsync runs outside it — the next train forms and applies
        while this one's sync is on the disk. Riders are acked only
        after the sync returns, so the durability contract is exactly
        the solo path's; only the mutex hold time shrinks."""
        db = self.db
        eng = db.engine
        solo: list[_Op] = []
        with eng.mu:
            muts, riders = [], []
            for op in chunk:
                try:
                    db._check_lock(op.key)
                except WriteIntentError as e:
                    op.error = e  # typed, demuxed to the one session
                    op.filled = True
                    continue
                if (b"\x00" in op.key or len(op.key) > eng.key_width
                        or (len(op.value) > eng.val_width
                            and eng.val_width < 8)):
                    # width/framing violations raise typed errors from
                    # the engine itself; run those solo so the message
                    # is byte-identical to the uncoalesced path
                    solo.append(op)
                    continue
                ts = db.clock.now()
                op.result = ts
                op.filled = True
                muts.append((op.key, op.value, ts, 0,
                             op.kind == "delete"))
                riders.append(op)
            if len(muts) == 1:
                # a train of one is a solo op: identical WAL shape
                # (engine.put syncs inline, so this rider is durable at
                # ack exactly like a solo DB.put)
                k, v, ts, _txn, tomb = muts[0]
                if tomb:
                    eng.delete(k, ts=ts)
                else:
                    eng.put(k, v, ts=ts)
            elif muts:
                resp = {"ts": [m[2] for m in muts]}
                eng.apply_rpc_batch(self.cid, next(self._seq), muts, resp,
                                    sync=False)
        if len(muts) > 1:
            try:
                eng.wal_sync()
            # crlint: allow-broad-except(per-rider demux: a failed sync — injected disk fault — reaches every rider the way it reaches a solo caller)
            except Exception as e:  # noqa: BLE001
                for op in riders:
                    op.result = None
                    op.error = e
        for op in solo:
            self._finish_solo(op)

    def _flush_reads(self, reads: list[_Op]) -> None:
        """All reads of the train under one engine-mutex hold (the locks
        are reentrant; solo reads acquire per call). Intent conflicts
        surface per-key, exactly as solo ``DB.get`` raises them."""
        db = self.db
        with db.engine.mu:
            for op in reads:
                try:
                    ts = (op.ts_arg if op.ts_arg is not None
                          else db.clock.now())
                    op.result = db.engine.get(op.key, ts=ts)
                # crlint: allow-broad-except(per-rider demux: the error is re-raised verbatim in the one submitting session)
                except Exception as e:  # noqa: BLE001
                    op.error = e
                op.filled = True

    def _finish_solo(self, op: _Op) -> None:
        """Degrade one rider to the uncoalesced per-session path (fault
        fallback and typed-error passthrough)."""
        db = self.db
        try:
            if op.kind == "put":
                op.result = db._put_solo(op.key, op.value)
            elif op.kind == "delete":
                op.result = db._delete_solo(op.key)
            else:
                op.result = db._get_solo(op.key, op.ts_arg)
        # crlint: allow-broad-except(per-rider demux: the error is re-raised verbatim in the one submitting session)
        except Exception as e:  # noqa: BLE001
            op.error = e
        op.filled = True


# one coalescer per DB, attached lazily the first time the gate is on
_attach_mu = locks.lock("kv.coalesce.attach")


def for_db(db) -> BatchCoalescer:
    co = getattr(db, "_coalescer", None)
    if co is None:
        with _attach_mu:
            co = getattr(db, "_coalescer", None)
            if co is None:
                co = BatchCoalescer(db)
                db._coalescer = co
    return co


def reset_db(db) -> None:
    """Drop a DB's attached coalescer (test isolation)."""
    with _attach_mu:
        if getattr(db, "_coalescer", None) is not None:
            db._coalescer = None
