"""KV-backed SQL tables — the TableReader path over the MVCC engine.

Reference: SQL reads flow through colfetcher's ColBatchScan -> cFetcher ->
kv.Txn (pkg/sql/colfetcher/colbatch_scan.go:200), decoding KV pairs into
coldata.Batch; writes encode rows and go through kv.Txn.Put. Here KVTable
is both:

- the write surface: ``insert``/``delete_pk`` run inside a kv transaction
  (intents, refresh validation, retries — kv/txn.py), encoding rows via
  storage/rowcodec.py;
- the read surface: ``device_batch`` produces a columnar Batch straight
  from the engine's device-resident merged view — mvcc_scan_filter picks
  newest-visible versions, rowcodec.decode_columns unpacks values — the
  "direct columnar scan" default path (pkg/storage/col_mvcc.go:25-90).

KVTable quacks like catalog.Table (schema / num_rows / dict_by_index /
device_batch), so ScanOp, the flow engine and sql() work unchanged on
KV-backed tables. Fixed-width column families only (INT/DECIMAL/DATE/
TIMESTAMP/INTERVAL/FLOAT/BOOL); STRING/BYTES land with the high-cardinality
string path.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..coldata.batch import Batch
from ..coldata.types import Family, Schema
from ..storage import rowcodec
from ..storage.lsm import Engine, WriteIntentError
from .txn import DB, Txn

_UNSUPPORTED = (Family.STRING, Family.BYTES, Family.JSON)


class KVTable:
    def __init__(self, db: DB, name: str, schema: Schema, pk: str,
                 table_id: int):
        for t in schema.types:
            if t.family in _UNSUPPORTED:
                raise TypeError(
                    f"KV tables support fixed-width columns only, got {t}"
                )
        if not 0 <= table_id <= rowcodec.MAX_TABLE_ID:
            raise ValueError(
                f"table_id must be in [0, {rowcodec.MAX_TABLE_ID}]"
            )
        self.db = db
        self.name = name
        self.schema = schema
        self.pk = pk
        self.pk_idx = schema.index(pk)
        self.table_id = table_id
        self._count_cache = None  # ((engine seq, gen), row count)
        need = rowcodec.value_width(schema)
        if db.engine.val_width < need:
            raise ValueError(
                f"engine val_width {db.engine.val_width} < row width {need}"
            )
        # snapshot timestamp for reads; None = now() at device_batch time
        self.read_ts: int | None = None

    # -- write surface ------------------------------------------------------

    def insert(self, t: Txn, row: dict) -> None:
        key = rowcodec.encode_pk(self.table_id, int(row[self.pk]))
        t.put(key, rowcodec.encode_row(self.schema, row))

    def delete_pk(self, t: Txn, pk: int) -> None:
        t.delete(rowcodec.encode_pk(self.table_id, int(pk)))

    def get_row(self, pk: int, ts: int | None = None) -> dict | None:
        v = self.db.get(rowcodec.encode_pk(self.table_id, int(pk)), ts=ts)
        return None if v is None else rowcodec.decode_row(self.schema, v)

    # -- Table facade (catalog.Table duck type) ------------------------------

    @property
    def num_rows(self) -> int:
        """Row-count estimate used only for planning (join ordering,
        broadcast decisions): a device-side count of newest-visible rows —
        no host materialization, and intents don't fail planning. Cached
        per engine write sequence so repeated binds don't re-scan."""
        from ..storage import keys as K
        from ..storage import mvcc

        eng: Engine = self.db.engine
        key = (eng._seq, eng._gen)  # _gen catches intent resolutions,
        # which change visibility without consuming a write sequence
        if self._count_cache is not None and self._count_cache[0] == key:
            return self._count_cache[1]
        view = eng._merged_view()
        if view is None:
            n = 0
        else:
            start, end = rowcodec.table_span(self.table_id)
            sel, _ = mvcc.mvcc_scan_filter(
                view, jnp.int64(self.db.clock.now()), jnp.int64(0),
                jnp.asarray(K.encode_bound(start, eng.key_width)),
                jnp.asarray(K.encode_bound(end, eng.key_width)),
            )
            n = int(np.asarray(jnp.sum(sel)))
        self._count_cache = (key, n)
        return n

    def dict_by_index(self) -> dict:
        return {}

    @property
    def dictionaries(self) -> dict:
        return {}

    @property
    def valids(self):
        # Nullability is data-dependent (it lives in the engine, not a host
        # bitmap). Raising AttributeError makes this sentinel impossible to
        # misread: duck-typed consumers using getattr(t, "valids", ...) /
        # hasattr fall back safely, while any code that would row-align a
        # host bitmap (arrow conversion, streaming scans) fails loudly
        # instead of silently treating a length-1 marker as real data.
        raise AttributeError(
            "KVTable has no host valid bitmaps; nullability is decoded on "
            "device by device_batch()"
        )

    def device_batch(self, names: tuple[str, ...] | None = None) -> Batch:
        """Columnar snapshot of the newest-visible rows, decoded on device.

        One mvcc_scan_filter pass over the merged view + the rowcodec
        decode kernel; raises WriteIntentError on another txn's intent in
        the span, exactly like the row read path."""
        from ..storage import keys as K
        from ..storage import mvcc

        names = names or self.schema.names
        idxs = tuple(self.schema.index(n) for n in names)
        ts = self.read_ts if self.read_ts is not None else self.db.clock.now()
        eng: Engine = self.db.engine
        view = eng._merged_view()
        if view is None:
            from ..coldata.batch import empty_batch

            return empty_batch(self.schema.select(idxs), 1024)
        start, end = rowcodec.table_span(self.table_id)
        sw = K.encode_bound(start, eng.key_width)
        ew = K.encode_bound(end, eng.key_width)
        sel, conflict = mvcc.mvcc_scan_filter(
            view, jnp.int64(ts), jnp.int64(0),
            jnp.asarray(sw), jnp.asarray(ew),
        )
        cnp = np.asarray(conflict)
        if cnp.any():
            hit = np.nonzero(cnp)[0]
            raise WriteIntentError(
                K.decode_keys(np.asarray(view.key)[hit]),
                [int(x) for x in np.asarray(view.txn)[hit]],
            )
        batch = rowcodec.decode_columns(view.value, sel,
                                        self.schema, idxs)
        if self.pk_idx in idxs:
            # the PK also lives in the value payload, but decoding it from
            # the key exercises/validates the key codec path
            pk_col = rowcodec.decode_pk_column(view.key)
            pos = idxs.index(self.pk_idx)
            from ..coldata.batch import Column

            cols = list(batch.cols)
            cols[pos] = Column(data=pk_col, valid=sel)
            batch = Batch(cols=tuple(cols), mask=batch.mask)
        return batch


def create_kv_table(catalog, db: DB, name: str, schema: Schema, pk: str,
                    table_id: int | None = None) -> KVTable:
    """Create + register a KV-backed table in the catalog so sql()/Rel
    scans resolve to it. table_id determines the key-space prefix; ids must
    be unique per engine or spans would overlap."""
    used = {t.table_id for t in catalog.tables.values()
            if isinstance(t, KVTable)}
    if table_id is None:
        table_id = max(used, default=0) + 1
    elif table_id in used:
        raise ValueError(f"table_id {table_id} already in use")
    t = KVTable(db, name, schema, pk, table_id)
    catalog.tables[name] = t
    return t
