"""KV-backed SQL tables — the TableReader path over the MVCC engine.

Reference: SQL reads flow through colfetcher's ColBatchScan -> cFetcher ->
kv.Txn (pkg/sql/colfetcher/colbatch_scan.go:200), decoding KV pairs into
coldata.Batch; writes encode rows and go through kv.Txn.Put. Here KVTable
is both:

- the write surface: ``insert``/``delete_pk`` run inside a kv transaction
  (intents, refresh validation, retries — kv/txn.py), encoding rows via
  storage/rowcodec.py;
- the read surface: ``device_batch`` produces a columnar Batch straight
  from the engine's device-resident merged view — mvcc_scan_filter picks
  newest-visible versions, rowcodec.decode_columns unpacks values — the
  "direct columnar scan" default path (pkg/storage/col_mvcc.go:25-90).

KVTable quacks like catalog.Table (schema / num_rows / dict_by_index /
device_batch), so ScanOp, the flow engine and sql() work unchanged on
KV-backed tables. Fixed-width column families only (INT/DECIMAL/DATE/
TIMESTAMP/INTERVAL/FLOAT/BOOL); STRING/BYTES land with the high-cardinality
string path.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..coldata.batch import Batch, Dictionary
from ..coldata.types import Family, Schema
from ..storage import rowcodec
from ..storage.lsm import Engine, WriteIntentError
from .txn import DB, Txn

_UNSUPPORTED = (Family.BYTES, Family.JSON)


class _TableDict:
    """Growable per-column string dictionary for a KV table.

    Codes live in the row payload (int32 slots); the code -> string mapping
    persists in the SAME engine under a companion dictionary table id, so a
    restore rebuilds it by scanning that span — the system-table discipline
    (the reference keeps descriptors/interning in system ranges). Query
    plans take an immutable Dictionary snapshot at bind time."""

    def __init__(self, values: list[str] | None = None):
        self.values: list[str] = list(values or [])
        self._code: dict[str, int] = {v: i for i, v in enumerate(self.values)}
        self._snapshot = None

    def code_of(self, v: str) -> int | None:
        return self._code.get(v)

    def add(self, v: str) -> int:
        code = len(self.values)
        self.values.append(v)
        self._code[v] = code
        self._snapshot = None
        return code

    def snapshot(self) -> Dictionary:
        if self._snapshot is None or len(self._snapshot) != len(self.values):
            self._snapshot = Dictionary(
                np.array(self.values, dtype=object)
            )
        return self._snapshot


class KVTable:
    def __init__(self, db: DB, name: str, schema: Schema, pk: str,
                 table_id: int, dict_table_id: int | None = None,
                 indexes: list | None = None):
        for t in schema.types:
            if t.family in _UNSUPPORTED:
                raise TypeError(
                    f"KV tables support fixed-width columns only, got {t}"
                )
        if not 0 <= table_id <= rowcodec.MAX_TABLE_ID:
            raise ValueError(
                f"table_id must be in [0, {rowcodec.MAX_TABLE_ID}]"
            )
        self.db = db
        self.name = name
        self.schema = schema
        self.pk = pk
        self.pk_idx = schema.index(pk)
        self.table_id = table_id
        self._count_cache = None  # ((engine seq, gen), row count)
        need = rowcodec.value_width(schema)
        if db.engine.val_width < need:
            raise ValueError(
                f"engine val_width {db.engine.val_width} < row width {need}"
            )
        # snapshot timestamp for reads; None = now() at device_batch time.
        # reader_txn makes columnar scans run AS a transaction: its own
        # intents are visible, other txns' intents conflict (the session's
        # explicit-txn SELECT path sets both around each statement)
        self.read_ts: int | None = None
        self.reader_txn: int = 0
        # STRING columns: dictionary-coded in the value slots; the mapping
        # persists in a companion key space of the same engine
        self._string_cols = tuple(
            i for i, t in enumerate(schema.types)
            if t.family is Family.STRING
        )
        self.dict_table_id = dict_table_id
        # secondary indexes (kv/index.IndexDesc); maintained inside every
        # row write's txn, visible to the planner via plan/indexopt.py
        self.indexes: list = list(indexes or [])
        self._dicts: dict[int, _TableDict] = {}
        if self._string_cols:
            if dict_table_id is None:
                raise ValueError(
                    "STRING columns need a dict_table_id (companion key "
                    "space for the persistent dictionary)"
                )
            self._load_dicts()

    # -- persistent dictionaries --------------------------------------------

    @staticmethod
    def _dict_pk(col: int, code: int) -> int:
        return (col << 40) | code

    def _load_dicts(self) -> None:
        """Rebuild dictionaries from the companion span (restore path)."""
        start, end = rowcodec.table_span(self.dict_table_id)
        rows = self.db.scan(start, end)
        by_col: dict[int, list[tuple[int, str]]] = {}
        for k, v in rows:
            pk = rowcodec.decode_pk(k)
            col, code = pk >> 40, pk & ((1 << 40) - 1)
            ln = int.from_bytes(v[:2], "little")
            by_col.setdefault(col, []).append(
                (code, v[2:2 + ln].decode("utf-8"))
            )
        for i in self._string_cols:
            entries = sorted(by_col.get(i, []))
            if [c for c, _ in entries] != list(range(len(entries))):
                raise ValueError(
                    f"corrupt string dictionary for {self.name!r} column "
                    f"{i}: codes {[c for c, _ in entries]} have holes"
                )
            self._dicts[i] = _TableDict([s for _, s in entries])

    def _encode_strings(self, t: Txn, row: dict) -> dict:
        """Replace str values with dictionary codes, persisting new entries
        in the same transaction (atomic with the row write).

        New codes stay PENDING on the transaction until commit: the
        in-memory dictionary must roll back with the txn, or a retry/abort
        would leave it permanently ahead of the engine's companion span
        (committed rows referencing codes the persistent dictionary lost)."""
        if not self._string_cols:
            return row
        out = dict(row)
        vw = self.db.engine.val_width
        slots = self._pending_slots(t)  # col -> {str: pending code}
        for i in self._string_cols:
            name = self.schema.names[i]
            v = out.get(name)
            if v is None:
                continue
            if isinstance(v, (int, np.integer)):
                continue  # already a code
            out[name] = self._txn_code(t, slots, i, str(v), vw)
        return out

    def _txn_code(self, t: Txn, slots: dict, i: int, v: str,
                  vw: int) -> int:
        """Dictionary code for one string value, allocating a txn-pending
        code (and its companion-span write) on first sight."""
        d = self._dicts.setdefault(i, _TableDict())
        slot = slots.setdefault(i, {})
        code = d.code_of(v)
        if code is None:
            code = slot.get(v)
        if code is None:
            enc = v.encode("utf-8")
            if len(enc) > 0xFFFF:
                raise ValueError(
                    f"string of {len(enc)} bytes exceeds the 64KiB "
                    "dictionary-entry bound (2-byte length header)"
                )
            code = len(d.values) + len(slot)
            slot[v] = code
            t.put(
                rowcodec.encode_pk(self.dict_table_id,
                                   self._dict_pk(i, code)),
                len(enc).to_bytes(2, "little") + enc,
            )
        return code

    def _pending_slots(self, t: Txn) -> dict:
        pending = getattr(t, "_dict_pending", None)
        if pending is None:
            pending = t._dict_pending = {}
        slots = pending.get(id(self))
        if slots is None:
            slots = pending[id(self)] = {}
            t.on_commit(lambda: self._commit_pending(slots))
        return slots

    def insert_rows(self, t: Txn, columns: dict[str, np.ndarray],
                    valids: dict[str, np.ndarray] | None = None) -> int:
        """Vectorized transactional INSERT (the colenc role: the write
        path encodes columns, not rows — sql/colenc in the reference).
        Keys and values encode in batched numpy passes; string columns
        dictionary-encode per UNIQUE value through the same txn-pending
        discipline as insert(); the txn takes one prepared put per row."""
        cols = dict(columns)
        valids = dict(valids or {})
        n = len(next(iter(cols.values())))
        vw = self.db.engine.val_width
        if self._string_cols:
            slots = self._pending_slots(t)
            for i in self._string_cols:
                name = self.schema.names[i]
                a = cols.get(name)
                if a is None:
                    continue
                arr = np.asarray(a)
                if arr.dtype.kind in ("i", "u"):
                    continue  # already codes
                vmask = valids.get(name)
                strs = np.array(
                    ["" if (vmask is not None and not vmask[j])
                     else str(x) for j, x in enumerate(arr)], dtype=str,
                )
                uvals, inverse = np.unique(strs, return_inverse=True)
                codes = np.empty(len(uvals), dtype=np.int64)
                for j, v in enumerate(uvals):
                    codes[j] = self._txn_code(t, slots, i, str(v), vw)
                cols[name] = codes[inverse]
        pks = np.asarray(cols[self.pk], dtype=np.int64)
        keys = rowcodec.encode_pk_batch(self.table_id, pks)
        values = rowcodec.encode_rows(self.schema, cols, valids)
        kb = keys.tobytes()
        vb = values.tobytes()
        kw = keys.shape[1]
        vw_row = values.shape[1]
        # upsert discipline: old rows must be read BEFORE the puts land
        # (afterwards t.get returns the txn's own fresh intent and the
        # stale-entry tombstone below would never fire)
        old_rows: dict[int, dict] = {}
        if self.indexes:
            for r in range(n):
                old_v = t.get(kb[r * kw:(r + 1) * kw])
                if old_v is not None:
                    old_rows[r] = rowcodec.decode_row(self.schema, old_v)
        for r in range(n):
            t.put(kb[r * kw:(r + 1) * kw], vb[r * vw_row:(r + 1) * vw_row])
        if self.indexes:
            from . import index as ixm

            for r in range(n):
                new_row = {}
                for name in self.schema.names:
                    a = cols.get(name)
                    if a is None:
                        continue
                    vmask = valids.get(name)
                    if vmask is not None and not vmask[r]:
                        continue
                    new_row[name] = a[r]
                ixm.maintain_row(t, self.indexes, self.schema, new_row,
                                 old_rows.get(r), int(pks[r]))
        self._count_cache = None
        return n

    def _commit_pending(self, slots: dict) -> None:
        for i, mapping in slots.items():
            d = self._dicts.setdefault(i, _TableDict())
            for v, code in sorted(mapping.items(), key=lambda x: x[1]):
                got = d.add(v)
                if got != code:
                    raise RuntimeError(
                        f"dictionary code drift: {v!r} got {got}, "
                        f"txn assigned {code}"
                    )

    # -- write surface ------------------------------------------------------

    def bulk_load(self, columns: dict[str, np.ndarray],
                  valids: dict[str, np.ndarray] | None = None,
                  chunk: int = 1 << 18) -> int:
        """Bulk-load typed host columns through the AddSSTable path: string
        columns dictionary-encode vectorized (np.unique + merge), values
        encode in one numpy pass (rowcodec.encode_rows), keys batch-encode,
        and each chunk lands as ONE sorted engine run — the IMPORT
        discipline (bulk writes skip the memtable/WAL and the per-row txn
        machinery; the load is atomic per chunk and idempotent to re-run
        at a higher timestamp)."""
        cols = dict(columns)
        n = len(next(iter(cols.values())))
        # vectorized dictionary encoding for STRING columns
        for i in self._string_cols:
            name = self.schema.names[i]
            a = np.asarray(cols[name])
            if a.dtype.kind in ("O", "U", "S"):
                d = self._dicts.setdefault(i, _TableDict())
                uvals, inverse = np.unique(a.astype(str),
                                           return_inverse=True)
                remap = np.empty(len(uvals), dtype=np.int32)
                new_entries = []
                for j, v in enumerate(uvals):
                    code = d.code_of(str(v))
                    if code is None:
                        code = d.add(str(v))
                        new_entries.append((code, str(v)))
                    remap[j] = code
                cols[name] = remap[inverse]
                for code, v in new_entries:  # persist the dictionary
                    enc = v.encode("utf-8")
                    self.db.put(
                        rowcodec.encode_pk(self.dict_table_id,
                                           self._dict_pk(i, code)),
                        len(enc).to_bytes(2, "little") + enc,
                    )
        ts = self.db.clock.now()
        pks = np.asarray(cols[self.pk], dtype=np.int64)
        keys = rowcodec.encode_pk_batch(self.table_id, pks)
        values = rowcodec.encode_rows(self.schema, cols, valids)
        from ..storage import ingest as bulk

        use_bulk = bulk.enabled()
        if use_bulk:
            # run-builder route: chunks accumulate into device-built
            # sorted/deduped runs (storage/ingest.py) and link into the
            # LSM with one WAL record per run
            rb = bulk.RunBuilder(self.db.engine, ts)
            for lo in range(0, n, chunk):
                hi = min(lo + chunk, n)
                rb.add(keys[lo:hi], values[lo:hi])
            rb.finish()
        else:
            for lo in range(0, n, chunk):
                hi = min(lo + chunk, n)
                self.db.engine.ingest(keys[lo:hi], values[lo:hi], ts=ts)
        if self.indexes:
            # index runs ingest alongside the rows (IMPORT assumes fresh
            # pks — the insert path handles upsert tombstoning)
            from . import index as ixm

            for ix in self.indexes:
                a = cols.get(ix.col)
                if a is None:
                    continue
                vmask = valids.get(ix.col)
                keep = (np.asarray(vmask, dtype=bool) if vmask is not None
                        else np.ones(n, dtype=bool))
                ik = ixm.encode_entries(
                    ix.index_id, np.asarray(a, dtype=np.int64)[keep],
                    pks[keep])
                iv = np.zeros((len(ik), 0), dtype=np.uint8)
                if use_bulk:
                    # the builder sorts device-side — no host lexsort
                    rb = bulk.RunBuilder(self.db.engine, ts)
                    for lo in range(0, len(ik), chunk):
                        hi = min(lo + chunk, len(ik))
                        rb.add(ik[lo:hi], iv[lo:hi])
                    rb.finish()
                else:
                    # entries must land SORTED (ingest builds one run)
                    order = np.lexsort(ik.T[::-1])
                    ik = ik[order]
                    for lo in range(0, len(ik), chunk):
                        hi = min(lo + chunk, len(ik))
                        self.db.engine.ingest(ik[lo:hi], iv[lo:hi], ts=ts)
        self._count_cache = None
        return n

    def insert(self, t: Txn, row: dict) -> None:
        row = self._encode_strings(t, row)
        pk = int(row[self.pk])
        key = rowcodec.encode_pk(self.table_id, pk)
        if self.indexes:
            # MVCC puts are upserts: a replaced row's stale index entries
            # must tombstone in the same txn (rowenc secondary-index
            # maintenance; the reference reads the old row for updates too)
            from . import index as ix

            old_v = t.get(key)
            old = (rowcodec.decode_row(self.schema, old_v)
                   if old_v is not None else None)
            ix.maintain_row(t, self.indexes, self.schema, row, old, pk)
        t.put(key, rowcodec.encode_row(self.schema, row))

    def delete_pk(self, t: Txn, pk: int) -> None:
        key = rowcodec.encode_pk(self.table_id, int(pk))
        if self.indexes:
            from . import index as ix

            old_v = t.get(key)
            if old_v is not None:
                ix.maintain_row(t, self.indexes, self.schema, None,
                                rowcodec.decode_row(self.schema, old_v),
                                int(pk))
        t.delete(key)

    def get_row_txn(self, t: Txn, pk: int) -> dict | None:
        """Transactional row read: goes through Txn.get so the read lands in
        the txn's read spans (commit-time refresh validation), observes the
        txn's snapshot, and converts intent conflicts to retryable errors —
        the difference between a real multi-statement transaction and a
        dirty read (kv.Txn.Get semantics)."""
        v = t.get(rowcodec.encode_pk(self.table_id, int(pk)))
        if v is None:
            return None
        row = rowcodec.decode_row(self.schema, v)
        for i in self._string_cols:
            name = self.schema.names[i]
            code = row.get(name)
            if code is not None:
                row[name] = self._dicts[i].values[int(code)]
        return row

    def get_row(self, pk: int, ts: int | None = None) -> dict | None:
        v = self.db.get(rowcodec.encode_pk(self.table_id, int(pk)), ts=ts)
        if v is None:
            return None
        row = rowcodec.decode_row(self.schema, v)
        for i in self._string_cols:
            name = self.schema.names[i]
            code = row.get(name)
            if code is not None:
                row[name] = self._dicts[i].values[int(code)]
        return row

    # -- Table facade (catalog.Table duck type) ------------------------------

    @property
    def num_rows(self) -> int:
        """Row-count estimate used only for planning (join ordering,
        broadcast decisions): a device-side count of newest-visible rows —
        no host materialization, and intents don't fail planning. Cached
        per engine write sequence so repeated binds don't re-scan."""
        from ..storage import keys as K
        from ..storage import mvcc

        eng: Engine = self.db.engine
        key = (eng._seq, eng._gen)  # _gen catches intent resolutions,
        # which change visibility without consuming a write sequence
        if self._count_cache is not None and self._count_cache[0] == key:
            return self._count_cache[1]
        view = eng._merged_view()
        if view is None:
            n = 0
        else:
            start, end = rowcodec.table_span(self.table_id)
            sel, _ = mvcc.mvcc_scan_filter(
                view, jnp.int64(self.db.clock.now()), jnp.int64(0),
                jnp.asarray(K.encode_bound(start, eng.key_width)),
                jnp.asarray(K.encode_bound(end, eng.key_width)),
            )
            n = int(np.asarray(jnp.sum(sel)))
        self._count_cache = (key, n)
        return n

    # -- statistics (sql/stats) ---------------------------------------------

    def set_stats(self, st) -> None:
        """Install ANALYZE statistics; (lo, hi) bounds feed col_stats for
        exact-key planning, row_count feeds estimated_rows."""
        self.table_stats = st

    def estimated_rows(self) -> int:
        st = getattr(self, "table_stats", None)
        return st.row_count if st is not None else self.num_rows

    def col_stats(self) -> dict[str, tuple]:
        st = getattr(self, "table_stats", None)
        if st is None:
            return {}
        return {
            n: (c.lo, c.hi)
            for n, c in st.cols.items()
            if c.lo is not None and c.hi is not None
        }

    def snapshot_live_rows(self) -> int:
        """Live-row count at the CURRENT read context (read_ts/reader_txn)
        — what a scan of this table will actually see. num_rows counts
        newest-visible at now() with no reader; a pinned snapshot or an
        in-txn read can hold MORE rows, and distributed planners must size
        shards for the snapshot, not the present."""
        from ..storage import keys as K
        from ..storage import mvcc
        from ..storage import rowcodec

        eng: Engine = self.db.engine
        view = eng._merged_view()
        if view is None:
            return 0
        start, end = rowcodec.table_span(self.table_id)
        ts = self.read_ts if self.read_ts is not None else self.db.clock.now()
        sel, _ = mvcc.mvcc_scan_filter(
            view, jnp.int64(ts), jnp.int64(self.reader_txn),
            jnp.asarray(K.encode_bound(start, eng.key_width)),
            jnp.asarray(K.encode_bound(end, eng.key_width)),
        )
        return int(np.asarray(jnp.sum(sel, dtype=jnp.int32)))

    def dict_by_index(self) -> dict:
        return {i: d.snapshot() for i, d in self._dicts.items()}

    @property
    def dictionaries(self) -> dict:
        return {
            self.schema.names[i]: d.snapshot()
            for i, d in self._dicts.items()
        }

    @property
    def valids(self):
        # Nullability is data-dependent (it lives in the engine, not a host
        # bitmap). Raising AttributeError makes this sentinel impossible to
        # misread: duck-typed consumers using getattr(t, "valids", ...) /
        # hasattr fall back safely, while any code that would row-align a
        # host bitmap (arrow conversion, streaming scans) fails loudly
        # instead of silently treating a length-1 marker as real data.
        raise AttributeError(
            "KVTable has no host valid bitmaps; nullability is decoded on "
            "device by device_batch()"
        )

    def snapshot_token(self):
        """Identity of the snapshot ``device_batch`` decodes RIGHT NOW:
        equal tokens guarantee bit-identical decodes. The engine write
        seq pins the version set (the clock only moves forward, so two
        current-time reads at the same seq see the same newest-visible
        rows); read_ts/reader_txn pin time-travel and intent visibility.
        flow/sharedscan.py uses this to let concurrent scans adopt one
        shared decoded batch. None when the backend has no seq surface."""
        eng = self.db.engine
        seq = getattr(eng, "_seq", None)
        if seq is None:
            stores = getattr(eng, "stores", None)  # DistSender backend
            if stores is None:
                return None
            seq = tuple(sorted(
                (sid, s.engine._seq) for sid, s in stores.items()))
        return (id(eng), seq, self.read_ts, self.reader_txn)

    def device_batch(self, names: tuple[str, ...] | None = None) -> Batch:
        """Columnar snapshot of the newest-visible rows, decoded on device.

        One mvcc_scan_filter pass over the merged view + the rowcodec
        decode kernel; raises WriteIntentError on another txn's intent in
        the span, exactly like the row read path."""
        from ..storage import keys as K
        from ..storage import mvcc

        names = names or self.schema.names
        idxs = tuple(self.schema.index(n) for n in names)
        ts = self.read_ts if self.read_ts is not None else self.db.clock.now()
        eng: Engine = self.db.engine
        view = eng._merged_view()
        if view is None:
            from ..coldata.batch import empty_batch

            return empty_batch(self.schema.select(idxs), 1024)
        start, end = rowcodec.table_span(self.table_id)
        sw = K.encode_bound(start, eng.key_width)
        ew = K.encode_bound(end, eng.key_width)
        sel, conflict = mvcc.mvcc_scan_filter(
            view, jnp.int64(ts), jnp.int64(self.reader_txn),
            jnp.asarray(sw), jnp.asarray(ew),
        )
        cnp = np.asarray(conflict)
        if cnp.any():
            hit = np.nonzero(cnp)[0]
            raise WriteIntentError(
                K.decode_keys(np.asarray(view.key)[hit]),
                [int(x) for x in np.asarray(view.txn)[hit]],
            )
        batch = rowcodec.decode_columns(view.value, sel,
                                        self.schema, idxs)
        if self.pk_idx in idxs:
            # the PK also lives in the value payload, but decoding it from
            # the key exercises/validates the key codec path
            pk_col = rowcodec.decode_pk_column(view.key)
            pos = idxs.index(self.pk_idx)
            from ..coldata.batch import Column

            cols = list(batch.cols)
            cols[pos] = Column(data=pk_col, valid=sel)
            batch = Batch(cols=tuple(cols), mask=batch.mask)
        return batch


_DESC_PREFIX = b"\x01desc"


def _descriptor_key(table_id: int, chunk: int) -> bytes:
    return _DESC_PREFIX + b"%03d|%03d" % (table_id, chunk)


def write_descriptor(db: DB, t: KVTable, writer=None) -> None:
    """Persist the table descriptor in the system keyspace (the
    system.descriptor discipline: schemas are data, so a fresh process over
    the same engine rediscovers every table). The JSON chunks across rows
    so descriptors fit any engine value width. `writer`: an open Txn so a
    caller can make the swap atomic with other writes (schema changes
    commit the descriptor and their completion marker together)."""
    import json

    desc = {
        "name": t.name,
        "names": list(t.schema.names),
        "types": [
            {"family": ty.family.name, "width": ty.width,
             "precision": ty.precision, "scale": ty.scale}
            for ty in t.schema.types
        ],
        "pk": t.pk,
        "table_id": t.table_id,
        "dict_table_id": t.dict_table_id,
        "indexes": [
            {"name": ix.name, "col": ix.col, "index_id": ix.index_id}
            for ix in t.indexes
        ],
    }
    from .chunked import chunk_blob

    blob = json.dumps(desc).encode("utf-8")
    step = max(16, db.engine.val_width - 1)
    # length-headered chunks: a SHORTER rewrite (DROP COLUMN) leaves the
    # old tail chunks in place and readers truncate past them
    w = writer if writer is not None else db
    for ci, piece in enumerate(chunk_blob(blob, step)):
        w.put(_descriptor_key(t.table_id, ci), piece)


def load_catalog_from_engine(catalog, db: DB,
                             id_range: tuple[int, int] | None = None
                             ) -> list[str]:
    """Rebuild KVTable entries from persisted descriptors (the catalog
    bootstrap / lease-free resolution path). Returns the table names.
    id_range scopes discovery to a tenant's table-id slice (kv/tenant.py):
    a tenant session never even learns other tenants' schemas."""
    import json

    from ..coldata.types import Family as F
    from ..coldata.types import Schema as S
    from ..coldata.types import SQLType

    blobs: dict[bytes, list[tuple[bytes, bytes]]] = {}
    for k, v in db.scan(_DESC_PREFIX, _DESC_PREFIX + b"\xff"):
        tid = k[len(_DESC_PREFIX):].split(b"|")[0]
        blobs.setdefault(tid, []).append((k, v))
    from .chunked import unchunk

    out = []
    for tid in sorted(blobs):
        blob = unchunk([v for _, v in sorted(blobs[tid])])
        desc = json.loads(blob.decode("utf-8"))
        if id_range is not None and not (
            id_range[0] <= desc["table_id"] <= id_range[1]
        ):
            continue
        types = tuple(
            SQLType(F[d["family"]], width=d["width"],
                    precision=d["precision"], scale=d["scale"])
            for d in desc["types"]
        )
        from .index import IndexDesc

        t = KVTable(db, desc["name"], S(tuple(desc["names"]), types),
                    desc["pk"], desc["table_id"], desc["dict_table_id"],
                    indexes=[IndexDesc(d["name"], d["col"], d["index_id"])
                             for d in desc.get("indexes", [])])
        catalog.tables[desc["name"]] = t
        out.append(desc["name"])
    return out


def create_kv_table(catalog, db: DB, name: str, schema: Schema, pk: str,
                    table_id: int | None = None,
                    id_range: tuple[int, int] | None = None) -> KVTable:
    """Create + register a KV-backed table in the catalog so sql()/Rel
    scans resolve to it. table_id determines the key-space prefix; ids must
    be unique per engine or spans would overlap. Tables with STRING columns
    get a second id for the persistent dictionary span. id_range confines
    allocation to a tenant's keyspace slice (kv/tenant.py) — the catalog
    then cannot even address another tenant's spans. Unscoped callers
    allocate within the SYSTEM tenant's range (1..127), so a legacy
    session can never squat on a secondary tenant's reserved slice."""
    from .tenant import _SYSTEM_RANGE

    lo, hi = id_range if id_range is not None else _SYSTEM_RANGE
    used = set()
    for t in catalog.tables.values():
        if isinstance(t, KVTable):
            used.add(t.table_id)
            if t.dict_table_id is not None:
                used.add(t.dict_table_id)
            used.update(ix.index_id for ix in t.indexes)

    def alloc() -> int:
        # only ids INSIDE the range matter: a foreign tenant's high id in
        # a shared catalog must neither seed the allocator past `hi` nor
        # fail an otherwise-empty range
        nxt = max([i for i in used if lo <= i <= hi], default=lo - 1) + 1
        if nxt > hi:
            raise ValueError(
                f"tenant keyspace [{lo},{hi}] exhausted"
            )
        return nxt

    if table_id is None:
        table_id = alloc()
    elif table_id in used:
        raise ValueError(f"table_id {table_id} already in use")
    used.add(table_id)
    dict_table_id = None
    if any(tt.family is Family.STRING for tt in schema.types):
        dict_table_id = alloc()
    t = KVTable(db, name, schema, pk, table_id, dict_table_id)
    catalog.tables[name] = t
    write_descriptor(db, t)
    return t
