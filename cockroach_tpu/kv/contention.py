"""Contention event registry — the pkg/sql/contention reduction.

Reference: every time a request waits on (or aborts against) another
transaction's lock, a contention event (key, waiting txn, holding txn,
duration) lands in a per-node registry surfaced through
crdb_internal.cluster_contention_events and the console's insights page.

Reduction: the txn layer reports each WriteIntentError conflict here;
the registry aggregates per KEY (count, last holding txn, waiting txns
seen) with the same bounded-memory discipline as sqlstats, surfaced via
``SHOW CONTENTION`` and ``/_status/contention``."""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..utils import locks


@dataclass
class ContentionEvent:
    key: bytes
    count: int = 0
    last_holder: int = 0
    last_wall: float = 0.0
    waiters: set = field(default_factory=set)


class ContentionRegistry:
    def __init__(self, max_keys: int = 2000):
        self._lock = locks.lock("kv.contention")
        self._by_key: dict[bytes, ContentionEvent] = {}
        self.max_keys = max_keys
        self.evicted = 0

    def record(self, keys, holders, waiting_txn: int = 0) -> None:
        with self._lock:
            for k, h in zip(keys, holders):
                ev = self._by_key.get(k)
                if ev is None:
                    if len(self._by_key) >= self.max_keys:
                        keep = sorted(self._by_key.values(),
                                      key=lambda e: -e.count)
                        keep = keep[: self.max_keys // 2]
                        self.evicted += len(self._by_key) - len(keep)
                        self._by_key = {e.key: e for e in keep}
                    ev = self._by_key[k] = ContentionEvent(k)
                ev.count += 1
                ev.last_holder = int(h)
                ev.last_wall = time.time()
                if waiting_txn:
                    ev.waiters.add(int(waiting_txn))

    def rows_payload(self) -> list[dict]:
        with self._lock:
            evs = sorted(self._by_key.values(), key=lambda e: -e.count)
            return [
                {"key": e.key.decode("utf-8", "replace"),
                 "count": e.count, "lastHolderTxn": e.last_holder,
                 "numWaiters": len(e.waiters)}
                for e in evs
            ]

    def clear(self) -> None:
        with self._lock:
            self._by_key.clear()


DEFAULT = ContentionRegistry()
