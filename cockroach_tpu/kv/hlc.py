"""Hybrid logical clock — the pkg/util/hlc analog.

Reference: hlc.Clock issues timestamps (walltime, logical) that are totally
ordered, monotone per node, and close to wall time; readings advance on
message receipt (clock.Update). Here the pair packs into one int64
(wall millis << 20 | logical), matching the storage layer's single-int64
version timestamps. Milliseconds (not the reference's nanos) so the packed
value stays inside int64 until ~year 2248 with 2^20 logical ticks per ms.
"""

from __future__ import annotations

import time

LOGICAL_BITS = 20
LOGICAL_MASK = (1 << LOGICAL_BITS) - 1


def pack(wall_ms: int, logical: int) -> int:
    if not 0 <= logical <= LOGICAL_MASK:
        raise OverflowError(f"hlc logical component out of range: {logical}")
    ts = (wall_ms << LOGICAL_BITS) | logical
    if ts >= (1 << 63):
        raise OverflowError(f"hlc wall component overflows int64: {wall_ms}")
    return ts


def unpack(ts: int) -> tuple[int, int]:
    return ts >> LOGICAL_BITS, ts & LOGICAL_MASK


class Clock:
    """Monotone hybrid clock. now() never returns the same or a smaller
    timestamp twice; update(ts) ratchets past a remote observation."""

    def __init__(self, wall_fn=None):
        self._wall_fn = wall_fn or (lambda: int(time.time() * 1e3))
        self._last = 0
        self._ticks = 0  # local increments since the wall last advanced

    def now(self) -> int:
        wall = self._wall_fn()
        ts = pack(wall, 0)
        if ts <= self._last:
            # count LOCAL saturation only: a remote timestamp ingested by
            # update() may legitimately carry a large logical component (the
            # clock absorbs skew by running ahead), so the overflow signal is
            # "2^20 local ticks without wall progress", not a carry bit
            self._ticks += 1
            if self._ticks > LOGICAL_MASK:
                raise OverflowError(
                    "hlc logical counter saturated: 2^20 local ticks "
                    "without wall-clock progress"
                )
            ts = self._last + 1
        else:
            self._ticks = 0
        self._last = ts
        return ts

    def update(self, observed: int) -> int:
        """Advance past an observed remote timestamp (clock.Update)."""
        if observed > self._last:
            self._last = observed
        return self.now()


class ManualClock(Clock):
    """Deterministic clock for tests (the reference's timeutil manual time)."""

    def __init__(self, start: int = 1):
        super().__init__(wall_fn=lambda: self._manual)
        self._manual = start

    def advance(self, ticks: int = 1) -> None:
        self._manual += ticks
