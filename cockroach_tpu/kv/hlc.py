"""Hybrid logical clock — the pkg/util/hlc analog.

Reference: hlc.Clock issues timestamps (walltime, logical) that are totally
ordered, monotone per node, and close to wall time; readings advance on
message receipt (clock.Update). Here the pair packs into one int64
(wall micros << 20 | logical), matching the storage layer's single-int64
version timestamps.
"""

from __future__ import annotations

import time

LOGICAL_BITS = 20
LOGICAL_MASK = (1 << LOGICAL_BITS) - 1


def pack(wall_us: int, logical: int) -> int:
    return (wall_us << LOGICAL_BITS) | logical


def unpack(ts: int) -> tuple[int, int]:
    return ts >> LOGICAL_BITS, ts & LOGICAL_MASK


class Clock:
    """Monotone hybrid clock. now() never returns the same or a smaller
    timestamp twice; update(ts) ratchets past a remote observation."""

    def __init__(self, wall_us=None):
        self._wall_us = wall_us or (lambda: int(time.time() * 1e6))
        self._last = 0

    def now(self) -> int:
        wall = self._wall_us()
        ts = pack(wall, 0)
        if ts <= self._last:
            ts = self._last + 1
        self._last = ts
        return ts

    def update(self, observed: int) -> int:
        """Advance past an observed remote timestamp (clock.Update)."""
        if observed > self._last:
            self._last = observed
        return self.now()


class ManualClock(Clock):
    """Deterministic clock for tests (the reference's timeutil manual time)."""

    def __init__(self, start_us: int = 1):
        super().__init__(wall_us=lambda: self._manual)
        self._manual = start_us

    def advance(self, us: int = 1) -> None:
        self._manual += us
