"""KV Batch RPC — the Internal.Batch service reduction.

Reference: every KV request travels as a BatchRequest of typed sub-
requests (Get/Put/Delete/Scan/...) over the gRPC `Internal` service
(kvpb/api.proto:3691 Batch, :3697 streaming BatchStream); DistSender
splits client batches by range and fans them out to these endpoints.

Reduction: one listening socket per server speaking the DCN length-
prefixed framing with JSON envelopes (base64 for byte payloads — the
same byte-exact discipline as raw rangefeeds). A batch is a list of sub-
requests evaluated IN ORDER against the server's DB (non-transactional
requests, like the reference's non-txn batches; the txn layer stays
client-side in this build). Errors return per-batch with a typed code so
clients can distinguish WriteIntentError (retryable wait) from hard
failures. The connection is persistent: one client can stream many
batches (the BatchStream shape).
"""

from __future__ import annotations

import base64
import json
import socket
import threading

from ..storage.lsm import WriteIntentError
from ..utils.faults import InjectedFault
from .txn import DB


def _b64(b: bytes | None) -> str | None:
    return None if b is None else base64.b64encode(b).decode("ascii")


def _unb64(s: str | None) -> bytes | None:
    return None if s is None else base64.b64decode(s)


class BatchServer:
    """Serve Batch RPCs against one DB (Node.Batch -> Store.Send role)."""

    def __init__(self, db: DB, host: str = "127.0.0.1", port: int = 0):
        self.db = db
        # SO_REUSEADDR so a restart rebinds the port while the previous
        # incarnation's conns sit in TIME_WAIT (create_server sets it on
        # POSIX; made explicit because restart-on-same-port is contract)
        self._srv = socket.create_server((host, port))
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.settimeout(0.2)
        self.addr = self._srv.getsockname()
        self._stop = threading.Event()
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        self._threads: list[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._serve, daemon=True, name="kv-batch-server")
        self._accept_thread.start()

    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._conns_lock:
                if self._stop.is_set():
                    conn.close()
                    return
                self._conns.add(conn)
                t = threading.Thread(target=self._conn_loop, args=(conn,),
                                     daemon=True)
                self._threads.append(t)
            t.start()

    def _conn_loop(self, conn):
        """Persistent per-connection loop (BatchStream shape): one bad
        request answers with an error frame, never kills the server."""
        from ..flow.dcn import _recv_msg, _send_msg

        try:
            while not self._stop.is_set():
                msg = _recv_msg(conn)
                if msg is None:
                    return
                try:
                    req = json.loads(msg.decode("utf-8"))
                    resp = self._eval_batch(req)
                except InjectedFault as e:
                    if e.kind == "drop":
                        raise  # sever the stream, like a crashed replica
                    resp = {"error": str(e), "code": "Internal"}
                except WriteIntentError as e:
                    # carry the REAL conflicting keys/txns: clients format
                    # them into user errors and conflict handling keys on
                    # the txn ids
                    resp = {"error": str(e), "code": "WriteIntentError",
                            "keys": [_b64(k) for k in e.keys],
                            "txns": list(e.txns)}
                except Exception as e:  # noqa: BLE001
                    resp = {"error": f"{type(e).__name__}: {e}",
                            "code": "Internal"}
                _send_msg(conn, json.dumps(resp).encode("utf-8"))
        except (OSError, ConnectionError):
            pass  # client went away
        finally:
            conn.close()
            with self._conns_lock:
                self._conns.discard(conn)

    def _eval_batch(self, req: dict) -> dict:
        """Evaluate sub-requests in order (batcheval's cmd_* dispatch)."""
        from ..utils import faults

        # replica-side evaluation fault (TestingEvalFilter analog): fires
        # BEFORE any sub-request touches the store, so a dropped batch is
        # all-or-nothing and a retry replays it exactly
        faults.fire("kv.rpc.server.eval")
        out = []
        for r in req.get("requests", ()):
            op = r["op"]
            if op == "put":
                ts = self.db.put(_unb64(r["key"]), _unb64(r["value"]))
                out.append({"ts": ts})
            elif op == "delete":
                ts = self.db.delete(_unb64(r["key"]))
                out.append({"ts": ts})
            elif op == "get":
                v = self.db.get(_unb64(r["key"]), ts=r.get("ts"))
                out.append({"value": _b64(v)})
            elif op == "scan":
                rows = self.db.scan(
                    _unb64(r.get("start")), _unb64(r.get("end")),
                    ts=r.get("ts"), max_keys=r.get("max_keys"),
                )
                out.append({"rows": [[_b64(k), _b64(v)] for k, v in rows]})
            else:
                raise ValueError(f"unknown batch op {op!r}")
        return {"responses": out}

    def close(self):
        """Idempotent full teardown: stop accepting, sever every accepted
        conn, and JOIN the accept + per-conn threads (the stopper's
        "start/stop bound every thread" contract) — a closed server holds
        no port, no fd, and no thread, so back-to-back restarts on the
        same port never collide."""
        self._stop.set()
        self._srv.close()
        # closing established conns unblocks per-connection loops parked
        # in recv
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
            threads = list(self._threads)
            self._threads.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            c.close()
        if self._accept_thread is not threading.current_thread():
            self._accept_thread.join(timeout=5)
        for t in threads:
            if t is not threading.current_thread():
                t.join(timeout=5)


class BatchClient:
    """Dial a BatchServer; issue batches over one persistent connection.
    Raises WriteIntentError/RuntimeError mirroring the server's typed
    error codes (the DistSender would catch the former and retry).

    Transport discipline (the DistSender's send-retry reduction): every
    RPC runs under a per-call deadline (rpc.batch.deadline_s) and
    TRANSPORT failures — drops, resets, timeouts — re-dial and re-send
    with exponential backoff + jitter (rpc.batch.max_retries attempts).
    Typed SERVER answers (WriteIntentError, Internal) are never retried
    here: the txn layer owns intent waits, and hard errors must surface.
    A re-sent batch may double-apply if the failure hit after evaluation
    (the reference's AmbiguousResultError window); sub-requests are
    MVCC-idempotent enough for the non-txn surface this serves."""

    def __init__(self, addr, deadline_s: float | None = None,
                 max_retries: int | None = None):
        from ..utils import settings

        self.addr = tuple(addr)
        self.deadline_s = (deadline_s if deadline_s is not None
                          else settings.get("rpc.batch.deadline_s"))
        self.max_retries = (max_retries if max_retries is not None
                            else settings.get("rpc.batch.max_retries"))
        self._sock = self._dial()
        self._lock = threading.Lock()

    def _dial(self) -> socket.socket:
        s = socket.create_connection(self.addr, timeout=self.deadline_s)
        s.settimeout(self.deadline_s)
        return s

    def _redial(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = self._dial()

    @staticmethod
    def _transport_error(e: BaseException) -> bool:
        """Retry ONLY wire-level failures; typed server errors surface."""
        return isinstance(e, (ConnectionError, socket.timeout,
                              TimeoutError, OSError))

    def batch(self, requests: list[dict]) -> list[dict]:
        from ..utils import faults, metric, retry
        from ..flow.dcn import _recv_msg, _send_msg

        payload = json.dumps({"requests": requests}).encode("utf-8")

        def send_once():
            with self._lock:  # one in-flight batch per connection
                faults.fire("kv.rpc.client.batch")
                try:
                    _send_msg(self._sock, payload)
                    msg = _recv_msg(self._sock)
                except (socket.timeout, TimeoutError) as e:
                    metric.RPC_TIMEOUTS.inc()
                    # a timed-out stream has unknown framing state: the
                    # next attempt MUST start on a fresh connection
                    self._redial()
                    raise retry.RPCDeadlineError(
                        f"batch rpc deadline ({self.deadline_s}s) "
                        f"exceeded against {self.addr}") from e
                except (ConnectionError, OSError):
                    self._redial()
                    raise
            if msg is None:
                self._redial()
                raise ConnectionError("batch server closed the stream")
            return msg

        msg = retry.call(
            send_once,
            retry.Backoff(max_attempts=self.max_retries,
                          deadline_s=self.deadline_s * self.max_retries),
            retryable=self._transport_error,
        )
        resp = json.loads(msg.decode("utf-8"))
        if "error" in resp:
            if resp.get("code") == "WriteIntentError":
                raise WriteIntentError(
                    [_unb64(k) for k in resp.get("keys", [])],
                    resp.get("txns", []),
                )
            raise RuntimeError(f"batch rpc failed: {resp['error']}")
        return resp["responses"]

    # convenience single-op wrappers (the kv.DB surface over RPC)
    def put(self, key: bytes, value: bytes) -> int:
        return self.batch([{"op": "put", "key": _b64(key),
                            "value": _b64(value)}])[0]["ts"]

    def get(self, key: bytes, ts: int | None = None) -> bytes | None:
        r = {"op": "get", "key": _b64(key)}
        if ts is not None:
            r["ts"] = ts
        return _unb64(self.batch([r])[0]["value"])

    def delete(self, key: bytes) -> int:
        return self.batch([{"op": "delete",
                            "key": _b64(key)}])[0]["ts"]

    def scan(self, start: bytes | None, end: bytes | None,
             max_keys: int | None = None) -> list[tuple[bytes, bytes]]:
        r = {"op": "scan", "start": _b64(start), "end": _b64(end)}
        if max_keys is not None:
            r["max_keys"] = max_keys
        return [(base64.b64decode(k), base64.b64decode(v))
                for k, v in self.batch([r])[0]["rows"]]

    def close(self):
        self._sock.close()
