"""KV Batch RPC — the Internal.Batch service reduction.

Reference: every KV request travels as a BatchRequest of typed sub-
requests (Get/Put/Delete/Scan/...) over the gRPC `Internal` service
(kvpb/api.proto:3691 Batch, :3697 streaming BatchStream); DistSender
splits client batches by range and fans them out to these endpoints.

Reduction: one listening socket per server speaking the DCN length-
prefixed framing with JSON envelopes (base64 for byte payloads — the
same byte-exact discipline as raw rangefeeds). A batch is a list of sub-
requests evaluated IN ORDER against the server's DB (non-transactional
requests, like the reference's non-txn batches; the txn layer stays
client-side in this build). Errors return per-batch with a typed code so
clients can distinguish WriteIntentError (retryable wait) from hard
failures. The connection is persistent: one client can stream many
batches (the BatchStream shape).
"""

from __future__ import annotations

import base64
import json
import socket
import threading

from ..storage.lsm import WriteIntentError
from .txn import DB


def _b64(b: bytes | None) -> str | None:
    return None if b is None else base64.b64encode(b).decode("ascii")


def _unb64(s: str | None) -> bytes | None:
    return None if s is None else base64.b64decode(s)


class BatchServer:
    """Serve Batch RPCs against one DB (Node.Batch -> Store.Send role)."""

    def __init__(self, db: DB, host: str = "127.0.0.1", port: int = 0):
        self.db = db
        self._srv = socket.create_server((host, port))
        self._srv.settimeout(0.2)
        self.addr = self._srv.getsockname()
        self._stop = threading.Event()
        self._conns: set = set()
        self._conns_lock = threading.Lock()
        threading.Thread(target=self._serve, daemon=True,
                         name="kv-batch-server").start()

    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._conns_lock:
                if self._stop.is_set():
                    conn.close()
                    return
                self._conns.add(conn)
            threading.Thread(target=self._conn_loop, args=(conn,),
                             daemon=True).start()

    def _conn_loop(self, conn):
        """Persistent per-connection loop (BatchStream shape): one bad
        request answers with an error frame, never kills the server."""
        from ..flow.dcn import _recv_msg, _send_msg

        try:
            while not self._stop.is_set():
                msg = _recv_msg(conn)
                if msg is None:
                    return
                try:
                    req = json.loads(msg.decode("utf-8"))
                    resp = self._eval_batch(req)
                except WriteIntentError as e:
                    # carry the REAL conflicting keys/txns: clients format
                    # them into user errors and conflict handling keys on
                    # the txn ids
                    resp = {"error": str(e), "code": "WriteIntentError",
                            "keys": [_b64(k) for k in e.keys],
                            "txns": list(e.txns)}
                except Exception as e:  # noqa: BLE001
                    resp = {"error": f"{type(e).__name__}: {e}",
                            "code": "Internal"}
                _send_msg(conn, json.dumps(resp).encode("utf-8"))
        except (OSError, ConnectionError):
            pass  # client went away
        finally:
            conn.close()
            with self._conns_lock:
                self._conns.discard(conn)

    def _eval_batch(self, req: dict) -> dict:
        """Evaluate sub-requests in order (batcheval's cmd_* dispatch)."""
        out = []
        for r in req.get("requests", ()):
            op = r["op"]
            if op == "put":
                ts = self.db.put(_unb64(r["key"]), _unb64(r["value"]))
                out.append({"ts": ts})
            elif op == "delete":
                ts = self.db.delete(_unb64(r["key"]))
                out.append({"ts": ts})
            elif op == "get":
                v = self.db.get(_unb64(r["key"]), ts=r.get("ts"))
                out.append({"value": _b64(v)})
            elif op == "scan":
                rows = self.db.scan(
                    _unb64(r.get("start")), _unb64(r.get("end")),
                    ts=r.get("ts"), max_keys=r.get("max_keys"),
                )
                out.append({"rows": [[_b64(k), _b64(v)] for k, v in rows]})
            else:
                raise ValueError(f"unknown batch op {op!r}")
        return {"responses": out}

    def close(self):
        self._stop.set()
        self._srv.close()
        # established connections must stop serving too (Node.stop's
        # "start/stop bound every thread" contract): closing them unblocks
        # the per-connection loops parked in recv
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            c.close()


class BatchClient:
    """Dial a BatchServer; issue batches over one persistent connection.
    Raises WriteIntentError/RuntimeError mirroring the server's typed
    error codes (the DistSender would catch the former and retry)."""

    def __init__(self, addr):
        self._sock = socket.create_connection(tuple(addr))
        self._lock = threading.Lock()

    def batch(self, requests: list[dict]) -> list[dict]:
        from ..flow.dcn import _recv_msg, _send_msg

        with self._lock:  # one in-flight batch per connection
            _send_msg(self._sock, json.dumps(
                {"requests": requests}).encode("utf-8"))
            msg = _recv_msg(self._sock)
        if msg is None:
            raise ConnectionError("batch server closed the stream")
        resp = json.loads(msg.decode("utf-8"))
        if "error" in resp:
            if resp.get("code") == "WriteIntentError":
                raise WriteIntentError(
                    [_unb64(k) for k in resp.get("keys", [])],
                    resp.get("txns", []),
                )
            raise RuntimeError(f"batch rpc failed: {resp['error']}")
        return resp["responses"]

    # convenience single-op wrappers (the kv.DB surface over RPC)
    def put(self, key: bytes, value: bytes) -> int:
        return self.batch([{"op": "put", "key": _b64(key),
                            "value": _b64(value)}])[0]["ts"]

    def get(self, key: bytes, ts: int | None = None) -> bytes | None:
        r = {"op": "get", "key": _b64(key)}
        if ts is not None:
            r["ts"] = ts
        return _unb64(self.batch([r])[0]["value"])

    def delete(self, key: bytes) -> int:
        return self.batch([{"op": "delete",
                            "key": _b64(key)}])[0]["ts"]

    def scan(self, start: bytes | None, end: bytes | None,
             max_keys: int | None = None) -> list[tuple[bytes, bytes]]:
        r = {"op": "scan", "start": _b64(start), "end": _b64(end)}
        if max_keys is not None:
            r["max_keys"] = max_keys
        return [(base64.b64decode(k), base64.b64decode(v))
                for k, v in self.batch([r])[0]["rows"]]

    def close(self):
        self._sock.close()
