"""KV Batch RPC — the Internal.Batch service reduction.

Reference: every KV request travels as a BatchRequest of typed sub-
requests (Get/Put/Delete/Scan/...) over the gRPC `Internal` service
(kvpb/api.proto:3691 Batch, :3697 streaming BatchStream); DistSender
splits client batches by range and fans them out to these endpoints.

Reduction: one listening socket per server speaking the DCN length-
prefixed framing with JSON envelopes (base64 for byte payloads — the
same byte-exact discipline as raw rangefeeds). A batch is a list of sub-
requests evaluated IN ORDER against the server's DB (non-transactional
requests, like the reference's non-txn batches; the txn layer stays
client-side in this build). Errors return per-batch with a typed code so
clients can distinguish WriteIntentError (retryable wait) from hard
failures. The connection is persistent: one client can stream many
batches (the BatchStream shape).
"""

from __future__ import annotations

import base64
import itertools
import json
import socket
import threading
import uuid

from ..storage.lsm import WriteIntentError
from ..utils import locks, tracing
from ..utils.errors import register_passthrough
from ..utils.faults import InjectedFault
from .liveness import EpochFencedError, NotLeaseHolderError
from .txn import DB

# leaseholder-guard errors travel as typed codes named after the class
_LEASE_ERRORS = (EpochFencedError, NotLeaseHolderError)


class AmbiguousResultError(RuntimeError):
    """A mutation batch's apply state is unknowable (kvpb's
    AmbiguousResultError): every transport retry failed, and the last
    attempt may or may not have been applied server-side. Deliberately
    NOT a ConnectionError — no layer may silently retry past this; the
    caller must read to disambiguate or surface it to the application."""

    def __init__(self, msg: str, cid: str | None = None,
                 seq: int | None = None):
        super().__init__(msg)
        self.cid = cid
        self.seq = seq


register_passthrough(AmbiguousResultError)

_client_ids = itertools.count(1)


def _b64(b: bytes | None) -> str | None:
    return None if b is None else base64.b64encode(b).decode("ascii")


def _unb64(s: str | None) -> bytes | None:
    return None if s is None else base64.b64decode(s)

_MUTATION_OPS = frozenset(("put", "delete"))


class BatchServer:
    """Serve Batch RPCs against one DB (Node.Batch -> Store.Send role)."""

    def __init__(self, db: DB, host: str = "127.0.0.1", port: int = 0,
                 lease_check=None):
        self.db = db
        # optional leaseholder guard: called with the decoded request
        # before mutation batches evaluate; raises EpochFencedError /
        # NotLeaseHolderError (kv/liveness.py) which travel to the client
        # as typed codes. Node wires this to its LeaseManager.
        self.lease_check = lease_check
        # SO_REUSEADDR so a restart rebinds the port while the previous
        # incarnation's conns sit in TIME_WAIT (create_server sets it on
        # POSIX; made explicit because restart-on-same-port is contract)
        self._srv = socket.create_server((host, port))
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.settimeout(0.2)
        self.addr = self._srv.getsockname()
        self._stop = threading.Event()
        self._conns: set = set()
        self._conns_lock = locks.lock("rpc.server.conns")
        self._threads: list[threading.Thread] = []
        self._accept_thread = threading.Thread(
            target=self._serve, daemon=True, name="kv-batch-server")
        self._accept_thread.start()

    def _serve(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._conns_lock:
                if self._stop.is_set():
                    conn.close()
                    return
                self._conns.add(conn)
                t = threading.Thread(target=self._conn_loop, args=(conn,),
                                     daemon=True)
                self._threads.append(t)
            t.start()

    def _conn_loop(self, conn):
        """Persistent per-connection loop (BatchStream shape): one bad
        request answers with an error frame, never kills the server."""
        from ..flow.dcn import _recv_msg, _send_msg

        try:
            while not self._stop.is_set():
                msg = _recv_msg(conn)
                if msg is None:
                    return
                ssp = None
                try:
                    req = json.loads(msg.decode("utf-8"))
                    # snowball half: the caller's (trace_id, span_id)
                    # rides the envelope; the server-side span's finished
                    # recording ships back on the response for grafting
                    with tracing.remote_span(
                            "kv/server.batch", req.get("trace"),
                            ops=len(req.get("requests", ()))) as ssp:
                        resp = self._eval_batch(req)
                        # post-apply response loss (the ambiguous-result
                        # window): the batch IS applied, the client never
                        # hears back. A `drop` here severs the stream; the
                        # retry must hit the replay cache, not re-apply.
                        from ..utils import faults

                        faults.fire("kv.rpc.server.respond")
                except InjectedFault as e:
                    if e.kind == "drop":
                        raise  # sever the stream, like a crashed replica
                    resp = {"error": str(e), "code": "Internal"}
                except _LEASE_ERRORS as e:
                    resp = {"error": str(e),
                            "code": type(e).__name__,
                            "holder": getattr(e, "holder", None)}
                except WriteIntentError as e:
                    # carry the REAL conflicting keys/txns: clients format
                    # them into user errors and conflict handling keys on
                    # the txn ids
                    resp = {"error": str(e), "code": "WriteIntentError",
                            "keys": [_b64(k) for k in e.keys],
                            "txns": list(e.txns)}
                except Exception as e:  # noqa: BLE001  # crlint: allow-broad-except(server loop converts the error to a wire response for the client)
                    resp = {"error": f"{type(e).__name__}: {e}",
                            "code": "Internal"}
                if ssp is not None:
                    # errored evals ship their recording too — the client
                    # grafts BEFORE raising, so failed batches still show
                    # in the trace
                    resp["trace"] = ssp.to_dict()
                _send_msg(conn, json.dumps(resp).encode("utf-8"))
        except (OSError, ConnectionError):
            pass  # client went away
        finally:
            conn.close()
            with self._conns_lock:
                self._conns.discard(conn)

    def _eval_batch(self, req: dict) -> dict:
        """Evaluate sub-requests in order (batcheval's cmd_* dispatch)."""
        from ..utils import faults

        # replica-side evaluation fault (TestingEvalFilter analog): fires
        # BEFORE any sub-request touches the store, so a dropped batch is
        # all-or-nothing and a retry replays it exactly
        faults.fire("kv.rpc.server.eval")
        reqs = req.get("requests", ())
        if self.lease_check is not None and any(
                r["op"] in _MUTATION_OPS for r in reqs):
            with tracing.leaf_span("kv/lease_check",
                                   range=req.get("range")):
                self.lease_check(req)
        if req.get("cid") is not None and reqs and all(
                r["op"] in _MUTATION_OPS for r in reqs):
            return self._eval_stamped_mutations(req)
        out = []
        for r in req.get("requests", ()):
            op = r["op"]
            if op == "put":
                ts = self.db.put(_unb64(r["key"]), _unb64(r["value"]))
                out.append({"ts": ts})
            elif op == "delete":
                ts = self.db.delete(_unb64(r["key"]))
                out.append({"ts": ts})
            elif op == "get":
                v = self.db.get(_unb64(r["key"]), ts=r.get("ts"))
                out.append({"value": _b64(v)})
            elif op == "scan":
                rows = self.db.scan(
                    _unb64(r.get("start")), _unb64(r.get("end")),
                    ts=r.get("ts"), max_keys=r.get("max_keys"),
                )
                out.append({"rows": [[_b64(k), _b64(v)] for k, v in rows]})
            else:
                raise ValueError(f"unknown batch op {op!r}")
        return {"responses": out}

    def _eval_stamped_mutations(self, req: dict) -> dict:
        """Exactly-once path for (cid, seq)-stamped mutation-only batches.

        Under the engine mutex: a replay-cache hit returns the FIRST
        attempt's response verbatim (the retry crossed a severed-response
        or restart window — applying again would double-write); a miss
        evaluates every mutation, then lands ops + dedup entry + response
        in one atomic WAL record via Engine.apply_rpc_batch. Reads and
        mixed batches take the legacy path above: reads are idempotent,
        so only mutations need replay protection (kvserver's replay
        protection covers writes for the same reason)."""
        from ..utils import metric

        db = self.db
        cid, seq = req["cid"], int(req["seq"])
        with db.engine.mu:
            cached = db.engine.replay_cache_get(cid, seq)
            if cached is not None:
                metric.REPLAY_CACHE_HITS.inc()
                return cached
            muts, out = [], []
            for r in req["requests"]:
                k = _unb64(r["key"])
                db._check_lock(k)  # WriteIntentError surfaces typed
                ts = db.clock.now()
                if r["op"] == "put":
                    muts.append((k, _unb64(r["value"]), ts, 0, False))
                else:
                    muts.append((k, b"", ts, 0, True))
                out.append({"ts": ts})
            resp = {"responses": out}
            db.engine.apply_rpc_batch(cid, seq, muts, resp)
        return resp

    def close(self):
        """Idempotent full teardown: stop accepting, sever every accepted
        conn, and JOIN the accept + per-conn threads (the stopper's
        "start/stop bound every thread" contract) — a closed server holds
        no port, no fd, and no thread, so back-to-back restarts on the
        same port never collide."""
        self._stop.set()
        self._srv.close()
        # closing established conns unblocks per-connection loops parked
        # in recv
        with self._conns_lock:
            conns = list(self._conns)
            self._conns.clear()
            threads = list(self._threads)
            self._threads.clear()
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            c.close()
        if self._accept_thread is not threading.current_thread():
            self._accept_thread.join(timeout=5)
        for t in threads:
            if t is not threading.current_thread():
                t.join(timeout=5)


class BatchClient:
    """Dial a BatchServer; issue batches over one persistent connection.
    Raises WriteIntentError/RuntimeError mirroring the server's typed
    error codes (the DistSender would catch the former and retry).

    Transport discipline (the DistSender's send-retry reduction): every
    RPC runs under a per-call deadline (rpc.batch.deadline_s) and
    TRANSPORT failures — drops, resets, timeouts — re-dial and re-send
    with exponential backoff + jitter (rpc.batch.max_retries attempts).
    Typed SERVER answers (WriteIntentError, Internal) are never retried
    here: the txn layer owns intent waits, and hard errors must surface.

    Exactly-once writes: every mutation-only batch is stamped with this
    client's id and a per-batch sequence number. A retry re-sends the
    SAME stamp, so a failure after server-side evaluation (severed
    response, server restart) dedups against the server's WAL-persisted
    replay cache instead of double-applying. When retries exhaust with
    the apply state still unknown, the client raises a typed
    AmbiguousResultError — never a silent retry, never a silent drop."""

    def __init__(self, addr, deadline_s: float | None = None,
                 max_retries: int | None = None):
        from ..utils import settings

        self.addr = tuple(addr)
        self.deadline_s = (deadline_s if deadline_s is not None
                          else settings.get("rpc.batch.deadline_s"))
        self.max_retries = (max_retries if max_retries is not None
                            else settings.get("rpc.batch.max_retries"))
        # globally unique client id: the replay cache keys dedup entries
        # on it, so two clients must never collide (uuid covers
        # multi-process; the counter disambiguates within-process)
        self.cid = f"{uuid.uuid4().hex[:12]}-{next(_client_ids)}"
        self._seq = itertools.count(1)
        self._sock = self._dial()
        self._lock = locks.lock("rpc.client.pool")

    def _dial(self) -> socket.socket:
        s = socket.create_connection(self.addr, timeout=self.deadline_s)
        s.settimeout(self.deadline_s)
        return s

    def _redial(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
        self._sock = self._dial()

    @staticmethod
    def _transport_error(e: BaseException) -> bool:
        """Retry ONLY wire-level failures; typed server errors surface."""
        return isinstance(e, (ConnectionError, socket.timeout,
                              TimeoutError, OSError))

    def batch(self, requests: list[dict],
              range_id: int | None = None) -> list[dict]:
        from ..utils import faults, metric, retry
        from ..flow.dcn import _recv_msg, _send_msg

        envelope: dict = {"requests": requests}
        if range_id is not None:
            # range-addressed batch: the server's lease guard verifies it
            # still holds this range's epoch lease before mutating
            envelope["range"] = int(range_id)
        # stamp mutation-only batches: the (cid, seq) token is allocated
        # ONCE here, so every transport retry below re-sends the same
        # token and the server can dedup (reads stay unstamped — they
        # are idempotent and must not occupy the one-entry window)
        stamped = bool(requests) and all(
            r["op"] in _MUTATION_OPS for r in requests)
        seq = None
        if stamped:
            seq = next(self._seq)
            envelope["cid"] = self.cid
            envelope["seq"] = seq
        # trace propagation: the current span's (trace_id, span_id) rides
        # the envelope — built ONCE here so every transport retry carries
        # the same parent and the server's recording grafts under it
        tctx = tracing.context()
        if tctx is not None:
            envelope["trace"] = tctx
        payload = json.dumps(envelope).encode("utf-8")

        with tracing.leaf_span(
                "kv/batch", addr=f"{self.addr[0]}:{self.addr[1]}",
                ops=len(requests)) as ksp:
            attempts = 0

            def send_once():
                nonlocal attempts
                attempts += 1
                with self._lock:  # one in-flight batch per connection
                    faults.fire("kv.rpc.client.batch")
                    try:
                        _send_msg(self._sock, payload)
                        msg = _recv_msg(self._sock)
                    except (socket.timeout, TimeoutError) as e:
                        metric.RPC_TIMEOUTS.inc()
                        # a timed-out stream has unknown framing state:
                        # the next attempt MUST start on a fresh
                        # connection
                        self._redial()
                        raise retry.RPCDeadlineError(
                            f"batch rpc deadline ({self.deadline_s}s) "
                            f"exceeded against {self.addr}") from e
                    except (ConnectionError, OSError):
                        self._redial()
                        raise
                if msg is None:
                    self._redial()
                    raise ConnectionError("batch server closed the stream")
                return msg

            try:
                msg = retry.call(
                    send_once,
                    retry.Backoff(
                        max_attempts=self.max_retries,
                        deadline_s=self.deadline_s * self.max_retries),
                    retryable=self._transport_error,
                )
            except Exception as e:
                if ksp is not None:
                    ksp.add_tag("attempts", attempts)
                if stamped and self._transport_error(e):
                    # retries exhausted mid-mutation: the batch may or may
                    # not have applied, and nothing below can find out.
                    # Surface a typed ambiguity instead of letting a
                    # ConnectionError tempt an outer layer into re-sending
                    # under a FRESH seq (which WOULD double-apply).
                    metric.AMBIGUOUS_RESULTS.inc()
                    raise AmbiguousResultError(
                        f"mutation batch (cid={self.cid}, seq={seq}) "
                        f"against {self.addr}: transport failed after "
                        f"{self.max_retries} attempts; apply state "
                        f"unknown", cid=self.cid, seq=seq) from e
                raise
            if ksp is not None:
                ksp.add_tag("attempts", attempts)
            resp = json.loads(msg.decode("utf-8"))
            # graft the server-side recording BEFORE the typed raises so
            # failed evals still land in the caller's trace
            tracing.graft(resp.pop("trace", None))
            if "error" in resp:
                code = resp.get("code")
                if code == "WriteIntentError":
                    raise WriteIntentError(
                        [_unb64(k) for k in resp.get("keys", [])],
                        resp.get("txns", []),
                    )
                if code == "EpochFencedError":
                    raise EpochFencedError(resp["error"])
                if code == "NotLeaseHolderError":
                    raise NotLeaseHolderError(
                        resp["error"], holder=resp.get("holder"))
                raise RuntimeError(f"batch rpc failed: {resp['error']}")
            return resp["responses"]

    # convenience single-op wrappers (the kv.DB surface over RPC)
    def put(self, key: bytes, value: bytes) -> int:
        return self.batch([{"op": "put", "key": _b64(key),
                            "value": _b64(value)}])[0]["ts"]

    def get(self, key: bytes, ts: int | None = None) -> bytes | None:
        r = {"op": "get", "key": _b64(key)}
        if ts is not None:
            r["ts"] = ts
        return _unb64(self.batch([r])[0]["value"])

    def delete(self, key: bytes) -> int:
        return self.batch([{"op": "delete",
                            "key": _b64(key)}])[0]["ts"]

    def scan(self, start: bytes | None, end: bytes | None,
             max_keys: int | None = None) -> list[tuple[bytes, bytes]]:
        r = {"op": "scan", "start": _b64(start), "end": _b64(end)}
        if max_keys is not None:
            r["max_keys"] = max_keys
        return [(base64.b64decode(k), base64.b64decode(v))
                for k, v in self.batch([r])[0]["rows"]]

    def close(self):
        self._sock.close()
