"""DistSender / RangeCache / multi-Store — the kvclient routing reduction.

Reference: the keyspace is split into ranges; range descriptors live in
meta ranges; DistSender (kvcoord/dist_sender.go:663) splits every batch by
range using the RangeDescriptorCache, routes each piece to the range's
leaseholder store, and retries with a fresh descriptor on
RangeKeyMismatchError when its cache was stale. Store.Send
(kvserver/store_send.go:41) verifies the request lies within a range it
owns.

TPU-native reduction, single process, N stores (one Engine each):

- ``Meta``: the authoritative descriptor table (the meta-range role) —
  sorted host list, copy-on-write snapshots so concurrent readers never
  see a half-applied split.
- ``RangeCache``: per-DistSender cached descriptors; binary search by key,
  evicted on RangeKeyMismatchError (stale routing), refilled from Meta.
- ``Store``: an Engine + the set of range ids it owns; every request
  verifies its span against the CURRENT descriptor before touching the
  engine (the bounds check that makes stale caches detectable).
- ``DistSender``: implements the Engine surface DB/Txn and the SQL scan
  path consume (put/get/scan/scan_batch/ingest/resolve_intents/
  checkpoint/_merged_view/...), so ``DB(DistSender(...), clock)`` drops
  in with the txn layer unchanged. Cross-range scans split by range
  boundary and concatenate per-store results in key order. NOT forwarded:
  the admission governor and LSM tuning knobs — those stay per-store
  (consult ``stores[i].engine`` directly).
- admin ops: ``split_at`` (metadata-only, like the reference's AdminSplit
  — both halves stay on the store), ``move_range`` (scan + ingest into
  the target store — the snapshot-rebalance role).

Replication (multiple replicas per range, raft) stays out of scope per
SURVEY §7; each range has exactly one home store.

The SQL columnar fast path (kv/table.py KVTable.device_batch) reads
``_merged_view()`` — here a cross-store merged device view — so SQL
tables work over a split keyspace (see test_sql_over_multi_range_
keyspace).
"""

from __future__ import annotations

import bisect
import threading
from dataclasses import dataclass

import numpy as np

from ..storage.lsm import Engine
from ..utils import locks, log, metric


class RangeKeyMismatchError(Exception):
    """The routed store does not own the request's span (stale cache)."""


@dataclass(frozen=True)
class RangeDescriptor:
    range_id: int
    start_key: bytes  # inclusive
    end_key: bytes | None  # exclusive; None = +inf
    store_id: int
    generation: int = 0

    def contains(self, key: bytes) -> bool:
        return key >= self.start_key and (
            self.end_key is None or key < self.end_key
        )


class Meta:
    """Authoritative descriptor table. Descriptors tile the keyspace:
    [b"", split1), [split1, split2), ... [splitN, None)."""

    def __init__(self, first_store: int = 1):
        self._lock = locks.rlock("kv.rangecache")
        self._next_id = 2
        self._descs: list[RangeDescriptor] = [
            RangeDescriptor(1, b"", None, first_store)
        ]
        self.lookups = 0  # authoritative reads (the meta-range's QPS)

    def snapshot(self) -> list[RangeDescriptor]:
        with self._lock:
            return list(self._descs)

    def lookup(self, key: bytes) -> RangeDescriptor:
        with self._lock:
            self.lookups += 1
            i = self._find(key)
            return self._descs[i]

    def _find(self, key: bytes) -> int:
        starts = [d.start_key for d in self._descs]
        return max(0, bisect.bisect_right(starts, key) - 1)

    def split_at(self, key: bytes) -> tuple[RangeDescriptor, RangeDescriptor]:
        """AdminSplit: [s, e) -> [s, key) + [key, e), both on the same
        store. Metadata-only, like the reference (data does not move)."""
        if not key:
            raise ValueError("cannot split at the minimum key")
        with self._lock:
            i = self._find(key)
            d = self._descs[i]
            if d.start_key == key:
                return d, d  # already a boundary
            left = RangeDescriptor(d.range_id, d.start_key, key, d.store_id,
                                   d.generation + 1)
            right = RangeDescriptor(self._next_id, key, d.end_key,
                                    d.store_id, 0)
            self._next_id += 1
            self._descs = (
                self._descs[:i] + [left, right] + self._descs[i + 1:]
            )
            metric.RANGE_SPLITS.inc()
            log.info(log.OPS, "range split", at=key.decode(errors="replace"),
                     left=left.range_id, right=right.range_id)
            return left, right

    def merge_at(self, key: bytes) -> RangeDescriptor | None:
        """AdminMerge reduction: remove the boundary at `key` — the range
        starting at key is absorbed into its left neighbor, which keeps
        its range id (generation bumped so caches notice the wider
        bounds). Metadata-only, so both sides must already be colocated.
        Idempotent: no descriptor starts at key -> None (a crashed retry
        already merged)."""
        if not key:
            raise ValueError("cannot merge at the minimum key")
        with self._lock:
            starts = [d.start_key for d in self._descs]
            i = bisect.bisect_left(starts, key)
            if i == 0 or i >= len(self._descs) or starts[i] != key:
                return None  # boundary already gone
            left, right = self._descs[i - 1], self._descs[i]
            if left.store_id != right.store_id:
                raise ValueError(
                    f"merge at {key!r}: r{left.range_id}@s{left.store_id} "
                    f"and r{right.range_id}@s{right.store_id} not colocated"
                )
            merged = RangeDescriptor(left.range_id, left.start_key,
                                     right.end_key, left.store_id,
                                     left.generation + 1)
            self._descs = self._descs[:i - 1] + [merged] + self._descs[i + 1:]
            metric.RANGE_MERGES.inc()
            log.info(log.OPS, "range merged",
                     at=key.decode(errors="replace"),
                     keep=merged.range_id, gone=right.range_id)
            return merged

    def reassign(self, range_id: int, to_store: int) -> RangeDescriptor:
        with self._lock:
            for i, d in enumerate(self._descs):
                if d.range_id == range_id:
                    nd = RangeDescriptor(d.range_id, d.start_key, d.end_key,
                                         to_store, d.generation + 1)
                    self._descs = (
                        self._descs[:i] + [nd] + self._descs[i + 1:]
                    )
                    return nd
            raise KeyError(f"no range {range_id}")


class RangeCache:
    """Per-sender descriptor cache (kvclient/rangecache role): lookups hit
    the cache; a RangeKeyMismatch evicts the stale entry and refills from
    Meta. Deliberately NOT invalidated by Meta writes — staleness is
    detected at the store, exactly like the reference.

    Authoritative refills are single-flight (rangecache's
    singleflight.Group over lookup requests): when a split storm evicts a
    hot descriptor, the first miss becomes the lookup leader and every
    concurrent miss for the same key parks on its Event instead of
    stampeding the meta range; followers re-check the cache once the
    leader publishes."""

    def __init__(self, meta: Meta):
        self.meta = meta
        self._mu = locks.lock("kv.singleflight")
        self._by_start: dict[bytes, RangeDescriptor] = {}
        self._inflight: dict[bytes, threading.Event] = {}
        self.misses = 0
        self.evictions = 0
        self.coalesced = 0

    def _cached_locked(self, key: bytes) -> RangeDescriptor | None:
        for d in self._by_start.values():
            if d.contains(key):
                return d
        return None

    def lookup(self, key: bytes) -> RangeDescriptor:
        while True:
            with self._mu:
                d = self._cached_locked(key)
                if d is not None:
                    return d
                ev = self._inflight.get(key)
                if ev is None:
                    ev = self._inflight[key] = threading.Event()
                    leader = True
                else:
                    leader = False
            if not leader:
                self.coalesced += 1
                metric.RANGE_CACHE_COALESCED.inc()
                ev.wait(timeout=5.0)
                continue  # re-check cache; leader failure -> become leader
            try:
                self.misses += 1
                d = self.meta.lookup(key)
                with self._mu:
                    self._by_start[d.start_key] = d
                return d
            finally:
                with self._mu:
                    self._inflight.pop(key, None)
                ev.set()

    def insert(self, d: RangeDescriptor) -> None:
        """Install a descriptor learned out-of-band (a store's
        RangeKeyMismatch repair carries the current one)."""
        with self._mu:
            self._by_start[d.start_key] = d

    def evict(self, d: RangeDescriptor) -> None:
        from ..utils import metric

        with self._mu:
            self.evictions += 1
            metric.RANGE_CACHE_EVICTIONS.inc()
            self._by_start.pop(d.start_key, None)


class Store:
    """One Engine + ownership verification (Store.Send's bounds check)."""

    def __init__(self, store_id: int, meta: Meta, **engine_kw):
        self.store_id = store_id
        self.meta = meta
        self.engine = Engine(**engine_kw)

    def check(self, desc: RangeDescriptor, start: bytes,
              end: bytes | None) -> RangeDescriptor:
        """Verify this store currently owns `desc`'s range and the span
        [start, end) (or point [start]) lies within it. Returns the
        CURRENT descriptor — like the reference's RangeKeyMismatchError
        carrying fresher descriptors, so the sender can repair its cache
        even when a narrowed range still answers the request."""
        cur = self.meta.lookup(start)
        if cur.store_id != self.store_id or cur.range_id != desc.range_id:
            raise RangeKeyMismatchError(
                f"store {self.store_id} does not own r{desc.range_id} "
                f"for key {start!r} (now r{cur.range_id}@s{cur.store_id})"
            )
        hi = end if end is not None else start
        if cur.end_key is not None and hi is not None and (
            hi > cur.end_key or (end is None and start >= cur.end_key)
        ):
            raise RangeKeyMismatchError(
                f"span [{start!r}, {end!r}) exceeds r{cur.range_id} "
                f"bounds [{cur.start_key!r}, {cur.end_key!r})"
            )
        return cur


def _sender_locked(fn):
    """Serialize a DistSender request under the sender mutex — restores the
    whole-keyspace atomicity the single-Engine @_locked surface provides
    (Txn.commit's refresh+resolve section and move_range's export->clear
    window must exclude concurrent writes on EVERY store)."""
    import functools

    @functools.wraps(fn)
    def wrapper(self, *a, **kw):
        with self.mu:
            return fn(self, *a, **kw)
    return wrapper


def _b(x) -> bytes:
    return x.encode() if isinstance(x, str) else bytes(x)


class DistSender:
    """Routes Engine-surface requests by range. Implements everything
    kv.DB/kv.Txn consume from an Engine, so it substitutes transparently.

    Concurrency: one reentrant mutex spanning all stores (`mu`) — the
    same latch reduction Engine.mu provides single-store. Individual
    engines keep their own mutexes for direct access."""

    def __init__(self, stores: list[Store], meta: Meta, lease_check=None,
                 load=None):
        assert stores, "need at least one store"
        self.meta = meta
        self.stores = {s.store_id: s for s in stores}
        self.cache = RangeCache(meta)
        self.mu = locks.rlock("kv.distsender")
        first = stores[0].engine
        self.key_width = first.key_width
        self.val_width = first.val_width
        # lease_check(range_id) raises NotLeaseHolderError/EpochFencedError
        # when this process may not serve the range — the (holder, epoch)
        # guard applied to EVERY routed piece, so range-addressed stamping
        # survives an auto-split mid-batch (ROADMAP open item)
        self.lease_check = lease_check
        # RangeLoadStats sampled on the routing path (split.Decider feed)
        self.load = load

    def _record_read(self, d, key: bytes) -> None:
        # system keyspace (\x01: liveness/lease/tsdb records) never feeds
        # the split decider — bookkeeping traffic must not look hot
        if self.load is not None and not key.startswith(b"\x01"):
            self.load.record_read(d.range_id, key)

    def _record_write(self, d, key: bytes, nbytes: int) -> None:
        if self.load is not None and not key.startswith(b"\x01"):
            self.load.record_write(d.range_id, key, nbytes)

    # -- routing core --------------------------------------------------------

    def _route_point(self, key: bytes):
        """(store, descriptor) for one key, retrying past stale cache.
        The returned descriptor is the store's CURRENT one — a cached
        entry that routed correctly but had stale bounds (a split kept
        this half in place) is repaired in the cache on the way out."""
        for _ in range(4):
            d = self.cache.lookup(key)
            store = self.stores[d.store_id]
            try:
                cur = store.check(d, key, None)
            except RangeKeyMismatchError:
                # retry accounting is per-RANGE, not per-client: one hot
                # range's churn shows up in its own counter
                metric.RPC_RETRIES_BY_RANGE.inc(d.range_id)
                self.cache.evict(d)
                continue
            if cur.generation != d.generation or cur.end_key != d.end_key:
                self.cache.evict(d)
                self.cache.insert(cur)
            if self.lease_check is not None:
                self.lease_check(cur.range_id)
            return store, cur
        # cache kept going stale (concurrent splits): go authoritative
        d = self.meta.lookup(key)
        if self.lease_check is not None:
            self.lease_check(d.range_id)
        return self.stores[d.store_id], d

    def _route_span(self, start: bytes | None, end: bytes | None):
        """Split [start, end) into per-range pieces (DistSender's batch
        truncation, dist_sender.go:1191): yields (store, piece_start,
        piece_end) in key order."""
        cursor = start if start is not None else b""
        while True:
            store, d = self._route_point(cursor)
            self._record_read(d, cursor)
            piece_end = d.end_key
            if end is not None and (piece_end is None or end <= piece_end):
                yield store, cursor, end
                return
            if piece_end is None:
                yield store, cursor, end
                return
            yield store, cursor, piece_end
            cursor = piece_end

    # -- Engine surface ------------------------------------------------------

    @_sender_locked
    def put(self, key, value, ts: int, txn: int = 0):
        k = _b(key)
        store, d = self._route_point(k)
        self._record_write(d, k, len(_b(value)))
        return store.engine.put(k, value, ts=ts, txn=txn)

    @_sender_locked
    def delete(self, key, ts: int, txn: int = 0):
        k = _b(key)
        store, d = self._route_point(k)
        self._record_write(d, k, 0)
        return store.engine.delete(k, ts=ts, txn=txn)

    @_sender_locked
    def get(self, key, ts: int, txn: int = 0):
        k = _b(key)
        store, d = self._route_point(k)
        self._record_read(d, k)
        return store.engine.get(k, ts=ts, txn=txn)

    @_sender_locked
    def apply_rpc_batch(self, cid: str, seq: int, muts, resp,
                        sync: bool = True) -> None:
        """Stamped-batch surface for the cross-session coalescer
        (kv/coalesce.py): truncate the train by range — DistSender's
        batch truncation applied to a mutation batch — and apply one
        range-addressed stamped sub-batch per range, so the atomic
        WAL-record + dedup discipline survives splits (a replay after a
        split dedups against the range that actually applied it).
        ``sync=False`` defers every store's WAL fsync to wal_sync()."""
        by_range: dict[int, list] = {}
        stores: dict[int, Store] = {}
        for m in muts:
            k = m[0]
            store, d = self._route_point(k)
            self._record_write(d, k, len(m[1]))
            by_range.setdefault(d.range_id, []).append(m)
            stores[d.range_id] = store
        for rid, ms in by_range.items():
            sub = {"ts": [m[2] for m in ms]}
            stores[rid].engine.apply_rpc_batch(f"{cid}.r{rid}", seq, ms,
                                               sub, sync=sync)

    def wal_sync(self) -> None:
        """Sync every store's WAL (the coalescer cannot know which ranges
        a train touched once apply returns; syncing an untouched store's
        WAL is a no-op fsync). Unlocked like Engine.wal_sync."""
        for s in self.stores.values():
            s.engine.wal_sync()

    @_sender_locked
    def scan(self, start, end, ts: int, txn: int = 0, max_keys=None):
        out: list[tuple[bytes, bytes]] = []
        s = _b(start) if start is not None else None
        e = _b(end) if end is not None else None
        for store, ps, pe in self._route_span(s, e):
            left = None if max_keys is None else max_keys - len(out)
            if left is not None and left <= 0:
                break
            out.extend(store.engine.scan(ps, pe, ts=ts, txn=txn,
                                         max_keys=left))
        return out

    @_sender_locked
    def scan_batch(self, starts, ts: int, txn: int = 0, max_keys: int = 64):
        """Batched scans grouped BY STORE so each store runs one device
        pass (the Streamer's per-range request grouping,
        kvstreamer/streamer.go:517). Results reassemble in request order;
        a scan whose window crosses its range's end is truncated at the
        boundary and continued on the next range host-side."""
        encs = [_b(s) for s in starts]
        by_store: dict[int, list[int]] = {}
        descs = []
        for i, k in enumerate(encs):
            store, d = self._route_point(k)
            self._record_read(d, k)
            by_store.setdefault(store.store_id, []).append(i)
            descs.append(d)
        results: list[list[tuple[bytes, bytes]]] = [None] * len(encs)
        for sid, idxs in by_store.items():
            eng = self.stores[sid].engine
            got = eng.scan_batch([encs[i] for i in idxs], ts=ts, txn=txn,
                                 max_keys=max_keys)
            for i, rows in zip(idxs, got):
                d = descs[i]
                if d.end_key is not None:
                    rows = [(k, v) for k, v in rows if k < d.end_key]
                results[i] = rows
        # continue truncated scans past their range boundary (self.scan
        # walks ALL remaining ranges, so one continuation suffices)
        for i, rows in enumerate(results):
            d = descs[i]
            if d.end_key is not None and len(rows) < max_keys:
                rows = rows + self.scan(d.end_key, None, ts=ts, txn=txn,
                                        max_keys=max_keys - len(rows))
            results[i] = rows[:max_keys]
        return results

    @_sender_locked
    def ingest(self, keys: np.ndarray, values: np.ndarray, ts: int,
               vlens=None, seq=None) -> None:
        """Bulk ingest split by range boundary (AddSSTable routing). One
        meta snapshot + one vectorized searchsorted routes the whole batch
        — never a per-key routing round trip. Per-row vlens split with
        the same selection; an explicit seq only makes sense against one
        store's sequence space and is rejected on a split keyspace."""
        n = len(keys)
        if n == 0:
            return
        descs = self.meta.snapshot()  # sorted by start_key, tiles keyspace
        ka = np.asarray(keys)
        if len(descs) == 1:
            if self.lease_check is not None:
                self.lease_check(descs[0].range_id)
            self.stores[descs[0].store_id].engine.ingest(
                ka, np.asarray(values), ts, vlens=vlens, seq=seq)
            return
        if seq is not None:
            raise ValueError(
                "explicit ingest seq is per-store; unsupported on a "
                "split keyspace"
            )
        width = ka.shape[1]
        starts = np.zeros((len(descs), width), np.uint8)
        for i, d in enumerate(descs):
            s = d.start_key[:width]
            starts[i, :len(s)] = np.frombuffer(s, np.uint8)
        kv = np.ascontiguousarray(ka).view(f"V{width}").reshape(-1)
        sv = np.ascontiguousarray(starts).view(f"V{width}").reshape(-1)
        piece_of = np.searchsorted(sv, kv, side="right") - 1
        va = np.asarray(values)
        vl = None if vlens is None else np.asarray(vlens)
        for di in np.unique(piece_of):
            sel = piece_of == di
            if self.lease_check is not None:
                self.lease_check(descs[int(di)].range_id)
            self.stores[descs[int(di)].store_id].engine.ingest(
                ka[sel], va[sel], ts,
                vlens=None if vl is None else vl[sel],
            )

    # engine-wide ops forward to every store
    @_sender_locked
    def resolve_intents(self, txn: int, commit_ts: int, commit: bool):
        for s in self.stores.values():
            s.engine.resolve_intents(txn, commit_ts, commit)

    @_sender_locked
    def has_committed_writes_in(self, start, end, ts_lo, ts_hi,
                                point: bool = False) -> bool:
        if point:
            store, _ = self._route_point(_b(start) if start else b"")
            return store.engine.has_committed_writes_in(
                start, end, ts_lo, ts_hi, point=True)
        # span refresh — open-ended spans (end=None) walk EVERY range the
        # span covers; routing them as a point would skip all other stores
        # and let an invalidated read commit
        for store, ps, pe in self._route_span(
            _b(start) if start is not None else None,
            _b(end) if end is not None else None,
        ):
            if store.engine.has_committed_writes_in(ps, pe, ts_lo, ts_hi):
                return True
        return False

    @_sender_locked
    def other_intent(self, key: bytes, txn: int):
        store, _ = self._route_point(_b(key))
        return store.engine.other_intent(key, txn)

    @_sender_locked
    def newest_committed_ts(self, key: bytes) -> int:
        store, _ = self._route_point(_b(key))
        return store.engine.newest_committed_ts(key)

    @_sender_locked
    def intent_keys(self, txn: int) -> list[bytes]:
        out: list[bytes] = []
        for s in self.stores.values():
            out.extend(s.engine.intent_keys(txn))
        return sorted(out)

    # -- columnar read surface (SQL fast path) -------------------------------

    @property
    def _seq(self):
        """Hashable write-sequence fingerprint across stores — KVTable's
        per-engine caches key on (engine._seq, engine._gen)."""
        return tuple(s.engine._seq for s in self.stores.values())

    @property
    def _gen(self):
        return tuple(s.engine._gen for s in self.stores.values())

    @_sender_locked
    def _merged_view(self):
        """One sorted device view over EVERY store — the cross-range
        columnar scan (KVTable.device_batch reads this exactly like a
        single engine's merged view). Cached per store-generation vector;
        stores' own caches make the per-store halves incremental."""
        from ..storage import mvcc
        from ..storage.lsm import _pad

        key = (self._seq, self._gen)
        cached = getattr(self, "_view_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        views = []
        for s in self.stores.values():
            with s.engine.mu:
                v = s.engine._merged_view()  # overlays memtable, cached
            if v is not None:
                views.append(v)
        if not views:
            view = None
        elif len(views) == 1:
            view = views[0]
        else:
            total = sum(v.capacity for v in views)
            view = mvcc.merge_blocks(tuple(views), cap=_pad(total))
        self._view_cache = (key, view)
        return view

    @_sender_locked
    def flush(self):
        for s in self.stores.values():
            s.engine.flush()

    @_sender_locked
    def compact(self, bottom: bool = True):
        for s in self.stores.values():
            s.engine.compact(bottom=bottom)

    @_sender_locked
    def checkpoint(self, path: str):
        """Checkpoint every store into a per-store subdirectory (the jobs
        framework's backup resumer calls db.engine.checkpoint)."""
        import os

        for sid, s in self.stores.items():
            s.engine.checkpoint(os.path.join(path, f"store{sid}"))

    # -- admin ---------------------------------------------------------------

    def split_at(self, key) -> None:
        self.meta.split_at(_b(key))

    def move_range(self, range_id: int, to_store: int) -> int:
        """Relocate a range's data: scan every version in-span from the
        old store, ingest into the new one, clear the old span, then flip
        the descriptor. The snapshot-rebalance reduction (the reference
        streams a raft snapshot then deletes the old replica). Runs under
        the sender mutex: a metadata flip mid-copy would lose writes."""
        with self.mu:
            src_desc = None
            for d in self.meta.snapshot():
                if d.range_id == range_id:
                    src_desc = d
                    break
            if src_desc is None:
                raise KeyError(f"no range {range_id}")
            if src_desc.store_id == to_store:
                return 0
            src = self.stores[src_desc.store_id].engine
            dst = self.stores[to_store].engine
            moved = src.export_span(src_desc.start_key, src_desc.end_key)
            dst.import_rows(moved)
            src.clear_span(src_desc.start_key, src_desc.end_key)
            self.meta.reassign(range_id, to_store)
            metric.RANGE_MOVES.inc()
            n = len(moved["ts"]) if moved else 0
            log.info(log.OPS, "range moved", range=range_id,
                     to_store=to_store, rows=n)
            return n


class LeaseRouter:
    """Leaseholder-aware RPC routing (the networked half of DistSender's
    per-range transport, dist_sender.go's sendToReplicas + the
    NotLeaseHolderError redirect loop).

    Resolves a range's current leaseholder from gossip (`lease/<rid>`
    infos the lease loop publishes), dials it through the NodeDialer,
    and sends the batch range-addressed so the server's lease guard
    fences stale holders. Reroute triggers — EpochFencedError /
    NotLeaseHolderError (failover finished; re-resolve), transport
    errors on read batches (reads are idempotent), breaker fast-fails —
    spend the per-RANGE retry budget; when it runs dry the caller gets
    RetryBudgetExhausted and must degrade, exactly the PR-1 flow
    discipline. AmbiguousResultError propagates untouched: re-sending a
    mutation under a fresh stamp is the double-apply this PR exists to
    prevent."""

    def __init__(self, gossip, dialer, budget=None,
                 resolve_timeout_s: float = 5.0):
        from ..utils import retry

        self.gossip = gossip
        self.dialer = dialer
        self.budget = budget if budget is not None \
            else retry.RangeRetryBudget()
        self.resolve_timeout_s = resolve_timeout_s

    def leaseholder(self, range_id: int) -> int | None:
        """Gossip's view of the range's holder node id (None = unknown)."""
        v = self.gossip.get_info(f"lease/{range_id}")
        if v is None:
            return None
        nid, _, _epoch = str(v).partition(":")
        try:
            return int(nid)
        except ValueError:
            return None

    def batch(self, range_id: int, requests: list[dict]) -> list[dict]:
        import time as _time

        from ..kv.liveness import EpochFencedError, NotLeaseHolderError
        from ..kv.rpc import AmbiguousResultError
        from .dialer import BreakerOpenError

        deadline = _time.monotonic() + self.resolve_timeout_s
        hint: int | None = None
        last: Exception = KeyError(
            f"no leaseholder known for r{range_id}")
        while True:
            nid = hint if hint is not None else self.leaseholder(range_id)
            hint = None
            if nid is not None:
                try:
                    client = self.dialer.dial(nid)
                    out = client.batch(requests, range_id=range_id)
                    self.dialer.report_ok(nid)
                    return out
                except AmbiguousResultError:
                    raise  # typed ambiguity: never silently re-sent
                except NotLeaseHolderError as e:
                    last = e
                    hint = e.holder  # redirect straight to the holder
                except EpochFencedError as e:
                    last = e  # stale route: wait out the failover
                except BreakerOpenError as e:
                    last = e
                except (ConnectionError, OSError) as e:
                    self.dialer.report_failure(nid)
                    last = e
            # a reroute costs one per-range retry token
            # (RetryBudgetExhausted propagates: budget dry = degrade)
            self.budget.spend(range_id)
            if _time.monotonic() > deadline:
                raise last
            _time.sleep(0.05)  # let gossip/failover converge
