"""Range lifecycle allocator — StorePool + split/merge/rebalance queues.

Reference: pkg/kv/kvserver keeps ranges healthy with background queues —
splitQueue (load/size splits via split.Decider), mergeQueue (cold adjacent
ranges), and the storeRebalancer moving leases/replicas off overloaded
stores using a gossip-fed StorePool (allocator/storepool/store_pool.go)
with mean-based overfull/underfull thresholds.

Reduction here: `RangeLifecycle` owns three `ReplicaQueue`s and a scanner
that walks the meta descriptor table each tick, consulting

- `RangeLoadStats` (kv/loadstats.py) sampled on the DistSender routing
  path for decayed per-range QPS + a split-key reservoir, and
- `Engine.span_stats` for authoritative logical size,

then enqueues decisions. Applications go through the EXISTING admin
machinery — `Meta.split_at` / `Meta.merge_at` / `DistSender.move_range` /
`LeaseManager.carry`/`transfer` — so RangeCache staleness detection and
LeaseRouter rerouting keep working unchanged. Every apply step is
idempotent across the `ranger.*` fault sites: a crash between the meta
write and the bookkeeping retries from purgatory and converges.

Everything is drivable synchronously (`scan_once` + queue `drain`) for
deterministic tests; `start`/`stop` add the paced background loops that
`Node.close()` joins.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from ..storage.lsm import WriteIntentError
from ..utils import faults, locks, log, metric, settings
from .loadstats import RangeLoadStats
from .queues import ReplicaQueue
from .txn import TransactionRetryError


@dataclass
class StoreCapacity:
    """One store's gossiped capacity advertisement (StoreDescriptor's
    Capacity reduced to what the thresholds read)."""

    store_id: int
    node_id: int
    ranges: int
    qps: float
    logical_bytes: int

    def to_info(self) -> dict:
        return {"storeId": self.store_id, "nodeId": self.node_id,
                "ranges": self.ranges, "qps": self.qps,
                "logicalBytes": self.logical_bytes}

    @classmethod
    def from_info(cls, v: dict) -> "StoreCapacity":
        return cls(int(v["storeId"]), int(v["nodeId"]), int(v["ranges"]),
                   float(v["qps"]), int(v["logicalBytes"]))


class StorePool:
    """Cluster-wide store capacity view (storepool reduction): local
    advertisements publish into gossip as ``capacity/<sid>`` infos;
    `refresh` folds in what peers gossiped. Thresholds are mean-based,
    exactly the reference's overfull/underfull discipline."""

    OVERFULL = 1.15   # qps > mean * OVERFULL  -> shed load
    UNDERFULL = 0.85  # qps < mean * UNDERFULL -> take load

    def __init__(self, gossip=None):
        self.gossip = gossip
        self._mu = locks.lock("kv.allocator")
        self._caps: dict[int, StoreCapacity] = {}

    def note(self, cap: StoreCapacity) -> None:
        with self._mu:
            self._caps[cap.store_id] = cap

    def advertise(self, cap: StoreCapacity) -> None:
        self.note(cap)
        if self.gossip is not None:
            self.gossip.add_info(f"capacity/{cap.store_id}", cap.to_info())

    def refresh(self) -> None:
        if self.gossip is None:
            return
        for k in list(self.gossip.keys()):
            if not k.startswith("capacity/"):
                continue
            v = self.gossip.get_info(k)
            if isinstance(v, dict):
                try:
                    self.note(StoreCapacity.from_info(v))
                except (KeyError, TypeError, ValueError):
                    continue

    def capacities(self) -> list[StoreCapacity]:
        with self._mu:
            return sorted(self._caps.values(), key=lambda c: c.store_id)

    def get(self, store_id: int) -> StoreCapacity | None:
        with self._mu:
            return self._caps.get(store_id)

    def mean_qps(self) -> float:
        caps = self.capacities()
        return sum(c.qps for c in caps) / len(caps) if caps else 0.0

    def overfull(self) -> list[StoreCapacity]:
        mean = self.mean_qps()
        return [c for c in self.capacities() if c.qps > mean * self.OVERFULL]

    def least_loaded(self, exclude_store: int | None = None
                     ) -> StoreCapacity | None:
        cands = [c for c in self.capacities()
                 if c.store_id != exclude_store]
        return min(cands, key=lambda c: c.qps) if cands else None


# failures that mean "the world will get better": transport-ish errors
# (InjectedFault subclasses ConnectionError), a txn that lost a race, or
# an intent in the way — these park in purgatory and retry with backoff
_PURGATORY = (ConnectionError, OSError, TimeoutError,
              WriteIntentError, TransactionRetryError)


class RangeLifecycle:
    """The queues + scanner, wired over a DistSender.

    `leases` (a LeaseManager) and `gossip` are optional: without them the
    lifecycle still splits/merges/moves ranges (store-level rebalance);
    with them, splits carry the parent's (holder, epoch) to the child and
    rebalance transfers the lease to the target's node. `store_nodes`
    maps store_id -> node_id for transfer targets (in-process clusters
    pin each store to the node that serves it)."""

    def __init__(self, sender, load: RangeLoadStats | None = None,
                 leases=None, gossip=None, node_id: int = 0,
                 store_nodes: dict[int, int] | None = None,
                 interval_s: float = 1.0,
                 registry: metric.Registry = metric.DEFAULT,
                 clock=time.monotonic):
        self.sender = sender
        self.meta = sender.meta
        if load is None:
            load = getattr(sender, "load", None) or RangeLoadStats()
        self.load = load
        if getattr(sender, "load", None) is None:
            sender.load = load  # start sampling the routing path
        self.leases = leases
        self.node_id = node_id
        self.store_nodes = dict(store_nodes or {})
        self.pool = StorePool(gossip)
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._scanner: threading.Thread | None = None
        self.split_queue = ReplicaQueue(
            "split", self._apply_split, interval_s,
            purgatory_errors=_PURGATORY, registry=registry, clock=clock)
        self.merge_queue = ReplicaQueue(
            "merge", self._apply_merge, interval_s,
            purgatory_errors=_PURGATORY, registry=registry, clock=clock)
        self.rebalance_queue = ReplicaQueue(
            "rebalance", self._apply_transfer, interval_s,
            purgatory_errors=_PURGATORY, registry=registry, clock=clock)

    # -- decisions (the scanner) --------------------------------------------

    def _desc(self, range_id: int):
        for d in self.meta.snapshot():
            if d.range_id == range_id:
                return d
        return None

    def _span_bytes(self, d) -> int:
        eng = self.sender.stores[d.store_id].engine
        return int(eng.span_stats(d.start_key, d.end_key)["logical_bytes"])

    def scan_once(self) -> None:
        """One decision pass over every range: enqueue splits for hot or
        oversized ranges, merges for cold adjacent pairs, and a rebalance
        for the hottest range of an overfull store. Pure decision — all
        mutation happens in queue processing."""
        descs = self.meta.snapshot()
        split_qps = settings.get("kv.range.split_qps_threshold")
        max_bytes = settings.get("kv.range.max_bytes")
        sizes = {d.range_id: self._span_bytes(d) for d in descs}
        # read each range's decayed rate ONCE and reuse it for every
        # decision below — per-decision re-reads decay in between, and
        # the epsilon lets a single-range store slip past the
        # improvement guard (hot_qps < its own advertised sum)
        qps_by_range = {d.range_id: self.load.qps(d.range_id)
                        for d in descs}
        for d in descs:
            qps = qps_by_range[d.range_id]
            ratio = max(qps / split_qps, sizes[d.range_id] / max_bytes)
            if ratio >= 1.0:
                self.split_queue.maybe_add(d.range_id, ratio)
        if settings.get("kv.range.merge_enabled"):
            # a pair is merge-worthy when BOTH the combined load and the
            # combined size sit far below the split thresholds (hysteresis
            # so a merge never immediately re-splits)
            for left, right in zip(descs, descs[1:]):
                qps = (qps_by_range[left.range_id]
                       + qps_by_range[right.range_id])
                size = sizes[left.range_id] + sizes[right.range_id]
                if qps < 0.25 * split_qps and size < max_bytes // 2:
                    self.merge_queue.maybe_add(right.start_key, 1.0)
        self._advertise(descs, sizes, qps_by_range)
        caps = self.pool.capacities()
        mean = self.pool.mean_qps()
        if len(caps) >= 2 and mean > 0:
            for oc in self.pool.overfull():
                target = self.pool.least_loaded(exclude_store=oc.store_id)
                if target is None or target.qps >= mean * self.pool.UNDERFULL:
                    continue
                hot = max(
                    (d for d in descs if d.store_id == oc.store_id),
                    key=lambda d: qps_by_range[d.range_id], default=None)
                if hot is None:
                    continue
                hot_qps = qps_by_range[hot.range_id]
                # the move must IMPROVE balance: shipping the range can't
                # leave the target hotter than the source was, or a
                # store's only range ping-pongs between stores forever
                if hot_qps > 0 and target.qps + hot_qps < oc.qps:
                    self.rebalance_queue.maybe_add(hot.range_id, hot_qps)

    def _advertise(self, descs, sizes, qps_by_range) -> None:
        # every LOCAL store advertises, including empty ones — a store
        # with no ranges is exactly the underfull rebalance target
        per: dict[int, list] = {sid: [0, 0.0, 0]
                                for sid in self.sender.stores}
        for d in descs:
            c = per.setdefault(d.store_id, [0, 0.0, 0])
            c[0] += 1
            c[1] += qps_by_range.get(d.range_id, 0.0)
            c[2] += sizes.get(d.range_id, 0)
        for sid, (ranges, qps, size) in per.items():
            self.pool.advertise(StoreCapacity(
                sid, self.store_nodes.get(sid, self.node_id),
                ranges, qps, size))
        self.pool.refresh()  # fold in peers' advertisements

    # -- applications (queue processors) ------------------------------------

    def _apply_split(self, range_id: int) -> None:
        d = self._desc(range_id)
        if d is None:
            return  # merged away since the decision
        # torn-split recovery: a crashed prior attempt got the meta write
        # in (our descriptor already shrank) but never ran the lease
        # carry / load handoff — visible as samples stranded beyond our
        # end_key. Finish THAT split's bookkeeping; recomputing a fresh
        # split key against the shrunk bounds would cut a second,
        # different boundary instead of converging.
        if (d.end_key is not None
                and self.load.stranded_beyond(range_id, d.end_key)):
            right = next((x for x in self.meta.snapshot()
                          if x.start_key == d.end_key), None)
            if right is not None:
                self._finish_split(d, right, d.end_key, range_id)
                return
        key = self.load.split_key(range_id, d.start_key, d.end_key)
        if key is None:
            return  # samples can't name an interior point (single hot key)
        left, right = self.meta.split_at(key)
        if left.range_id == right.range_id:
            # boundary already present (e.g. a concurrent admin split at
            # the same key): recover both sides, redo the bookkeeping
            right = left
            left = next((x for x in self.meta.snapshot()
                         if x.end_key == key), None)
            if left is None:
                return
        # crash window the chaos suite targets: meta is split, but the
        # lease carry / cache repair / load handoff below hasn't happened
        faults.fire("ranger.split.apply")
        self._finish_split(left, right, key, range_id)

    def _finish_split(self, left, right, key: bytes, range_id: int) -> None:
        if self.leases is not None:
            self.leases.carry(left.range_id, right.range_id)
        self.load.note_split(left.range_id, right.range_id, key)
        self.sender.cache.insert(left)
        self.sender.cache.insert(right)
        metric.KV_RANGE_SPLITS.inc()
        log.info(log.OPS, "load/size split applied",
                 range=range_id, at=key.decode(errors="replace"),
                 child=right.range_id)

    def _apply_merge(self, boundary: bytes) -> None:
        descs = self.meta.snapshot()
        right = next((d for d in descs if d.start_key == boundary), None)
        if right is None:
            # boundary already gone (crashed retry or concurrent merge):
            # repair the cache with the current owner and converge
            self.sender.cache.insert(self.meta.lookup(boundary))
            return
        i = descs.index(right)
        if i == 0:
            return
        left = descs[i - 1]
        # re-validate at apply time — load may have returned since the scan
        split_qps = settings.get("kv.range.split_qps_threshold")
        if not settings.get("kv.range.merge_enabled"):
            return
        if (self.load.qps(left.range_id)
                + self.load.qps(right.range_id)) >= 0.25 * split_qps:
            return
        if left.store_id != right.store_id:
            # metadata-only merge needs colocation; move the cold right
            # side over first (idempotent: re-moving is a no-op)
            self.sender.move_range(right.range_id, left.store_id)
        merged = self.meta.merge_at(boundary)
        if merged is None:
            return
        faults.fire("ranger.merge.apply")
        self.load.note_merge(merged.range_id, right.range_id)
        if self.leases is not None:
            self.leases.release(right.range_id)
        self.sender.cache.evict(right)
        self.sender.cache.insert(merged)
        metric.KV_RANGE_MERGES.inc()

    def _apply_transfer(self, range_id: int) -> None:
        d = self._desc(range_id)
        if d is None:
            return
        # crashed-retry convergence: the data move landed but the lease
        # write was lost. The range's home store names the intended
        # holder, so finish the handoff before any fresh balance
        # decision (a completed transfer makes this a no-op).
        dest_node = self.store_nodes.get(d.store_id, 0)
        if self.leases is not None and dest_node:
            cur = self.leases.holder(range_id)
            if cur is not None and cur.node_id != dest_node:
                self.leases.transfer(range_id, dest_node)
                metric.KV_LEASE_TRANSFERS.inc()
                log.info(log.OPS, "lease transfer completed on retry",
                         range=range_id, to_node=dest_node)
                return
        # re-advertise from CURRENT state before re-checking the balance:
        # the scan-time capacities are stale once any earlier drained item
        # moved a range, and refresh() alone can't see local moves
        descs = self.meta.snapshot()
        sizes = {x.range_id: self._span_bytes(x) for x in descs}
        qps_by_range = {x.range_id: self.load.qps(x.range_id)
                        for x in descs}
        self._advertise(descs, sizes, qps_by_range)
        src = self.pool.get(d.store_id)
        target = self.pool.least_loaded(exclude_store=d.store_id)
        r_qps = qps_by_range.get(range_id, 0.0)
        if target is None or (
                src is not None and target.qps + r_qps >= src.qps):
            return  # imbalance resolved itself since the decision
        self.sender.move_range(range_id, target.store_id)
        # the in-flight window the chaos suite targets: data moved, lease
        # transfer write lost — retry re-enters with a no-op move
        faults.fire("ranger.lease.transfer")
        if self.leases is not None and target.node_id:
            self.leases.transfer(range_id, target.node_id)
        metric.KV_LEASE_TRANSFERS.inc()
        log.info(log.OPS, "lease rebalanced", range=range_id,
                 to_store=target.store_id, to_node=target.node_id)

    # -- driving ------------------------------------------------------------

    def tick(self, force_purgatory: bool = False) -> int:
        """Synchronous scan + drain of every queue (deterministic tests
        and the CLI's one-shot mode). Returns items attempted."""
        self.scan_once()
        n = self.split_queue.drain(force_purgatory)
        n += self.merge_queue.drain(force_purgatory)
        n += self.rebalance_queue.drain(force_purgatory)
        return n

    def hot_ranges(self) -> dict:
        """The /hot_ranges payload: every range with its decayed load,
        authoritative size, home store, and leaseholder node."""
        rows = []
        for d in self.meta.snapshot():
            rec = self.leases.holder(d.range_id) if self.leases else None
            rows.append({
                "rangeId": d.range_id,
                "startKey": d.start_key.decode(errors="replace"),
                "endKey": (d.end_key.decode(errors="replace")
                           if d.end_key is not None else None),
                "storeId": d.store_id,
                "qps": round(self.load.qps(d.range_id), 3),
                "writeBytesRate": round(
                    self.load.write_bytes_rate(d.range_id), 3),
                "sizeBytes": self._span_bytes(d),
                "leaseholder": rec.node_id if rec is not None else None,
            })
        rows.sort(key=lambda r: -r["qps"])
        return {"hotRanges": rows}

    def _scan_loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.scan_once()
            except Exception as e:  # a scan must never kill the loop  # crlint: allow-broad-except(background scan loop must survive; logged)
                log.warning(log.OPS, "range lifecycle scan failed",
                            error=str(e))

    def start(self) -> None:
        if not settings.get("kv.allocator.enabled"):
            return
        for q in (self.split_queue, self.merge_queue, self.rebalance_queue):
            q.start()
        if self._scanner is None:
            self._stop.clear()
            self._scanner = threading.Thread(
                target=self._scan_loop, name="range-lifecycle-scan",
                daemon=True)
            self._scanner.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._scanner = self._scanner, None
        if t is not None and t is not threading.current_thread():
            t.join(timeout=5.0)
        for q in (self.split_queue, self.merge_queue, self.rebalance_queue):
            q.stop()
