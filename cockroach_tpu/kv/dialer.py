"""Node dialer — the rpc/nodedialer reduction.

Reference: nodedialer resolves a NodeID to an address (via gossip's
node-descriptor entries) and hands out cached gRPC connections; callers
never manage addresses themselves (pkg/rpc/nodedialer).

Reduction: nodes advertise their KV Batch RPC address into gossip under
``node/<id>/kv`` (Node.start does this when both gossip and the kv
endpoint are up); ``NodeDialer.dial(node_id)`` resolves through the
LOCAL infostore and returns a cached BatchClient, re-dialing after a
connection failure or an address change (a restarted node re-advertises
a new port)."""

from __future__ import annotations

import time

from ..utils import locks, racesan
from .rpc import BatchClient

_KEY = "node/%d/kv"


class BreakerOpenError(Exception):
    """Fast-fail: the peer's circuit breaker is open (recent failures);
    callers route around it instead of timing out on every attempt."""


class _Breaker:
    """Per-peer circuit breaker (rpc/peer.go + dist_sender_circuit_
    breaker.go reduction): `trip_threshold` consecutive reported RPC
    failures open the breaker for `cooldown_s`; after the cooldown
    exactly ONE caller is admitted as the half-open probe. ONLY
    report_ok()/report_failure() move the failure state — a successful
    TCP connect proves nothing (a wedged peer can accept connections and
    fail every RPC), so dialing never closes the breaker by itself.
    Durations use the monotonic clock (wall steps must not extend or
    collapse cooldowns)."""

    def __init__(self, trip_threshold: int = 3, cooldown_s: float = 5.0):
        self.trip_threshold = trip_threshold
        self.cooldown_s = cooldown_s
        self.failures = 0
        self.opened_at: float | None = None
        self.probing = False
        self.probe_started = 0.0

    def admit(self) -> None:
        if self.opened_at is None:
            return
        since = time.monotonic() - self.opened_at
        if since < self.cooldown_s:
            raise BreakerOpenError(
                f"breaker open ({self.failures} failures, retry in "
                f"{self.cooldown_s - since:.1f}s)"
            )
        if self.probing:
            # a probe whose caller never reported back must not wedge the
            # breaker forever: after 2x cooldown the slot re-opens
            if time.monotonic() - self.probe_started < 2 * self.cooldown_s:
                raise BreakerOpenError("breaker half-open: probe in flight")
        self.probing = True  # this caller IS the probe
        self.probe_started = time.monotonic()

    def probe_aborted(self) -> None:
        """The admitted probe's dial itself failed: free the half-open
        slot (the caller reports the failure separately)."""
        self.probing = False

    def ok(self) -> None:
        self.failures = 0
        self.opened_at = None
        self.probing = False

    def fail(self) -> None:
        from ..utils import metric

        self.failures += 1
        self.probing = False
        if self.failures >= self.trip_threshold:
            if self.opened_at is None:
                metric.BREAKER_TRIPS.inc()
            self.opened_at = time.monotonic()


def advertise(gossip, node_id: int, addr) -> None:
    """Publish this node's KV endpoint (host, port) into gossip."""
    gossip.add_info(_KEY % node_id, list(addr))


class NodeDialer:
    def __init__(self, gossip, trip_threshold: int | None = None,
                 cooldown_s: float | None = None):
        from ..utils import settings

        if trip_threshold is None:
            trip_threshold = settings.get("rpc.breaker.trip_threshold")
        if cooldown_s is None:
            cooldown_s = settings.get("rpc.breaker.cooldown_s")
        self.gossip = gossip
        self._conns: dict[int, tuple[tuple, BatchClient]] = {}
        self._breakers: dict[int, _Breaker] = {}
        self._trip = trip_threshold
        self._cooldown = cooldown_s
        self._lock = locks.lock("kv.dialer")

    def resolve(self, node_id: int) -> tuple:
        addr = self.gossip.get_info(_KEY % node_id)
        if addr is None:
            raise KeyError(f"no gossiped address for node {node_id}")
        return tuple(addr)

    def _breaker(self, node_id: int) -> _Breaker:
        b = self._breakers.get(node_id)
        if b is None:
            b = self._breakers[node_id] = _Breaker(self._trip,
                                                  self._cooldown)
        return b

    def dial(self, node_id: int) -> BatchClient:
        """Cached connection to node_id; re-dials when the advertised
        address changed (node restart) or the cached conn is gone. An
        OPEN breaker fast-fails with BreakerOpenError; after the cooldown
        one caller gets through as the half-open probe. Callers report
        RPC outcomes via report_ok/report_failure — dialing alone never
        changes breaker state (a gossip-resolution miss says nothing
        about peer health, and a wedged peer can accept connects).

        The blocking TCP connect runs OUTSIDE the dialer lock: one
        black-holed peer must not stall dials or fast-fails to others."""
        # resolution BEFORE breaker admission: an unknown address is not
        # a peer failure and must not consume the half-open probe slot
        addr = self.resolve(node_id)
        with self._lock:
            self._breaker(node_id).admit()
            racesan.note_read(self, "_conns")
            cached = self._conns.get(node_id)
            if cached is not None and cached[0] == addr:
                self._breaker(node_id).probe_aborted()  # no probe needed
                return cached[1]
        try:
            from ..utils import faults

            faults.fire("kv.dialer.dial")
            client = BatchClient(addr)
        except Exception:
            with self._lock:
                self._breaker(node_id).probe_aborted()
            raise
        with self._lock:
            cached = self._conns.get(node_id)
            if cached is not None and cached[0] == addr:
                # another dial won the publish race
                try:
                    client.close()
                except OSError:
                    pass
                return cached[1]
            if cached is not None:
                try:
                    cached[1].close()
                except OSError:
                    pass
            racesan.note_write(self, "_conns")
            self._conns[node_id] = (addr, client)
            return client

    def report_ok(self, node_id: int) -> None:
        """Callers report a successful RPC: closes/resets the breaker."""
        with self._lock:
            self._breaker(node_id).ok()

    def report_failure(self, node_id: int) -> None:
        """Callers report an RPC failure: counts toward the trip
        threshold and drops the cached conn so the next dial reconnects."""
        with self._lock:
            self._breaker(node_id).fail()
        self.forget(node_id)

    def breaker_open(self, node_id: int) -> bool:
        with self._lock:
            b = self._breakers.get(node_id)
            return bool(b and b.opened_at is not None
                        and time.monotonic() - b.opened_at < b.cooldown_s)

    def forget(self, node_id: int) -> None:
        """Drop a cached conn (callers do this on a connection error so
        the next dial reconnects)."""
        with self._lock:
            racesan.note_write(self, "_conns")
            cached = self._conns.pop(node_id, None)
        if cached is not None:
            try:
                cached[1].close()
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            racesan.note_write(self, "_conns")
            conns = list(self._conns.values())
            self._conns.clear()
        for _, c in conns:
            try:
                c.close()
            except OSError:
                pass
