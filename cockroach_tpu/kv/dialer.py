"""Node dialer — the rpc/nodedialer reduction.

Reference: nodedialer resolves a NodeID to an address (via gossip's
node-descriptor entries) and hands out cached gRPC connections; callers
never manage addresses themselves (pkg/rpc/nodedialer).

Reduction: nodes advertise their KV Batch RPC address into gossip under
``node/<id>/kv`` (Node.start does this when both gossip and the kv
endpoint are up); ``NodeDialer.dial(node_id)`` resolves through the
LOCAL infostore and returns a cached BatchClient, re-dialing after a
connection failure or an address change (a restarted node re-advertises
a new port)."""

from __future__ import annotations

import threading

from .rpc import BatchClient

_KEY = "node/%d/kv"


def advertise(gossip, node_id: int, addr) -> None:
    """Publish this node's KV endpoint (host, port) into gossip."""
    gossip.add_info(_KEY % node_id, list(addr))


class NodeDialer:
    def __init__(self, gossip):
        self.gossip = gossip
        self._conns: dict[int, tuple[tuple, BatchClient]] = {}
        self._lock = threading.Lock()

    def resolve(self, node_id: int) -> tuple:
        addr = self.gossip.get_info(_KEY % node_id)
        if addr is None:
            raise KeyError(f"no gossiped address for node {node_id}")
        return tuple(addr)

    def dial(self, node_id: int) -> BatchClient:
        """Cached connection to node_id; re-dials when the advertised
        address changed (node restart) or the cached conn is gone."""
        addr = self.resolve(node_id)
        with self._lock:
            cached = self._conns.get(node_id)
            if cached is not None and cached[0] == addr:
                return cached[1]
            if cached is not None:
                try:
                    cached[1].close()
                except OSError:
                    pass
            client = BatchClient(addr)
            self._conns[node_id] = (addr, client)
            return client

    def forget(self, node_id: int) -> None:
        """Drop a cached conn (callers do this on a connection error so
        the next dial reconnects)."""
        with self._lock:
            cached = self._conns.pop(node_id, None)
        if cached is not None:
            try:
                cached[1].close()
            except OSError:
                pass

    def close(self) -> None:
        with self._lock:
            conns = list(self._conns.values())
            self._conns.clear()
        for _, c in conns:
            try:
                c.close()
            except OSError:
                pass
