"""Multi-tenancy (reduced) — shared-KV tenants with keyspace isolation.

Reference: pkg/multitenant + pkg/ccl/sqlproxyccl + kvclient/kvtenant run
SQL pods against a shared KV cluster, each tenant confined to its own
keyspace prefix and gated by a capability set (tenantcapabilities). This
reduction keeps the architectural invariants on the engine's one-byte
table-prefix keyspace (storage/rowcodec.py):

- every tenant owns a DISJOINT table-id range, so its keys occupy a
  disjoint span of the shared LSM by construction — no runtime check can
  leak cross-tenant rows because the catalog cannot even address them;
- tenant records live in the system keyspace (b"\\x01tnt"), created/
  altered only through the system tenant (tenant 1), mirroring how the
  reference gates tenant DDL on the system tenant;
- capabilities gate tenant-visible features at the Session dispatch
  boundary (can_create_table, can_backup, max_tables — the
  tenantcapabilities.CanUseNodelocalStorage/... role).

Scale bound (documented divergence): the one-byte table prefix caps the
keyspace at 253 table ids, so tenants get 16-id ranges past the system
tenant's 1..127 — enough for the test matrix, not production scale; the
reference's varint tenant prefixes lift that bound, not the design.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from ..storage.rowcodec import MAX_TABLE_ID
from .txn import DB

_PREFIX = b"\x01tnt"

SYSTEM_TENANT_ID = 1
# utils/admission.py hardcodes this id (the utils layer must not import
# kv); keep the two pinned together
from ..utils.admission import SYSTEM_TENANT_ID as _ADM_SYSTEM_ID  # noqa: E402

assert _ADM_SYSTEM_ID == SYSTEM_TENANT_ID

_SYSTEM_RANGE = (1, 127)
_RANGE_WIDTH = 16
_FIRST_SECONDARY_LO = 128

DEFAULT_CAPS = {
    "can_create_table": True,
    "can_backup": False,
    "max_tables": _RANGE_WIDTH // 2,  # table + dictionary span per table
}


class TenantError(Exception):
    pass


class CapabilityError(TenantError):
    """A tenant attempted an operation its capability set denies."""


@dataclass
class TenantRecord:
    tenant_id: int
    name: str
    id_lo: int
    id_hi: int
    caps: dict = field(default_factory=dict)

    def to_bytes(self) -> bytes:
        return json.dumps({
            "tenant_id": self.tenant_id, "name": self.name,
            "id_lo": self.id_lo, "id_hi": self.id_hi, "caps": self.caps,
        }).encode()

    @staticmethod
    def from_bytes(b: bytes) -> "TenantRecord":
        d = json.loads(bytes(b).decode())
        return TenantRecord(d["tenant_id"], d["name"], d["id_lo"],
                            d["id_hi"], d["caps"])


def _key(tenant_id: int, chunk: int = 0) -> bytes:
    # records chunk across rows like table descriptors (kv/chunked.py):
    # the JSON outgrows small engine value widths
    return _PREFIX + b"%03d|%02d" % (tenant_id, chunk)


def _write_record(t, rec: "TenantRecord", val_width: int) -> None:
    from .chunked import chunk_blob

    step = max(16, val_width - 1)
    for ci, piece in enumerate(chunk_blob(rec.to_bytes(), step)):
        t.put(_key(rec.tenant_id, ci), piece)


def _decode_records(rows) -> list["TenantRecord"]:
    from .chunked import unchunk

    by_id: dict[bytes, list[tuple[bytes, bytes]]] = {}
    for k, v in rows:
        tid = k[len(_PREFIX):].split(b"|")[0]
        by_id.setdefault(tid, []).append((k, v))
    return [
        TenantRecord.from_bytes(unchunk([v for _, v in sorted(chunks)]))
        for _, chunks in sorted(by_id.items())
    ]


class TenantRegistry:
    """Tenant records in the shared KV store. All mutations run as
    transactions so concurrent CREATE TENANT calls serialize on the
    record keys (same discipline as jobs id allocation)."""

    def __init__(self, db: DB):
        self.db = db

    # -- reads -------------------------------------------------------------

    def list(self) -> list[TenantRecord]:
        from ..utils.errors import retry_past_intents

        rows = retry_past_intents(
            lambda: self.db.scan(_PREFIX, _PREFIX + b"\xff")
        )
        return _decode_records(rows)

    def get(self, name_or_id) -> TenantRecord:
        for rec in self.list():
            if rec.tenant_id == name_or_id or rec.name == name_or_id:
                return rec
        raise TenantError(f"tenant {name_or_id!r} does not exist")

    # -- system-tenant DDL ---------------------------------------------------

    def create(self, name: str, caps: dict | None = None) -> TenantRecord:
        """Allocate the next disjoint table-id range and persist the
        record; the whole read-allocate-write runs in one txn."""
        if not name or name == "system":
            raise TenantError("invalid tenant name")

        out: list[TenantRecord] = []

        def op(t):
            out.clear()
            existing = _decode_records(t.scan(_PREFIX, _PREFIX + b"\xff"))
            if any(r.name == name for r in existing):
                raise TenantError(f"tenant {name!r} already exists")
            next_id = max((r.tenant_id for r in existing),
                          default=SYSTEM_TENANT_ID) + 1
            lo = _FIRST_SECONDARY_LO + _RANGE_WIDTH * (next_id - 2)
            hi = lo + _RANGE_WIDTH - 1
            if hi > MAX_TABLE_ID:
                raise TenantError(
                    "tenant keyspace exhausted (one-byte table prefix; "
                    "see module docstring)"
                )
            rec = TenantRecord(next_id, name, lo, hi,
                               dict(DEFAULT_CAPS, **(caps or {})))
            _write_record(t, rec, self.db.engine.val_width)
            out.append(rec)

        self.db.txn(op)
        return out[0]

    def set_capability(self, name: str, cap: str, value) -> TenantRecord:
        out: list[TenantRecord] = []

        def op(t):
            out.clear()
            for rec in _decode_records(t.scan(_PREFIX, _PREFIX + b"\xff")):
                if rec.name == name:
                    rec.caps[cap] = value
                    _write_record(t, rec, self.db.engine.val_width)
                    out.append(rec)
                    return
            raise TenantError(f"tenant {name!r} does not exist")

        self.db.txn(op)
        return out[0]

    def drop(self, name: str) -> None:
        """Drop the record. Table data in the tenant's range stays until
        GC (the reference also decouples record drop from data GC)."""
        def op(t):
            rows = t.scan(_PREFIX, _PREFIX + b"\xff")
            for rec in _decode_records(rows):
                if rec.name == name:
                    if rec.tenant_id == SYSTEM_TENANT_ID:
                        raise TenantError("cannot drop the system tenant")
                    pref = _PREFIX + b"%03d|" % rec.tenant_id
                    for k, _ in rows:
                        if k.startswith(pref):
                            t.delete(k)
                    return
            raise TenantError(f"tenant {name!r} does not exist")

        self.db.txn(op)

    def bootstrap(self) -> TenantRecord:
        """Ensure the system tenant record exists (idempotent)."""
        def op(t):
            if t.get(_key(SYSTEM_TENANT_ID)) is None:
                rec = TenantRecord(
                    SYSTEM_TENANT_ID, "system", *_SYSTEM_RANGE,
                    {"can_create_table": True, "can_backup": True,
                     "max_tables": 63},
                )
                _write_record(t, rec, self.db.engine.val_width)

        self.db.txn(op)
        return self.get(SYSTEM_TENANT_ID)


def check_capability(rec: TenantRecord, cap: str) -> None:
    if not rec.caps.get(cap, False):
        raise CapabilityError(
            f"tenant {rec.name!r} lacks capability {cap!r}"
        )
