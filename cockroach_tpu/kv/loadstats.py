"""Per-range load stats — the replicastats/split.Decider analog.

Reference: pkg/kv/kvserver/replicastats tracks per-replica QPS with
exponentially decaying counters; split.(*Decider) additionally records a
reservoir of request keys (split/finder.go) so that when the decider
declares the range hot, a split key balancing the observed load is already
at hand. Here a `RangeLoadStats` lives on the DistSender (the single place
every routed request passes through in-process) and keeps, per range:

- decayed queries/sec and write-bytes/sec (half-life decay, no timer
  thread: decay is applied lazily at record/read time), and
- a seeded reservoir sample of request keys, from which `split_key`
  proposes the median — the key that puts ~half the observed load on
  each side.

The clock is injectable so tests can step time deterministically.
"""

from __future__ import annotations

import random
import time

from ..utils import locks


class DecayingCounter:
    """Exponentially decaying rate estimator.

    `record(n)` adds n events "now"; `rate()` returns events/sec with
    past events discounted by half every `half_life_s`. Lazy decay: the
    running total is folded forward on every touch, so an idle range's
    rate falls toward zero without any background work.
    """

    def __init__(self, half_life_s: float = 30.0, clock=time.monotonic):
        self.half_life_s = float(half_life_s)
        self._clock = clock
        self._value = 0.0          # decayed event count
        self._last = self._clock()

    def _decay(self) -> None:
        now = self._clock()
        dt = now - self._last
        if dt > 0:
            self._value *= 0.5 ** (dt / self.half_life_s)
            self._last = now

    def record(self, n: float = 1.0) -> None:
        self._decay()
        self._value += n

    def rate(self) -> float:
        """Decayed events/sec: the decayed count spread over the window
        that contributed it (~1.44 half-lives, the decay's mean age)."""
        self._decay()
        return self._value / (1.4427 * self.half_life_s)


class _RangeLoad:
    __slots__ = ("qps", "wbps", "samples", "seen")

    def __init__(self, half_life_s: float, clock):
        self.qps = DecayingCounter(half_life_s, clock)
        self.wbps = DecayingCounter(half_life_s, clock)
        self.samples: list[bytes] = []   # reservoir of request keys
        self.seen = 0                    # requests offered to the reservoir


class RangeLoadStats:
    """Per-range decayed load + split-key reservoir, keyed by range id."""

    def __init__(self, half_life_s: float = 30.0, sample_size: int = 16,
                 seed: int = 0, clock=time.monotonic):
        self.half_life_s = float(half_life_s)
        self.sample_size = int(sample_size)
        self._rng = random.Random(seed)
        self._clock = clock
        self._mu = locks.lock("kv.loadstats")
        self._ranges: dict[int, _RangeLoad] = {}

    def _load(self, range_id: int) -> _RangeLoad:
        rl = self._ranges.get(range_id)
        if rl is None:
            rl = self._ranges[range_id] = _RangeLoad(
                self.half_life_s, self._clock)
        return rl

    def _sample(self, rl: _RangeLoad, key: bytes) -> None:
        rl.seen += 1
        if len(rl.samples) < self.sample_size:
            rl.samples.append(bytes(key))
        else:
            j = self._rng.randrange(rl.seen)
            if j < self.sample_size:
                rl.samples[j] = bytes(key)

    def record_read(self, range_id: int, key: bytes) -> None:
        with self._mu:
            rl = self._load(range_id)
            rl.qps.record(1.0)
            self._sample(rl, key)

    def record_write(self, range_id: int, key: bytes, nbytes: int) -> None:
        with self._mu:
            rl = self._load(range_id)
            rl.qps.record(1.0)
            rl.wbps.record(float(nbytes))
            self._sample(rl, key)

    def qps(self, range_id: int) -> float:
        with self._mu:
            rl = self._ranges.get(range_id)
            return rl.qps.rate() if rl else 0.0

    def write_bytes_rate(self, range_id: int) -> float:
        with self._mu:
            rl = self._ranges.get(range_id)
            return rl.wbps.rate() if rl else 0.0

    def split_key(self, range_id: int, start_key: bytes,
                  end_key: bytes | None) -> bytes | None:
        """Median sampled key strictly inside (start_key, end_key) — the
        split.Finder reduction: cut where ~half the observed requests land
        on each side. None when the samples can't name an interior point
        (single hot key, or everything at the range start)."""
        with self._mu:
            rl = self._ranges.get(range_id)
            if rl is None or not rl.samples:
                return None
            inside = sorted(
                k for k in rl.samples
                if k > start_key and (end_key is None or k < end_key))
            if not inside:
                return None
            return inside[len(inside) // 2]

    def note_split(self, parent_id: int, child_id: int,
                   split_key: bytes) -> None:
        """Hand the child its share of the parent's history so the fresh
        range doesn't look cold (and immediately merge-eligible): samples
        partition by the split key; rates halve on both sides."""
        with self._mu:
            rl = self._ranges.get(parent_id)
            if rl is None:
                return
            child = self._load(child_id)
            child_samples = [k for k in rl.samples if k >= split_key]
            rl.samples = [k for k in rl.samples if k < split_key]
            child.samples = child_samples[-self.sample_size:]
            child.seen = len(child.samples)
            rl.seen = max(rl.seen // 2, len(rl.samples))
            for src, dst in ((rl.qps, child.qps), (rl.wbps, child.wbps)):
                src._decay()
                dst._decay()
                dst._value += src._value / 2.0
                src._value /= 2.0

    def note_merge(self, keep_id: int, gone_id: int) -> None:
        """Fold the absorbed range's remaining load into the survivor."""
        with self._mu:
            gone = self._ranges.pop(gone_id, None)
            if gone is None:
                return
            keep = self._load(keep_id)
            for src, dst in ((gone.qps, keep.qps), (gone.wbps, keep.wbps)):
                src._decay()
                dst._decay()
                dst._value += src._value
            room = self.sample_size - len(keep.samples)
            if room > 0:
                keep.samples.extend(gone.samples[:room])
            keep.seen += gone.seen

    def stranded_beyond(self, range_id: int, end_key: bytes) -> bool:
        """True when the range still holds samples at/after `end_key` —
        the signature of a torn split: the meta boundary landed but the
        load handoff (note_split) never ran. A healthy split partitions
        samples at the boundary, and post-split requests route per-range,
        so out-of-bounds samples only survive a crashed apply."""
        with self._mu:
            rl = self._ranges.get(range_id)
            return bool(rl and any(k >= end_key for k in rl.samples))

    def forget(self, range_id: int) -> None:
        with self._mu:
            self._ranges.pop(range_id, None)

    def report(self) -> dict[int, dict]:
        """Snapshot for /hot_ranges: {rid: {qps, writeBytesRate}}."""
        with self._mu:
            return {
                rid: {"qps": rl.qps.rate(),
                      "writeBytesRate": rl.wbps.rate()}
                for rid, rl in self._ranges.items()
            }
