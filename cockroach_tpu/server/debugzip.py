"""debug zip — the `cockroach debug zip` reduction.

Reference: pkg/cli/zip.go walks every status endpoint of a cluster and
packs the responses into one archive a support engineer can read offline.
Here the same shape over this node's surfaces: metrics, settings,
statement statistics, hot ranges, in-flight spans, and every statement
diagnostics bundle still in the ring (sql/diagnostics.py).

Two collection modes:

- ``collect(url=...)`` pulls the /_status endpoints of a RUNNING node over
  HTTP (the normal operator path — `cockroach-tpu debug zip --url ...`);
- ``collect()`` snapshots the current process's registries directly, so an
  in-process session (tests, the demo shell) can produce the same archive
  without a server.

Per-endpoint failures degrade to an error stub inside the archive instead
of aborting it — a half-broken node is exactly when you want the zip.
"""

from __future__ import annotations

import json
import zipfile

_ENDPOINTS = {
    "metrics.txt": "/_status/vars",
    "nodes.json": "/_status/nodes",
    "jobs.json": "/_status/jobs",
    "settings.json": "/_status/settings",
    "statements.json": "/_status/statements",
    "hot_ranges.json": "/hot_ranges",
    "contention.json": "/_status/contention",
    "spans.json": "/_status/spans",
    "diagnostics.json": "/_status/diagnostics",
    "load.json": "/_status/load",
}


def _url_files(base: str) -> dict[str, str]:
    from urllib.request import urlopen

    base = base.rstrip("/")
    files: dict[str, str] = {}
    for fname, path in _ENDPOINTS.items():
        try:
            with urlopen(base + path, timeout=5) as r:
                files[fname] = r.read().decode("utf-8")
        except (OSError, ValueError) as e:
            files[fname] = json.dumps({"error": str(e)})
    try:
        listing = json.loads(files.get("diagnostics.json", "{}"))
        for b in listing.get("bundles", []):
            bid = int(b["id"])
            with urlopen(base + f"/_status/diagnostics?id={bid}",
                         timeout=5) as r:
                files[f"diagnostics/bundle_{bid:06d}.json"] = (
                    r.read().decode("utf-8"))
    except (OSError, ValueError, KeyError):
        pass  # the ring listing is already in the archive; bundles degrade
    return files


def _process_files() -> dict[str, str]:
    from ..kv.contention import DEFAULT as _cont
    from ..sql import diagnostics as diag
    from ..sql import sqlstats
    from ..utils import metric, settings, tracing
    from .http import load_payload

    files = {
        "metrics.txt": metric.DEFAULT.scrape(),
        "settings.json": json.dumps({"settings": {
            name: s.get() for name, s in settings.all_settings().items()
        }}, indent=1, default=str),
        "statements.json": json.dumps(
            {"statements": sqlstats.DEFAULT.rows_payload()}, indent=1),
        "contention.json": json.dumps({"events": _cont.rows_payload()},
                                      indent=1, default=str),
        "spans.json": json.dumps({"spans": [
            {"traceId": s.trace_id, "spanId": s.span_id,
             "operation": s.name} for s in tracing.inflight()
        ]}, indent=1),
        "diagnostics.json": json.dumps({"bundles": diag.bundles()},
                                       indent=1),
        "load.json": json.dumps(load_payload(), indent=1, default=str),
    }
    for b in diag.bundles():
        full = diag.get(b["id"])
        if full is not None:
            files[f"diagnostics/bundle_{b['id']:06d}.json"] = json.dumps(
                full, indent=1, default=str)
    return files


def collect(url: str | None = None) -> dict[str, str]:
    """Archive contents as {member name: text}; url=None snapshots the
    current process instead of a remote node."""
    return _url_files(url) if url else _process_files()


def write_zip(path: str, files: dict[str, str]) -> str:
    with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as z:
        for name in sorted(files):
            z.writestr("debug/" + name, files[name])
    return path
