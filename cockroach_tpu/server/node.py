"""Node lifecycle — the server.Server / node startup reduction.

Reference: pkg/server/server.go assembles the engine, liveness heartbeats,
gossip, the jobs registry and the timeseries poller around one stopper;
pkg/server/node.go is the per-node identity. This Node composes the same
subsystems over one Engine/DB so they run AS A SYSTEM instead of as
libraries:

- liveness:   a background heartbeat keeps this node's epoch-stamped record
  fresh (kv/liveness.py); the jobs registry fences stale claimants with it.
- jobs:       Registry(liveness=...) adopts orphaned jobs of dead nodes on a
  ticker (jobs/adopt.go's claim-expired loop).
- tsdb:       a metrics poller snapshots the default registry into the
  timeseries keyspace on a ticker (ts/db.go PollSource role).
- gossip:     optional; serves an infostore endpoint, exchanges with peers,
  and bridges CLUSTER SETTINGS both ways — a SET here publishes
  `setting/<name>`, a fresher remote info applies locally (the
  settings/updater.go <- gossip path).
- admission:  the engine's IOGovernor paces writes by L0 health; the Node
  exposes it for observability.

start()/stop() bound every thread (the stopper discipline); everything is
single-process-scoped, multi-host rides the DCN socket plane (flow/dcn.py).
"""

from __future__ import annotations

import threading

from ..kv import DB, Clock
from ..kv.jobs import Registry, register_builtin_jobs
from ..kv.liveness import LeaseManager, NodeLiveness
from ..kv.tsdb import TimeSeriesDB
from ..storage.lsm import Engine
from ..utils import admission, log, metric, settings


class Node:
    def __init__(
        self,
        node_id: int = 1,
        db: DB | None = None,
        engine: Engine | None = None,
        heartbeat_interval_s: float = 0.2,
        ttl_ms: int = 1000,
        metrics_interval_s: float | None = 0.5,
        adopt_interval_s: float = 0.5,
        gossip_peers: list | None = None,
        lease_ranges: list[int] | None = None,
    ):
        self.node_id = int(node_id)
        self.db = db if db is not None else DB(
            # key budget: tsdb keys are "\x01ts<metric>|<13-digit ms>" —
            # metric names run ~30 bytes, so the node store uses wide keys
            engine if engine is not None else Engine(key_width=64,
                                                     val_width=128),
            Clock(),
        )
        self.liveness = NodeLiveness(
            self.db, self.node_id,
            heartbeat_interval_ms=int(heartbeat_interval_s * 1000),
            ttl_ms=ttl_ms,
        )
        # epoch leases: the node competes for every range in lease_ranges
        # (replica_range_lease acquisition loop); a vacant or dead-holder
        # lease is taken after fencing the holder's liveness epoch
        self.leases = LeaseManager(self.liveness)
        self._lease_ranges = list(lease_ranges or [])
        self._advertised_leases: dict[int, tuple[int, int]] = {}
        self.jobs = Registry(self.db, node_id=self.node_id,
                             liveness=self.liveness)
        register_builtin_jobs(self.jobs)
        self.tsdb = TimeSeriesDB(self.db)
        self.gossip = None
        self._gossip_peers = list(gossip_peers or [])
        self._hb_interval = heartbeat_interval_s
        self._metrics_interval = metrics_interval_s
        self._adopt_interval = adopt_interval_s
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._settings_cb = None
        self._applying_remote = False
        # range lifecycle (kv/allocator.py): wired in start() when the DB
        # is DistSender-backed and kv.allocator.enabled
        self.ranger = None
        self._wired_sender = None
        self._lease_guard_local = threading.local()

    # -- lifecycle -----------------------------------------------------------

    def start(self, gossip_port: int = 0,
              pg_port: int | None = None,
              http_port: int | None = None,
              kv_port: int | None = None) -> "Node":
        self._stop.clear()
        self.liveness.heartbeat()  # own record exists before anything reads

        # disk health: WAL-backed engines get a monitor fed by their own
        # WAL appends plus a periodic probe (storage/disk.py)
        self.disk = None
        eng = self.db.engine
        if getattr(eng, "wal_path", None):
            import os

            from ..storage.disk import DiskMonitor

            self.disk = DiskMonitor(
                os.path.dirname(eng.wal_path) or "."
            ).start()
            eng.disk_monitor = self.disk

        # run pending upgrade migrations before serving (upgrademanager
        # role: the store's persisted version catches up to the binary's)
        from ..kv.upgrade import run_upgrades

        ran = run_upgrades(self.db)
        for name in ran:
            log.info(log.OPS, "upgrade migration complete", name=name)

        # the serving engine's L0 health feeds the admission shed ladder:
        # a badly-behind LSM sheds analytical statements before the write
        # path inverts (io_load_listener -> GrantCoordinator shape)
        if getattr(eng, "governor", None) is not None:
            admission.set_io_health_provider(eng.governor.l0_overload)

        self._spawn(self._heartbeat_loop, "liveness-heartbeat")
        self._spawn(self._metrics_loop, "tsdb-poller")
        self._spawn(self._adopt_loop, "jobs-adopt")

        self.admin = None
        if http_port is not None:
            from .http import AdminServer

            self.admin = AdminServer(self, port=http_port).serve_background()

        self.kv_rpc = None
        if kv_port is not None:
            from ..kv.rpc import BatchServer

            # the Internal.Batch endpoint (server/node.go Node.Batch role).
            # Range-addressed mutation batches are guarded by the lease
            # check: a fenced node answers EpochFencedError instead of
            # serving writes under an epoch it no longer owns.
            self.kv_rpc = BatchServer(self.db, port=kv_port,
                                      lease_check=self._lease_check)
        if self._lease_ranges:
            self._spawn(self._lease_loop, "lease-acquire")

        self.dialer = None

        self.pg = None
        if pg_port is not None:
            from .pgwire import PgServer

            # every pgwire connection gets its own Session over this
            # node's shared catalog/DB (conn-executor-per-session)
            from ..catalog import Catalog

            self._sql_catalog = Catalog()
            self.pg = PgServer(catalog=self._sql_catalog, db=self.db,
                               port=pg_port).serve_background()

        if gossip_port is not None and (self._gossip_peers
                                        or gossip_port >= 0):
            from ..flow.gossip import Gossip

            self.gossip = Gossip(self.node_id)
            self._gossip_addr = self.gossip.serve(port=gossip_port)
            if self._gossip_peers:
                self.gossip.run_background(self._gossip_peers,
                                           interval_s=0.1)
            self._settings_cb = self._publish_setting
            settings.on_change(self._settings_cb)
            self._spawn(self._settings_apply_loop, "gossip-settings")
            # advertise the KV endpoint + hand out a dialer (nodedialer
            # role: peers resolve node ids through gossip, never addresses)
            from ..kv.dialer import NodeDialer, advertise

            if self.kv_rpc is not None:
                advertise(self.gossip, self.node_id, self.kv_rpc.addr)
            self.dialer = NodeDialer(self.gossip)

        # range lifecycle: a DistSender-backed node runs the split/merge/
        # rebalance queues and carries the (holder, epoch) guard onto
        # EVERY routed piece — range-addressed stamping survives an
        # auto-split mid-batch (the DistSender split-path open item)
        from ..kv.dist import DistSender

        sender = self.db.engine
        if isinstance(sender, DistSender):
            if sender.lease_check is None:
                sender.lease_check = self._dist_lease_check
                self._wired_sender = sender
            if settings.get("kv.allocator.enabled"):
                from ..kv.allocator import RangeLifecycle
                from ..kv.loadstats import RangeLoadStats

                if sender.load is None:
                    sender.load = RangeLoadStats()
                self.ranger = RangeLifecycle(
                    sender, load=sender.load, leases=self.leases,
                    gossip=self.gossip, node_id=self.node_id,
                    store_nodes={sid: self.node_id
                                 for sid in sender.stores},
                    # scans walk span_stats over every range — pace them
                    # well below the heartbeat cadence or the scanner's
                    # engine passes starve foreground traffic
                    interval_s=max(self._hb_interval * 5, 0.25),
                )
                self.ranger.start()
        if (getattr(self, "_sql_catalog", None) is not None
                and settings.get("sql.warmup.menu.enabled")):
            # AOT kernel menu: compile the shape-ladder/hot-statement
            # kernels BEFORE advertising readiness, bounded by
            # sql.warmup.menu.budget_s — a fresh node joins pre-warmed
            from ..sql import warmmenu

            warmmenu.warm_node(self)
        log.info(log.OPS, "node started", node=self.node_id)
        return self

    def stop(self) -> None:
        self._stop.set()
        if getattr(self, "_warmmenu_run", None) is not None:
            # a budget-bound menu straggler stops at its next statement
            # boundary; join so no warm-menu thread survives teardown
            self._warmmenu_run.stop_join()
            self._warmmenu_run = None
        admission.set_io_health_provider(None)
        if self.ranger is not None:
            self.ranger.stop()
            self.ranger = None
        if self._wired_sender is not None:
            self._wired_sender.lease_check = None
            self._wired_sender = None
        if self._settings_cb is not None:
            settings.remove_on_change(self._settings_cb)
            self._settings_cb = None
        # stop() may run ON a node thread (the fenced heartbeat path):
        # joining yourself deadlocks, so skip the calling thread
        for t in self._threads:
            if t is not threading.current_thread():
                t.join(timeout=5)
        self._threads.clear()
        if self.gossip is not None:
            self.gossip.close()
            self.gossip = None
        if getattr(self, "pg", None) is not None:
            self.pg.close()
            self.pg = None
        if getattr(self, "admin", None) is not None:
            self.admin.close()
            self.admin = None
        if getattr(self, "disk", None) is not None:
            self.disk.stop()
            self.disk = None
        if getattr(self, "kv_rpc", None) is not None:
            self.kv_rpc.close()
            self.kv_rpc = None
        if getattr(self, "dialer", None) is not None:
            self.dialer.close()
            self.dialer = None
        log.info(log.OPS, "node stopped", node=self.node_id)

    # stopper discipline: close() is the public teardown name (the
    # reference's stopper.Stop); every queue/scanner thread is joined
    close = stop

    def _dist_lease_check(self, range_id: int) -> None:
        """DistSender routing guard: when THIS node believes it holds the
        range's lease, verify the (holder, epoch) pair is still valid —
        so a fenced node fails every piece of a multi-range batch,
        including children minted by an auto-split mid-batch. Vacant or
        foreign leases pass through (the server-side guard owns those).
        Reentrancy: the guard's own lease/liveness reads route through
        this same sender; the thread-local skips the nested check.

        An intent on the lease record means a transfer/carry txn is
        mid-commit — and that txn's commit may be waiting on the sender
        lock THIS request holds, so waiting the intent out would
        deadlock until the retry budget expires. Serve under the
        current terms instead: the fencing property lives in the epoch
        equality check, which a committed transfer re-asserts on the
        very next request."""
        from ..kv.txn import TransactionRetryError
        from ..storage.lsm import WriteIntentError

        if getattr(self._lease_guard_local, "busy", False):
            return
        self._lease_guard_local.busy = True
        try:
            rec = self.leases.holder(range_id)
            if rec is not None and rec.node_id == self.node_id:
                self.leases.check(range_id)
        except (WriteIntentError, TransactionRetryError):
            pass
        finally:
            self._lease_guard_local.busy = False

    def _spawn(self, fn, name: str) -> None:
        t = threading.Thread(target=fn, name=f"{name}-n{self.node_id}",
                             daemon=True)
        t.start()
        self._threads.append(t)

    # -- loops ---------------------------------------------------------------

    def _heartbeat_loop(self) -> None:
        from ..kv.liveness import EpochFencedError
        from ..kv.txn import TransactionRetryError

        while not self._stop.wait(self._hb_interval):
            try:
                self.liveness.heartbeat()
            except EpochFencedError:
                # declared dead by a peer: the WHOLE node must stop taking
                # work (a fenced node that keeps adopting jobs runs them in
                # parallel with its fencer). Stop every loop; claims made
                # under the old believed epoch keep failing their fence
                # check. The reference's node exits on this signal too.
                log.warning(log.OPS, "heartbeat fenced; stopping node",
                            node=self.node_id)
                self._stop.set()
                return
            except TransactionRetryError:
                continue  # contended heartbeat key; next tick retries
            except (ConnectionError, OSError):
                # blackholed heartbeat (liveness.heartbeat fault or a real
                # partition): the record silently ages toward expiry while
                # the node keeps trying — exactly the reference's behavior
                # when a node loses the liveness range
                continue

    # -- leases ---------------------------------------------------------------

    def _lease_check(self, req: dict) -> None:
        """BatchServer guard for range-addressed mutation batches: raises
        EpochFencedError / NotLeaseHolderError when this node may not
        serve the range. Batches without a range address (plain
        BatchClient traffic) bypass the guard — single-node topologies
        have no lease protocol to honor."""
        from ..kv.liveness import NotLeaseHolderError
        from ..storage.lsm import WriteIntentError

        rid = req.get("range")
        if rid is not None:
            try:
                self.leases.check(int(rid))
            except WriteIntentError as e:
                # lease/liveness record mid-commit (a heartbeat or a
                # failover's fencing write): lease state is UNRESOLVED,
                # and the only safe answer is "don't serve" — typed so
                # the router re-resolves and retries instead of
                # surfacing a storage-level error to the application
                raise NotLeaseHolderError(
                    f"r{rid} lease state unresolved (record mid-commit); "
                    f"retry") from e

    def _lease_loop(self) -> None:
        from ..kv.liveness import NotLeaseHolderError, StillLiveError
        from ..kv.txn import TransactionRetryError
        from ..storage.lsm import WriteIntentError

        while not self._stop.wait(self._hb_interval):
            for rid in self._lease_ranges:
                try:
                    prev = self.leases.holder(rid)
                    rec = self.leases.acquire(rid)
                except NotLeaseHolderError:
                    continue  # a live peer holds it; that's healthy
                except (StillLiveError, TransactionRetryError):
                    continue  # lost a failover race; next tick re-reads
                except WriteIntentError:
                    continue  # a peer's lease write mid-commit; next tick
                except (ConnectionError, OSError):
                    continue  # injected epoch_bump/transport fault
                except Exception as e:  # noqa: BLE001 - loop must survive  # crlint: allow-broad-except(lease loop must survive; logged)
                    log.warning(log.OPS, "lease acquire failed",
                                range=rid, error=str(e))
                    continue
                if (prev is not None and prev.node_id != self.node_id
                        and self.gossip is not None):
                    # we just fenced the old holder: its gossiped state
                    # is stale under the bumped epoch — expire it
                    self.gossip.note_epoch(prev.node_id, prev.epoch + 1)
                ad = (rec.node_id, rec.epoch)
                if (self._advertised_leases.get(rid) != ad
                        and self.gossip is not None):
                    self.gossip.add_info(f"lease/{rid}",
                                         f"{rec.node_id}:{rec.epoch}")
                    self._advertised_leases[rid] = ad

    def _metrics_loop(self) -> None:
        import time as _time

        from ..kv import hlc

        last_prune = _time.monotonic()
        while True:
            # constructor interval wins when given; otherwise the live
            # cluster setting paces the scraper (SET takes effect next tick)
            iv = (self._metrics_interval if self._metrics_interval is not None
                  else settings.get("ts.scrape_interval_seconds"))
            if self._stop.wait(iv):
                return
            try:
                # re-publish the pull-style gauges (memory monitors,
                # admission queue) so each scrape records live values even
                # when nothing ran since the last tick
                from ..flow import memory as flowmem
                from ..kv import fanout
                from ..storage import blockcache

                flowmem.refresh_gauges()
                admission.refresh_gauges()
                blockcache.refresh_gauges()
                fanout.refresh_gauges()
                self.tsdb.record(metric.DEFAULT)
                retention = settings.get("ts.retention_seconds")
                # prune at ~1/10 the scrape cadence: a retention trim scans
                # the whole ts keyspace, too heavy for per-tick work
                if retention and _time.monotonic() - last_prune >= iv * 10:
                    wall, _ = hlc.unpack(self.db.clock.now())
                    self.tsdb.prune_all(wall - int(retention * 1e3))
                    last_prune = _time.monotonic()
            except Exception as e:  # metric write must never kill the node  # crlint: allow-broad-except(metric write must never kill the node; logged)
                log.warning(log.OPS, "tsdb poll failed", error=str(e))

    def _adopt_loop(self) -> None:
        while not self._stop.wait(self._adopt_interval):
            try:
                adopted = self.jobs.adopt_orphans()
                for j in adopted:
                    log.info(log.OPS, "re-adopted orphaned job",
                             job=j.job_id, state=j.state)
            except Exception as e:  # crlint: allow-broad-except(adoption pass failure is logged, loop continues)
                log.warning(log.OPS, "adoption pass failed", error=str(e))

    # -- gossip <-> settings bridge ------------------------------------------

    _SETTING_PREFIX = "setting/"

    def _publish_setting(self, name: str, value) -> None:
        if self.gossip is None or self._applying_remote:
            return
        self.gossip.add_info(self._SETTING_PREFIX + name, value)

    def _settings_apply_loop(self) -> None:
        applied: dict[str, object] = {}
        while not self._stop.wait(0.1):
            if self.gossip is None:
                return
            for key in self.gossip.keys():
                if not key.startswith(self._SETTING_PREFIX):
                    continue
                name = key[len(self._SETTING_PREFIX):]
                info = self.gossip.get_info(key)
                if info is None or applied.get(name) == info:
                    continue
                try:
                    self._applying_remote = True
                    settings.set(name, info)
                    applied[name] = info
                except Exception as e:  # crlint: allow-broad-except(bad gossiped value is logged and pinned to avoid a retry storm)
                    log.warning(log.OPS, "gossiped setting rejected",
                                setting=name, error=str(e))
                    applied[name] = info  # don't retry a bad value forever
                finally:
                    self._applying_remote = False

    def gossip_addr(self):
        return getattr(self, "_gossip_addr", None)
