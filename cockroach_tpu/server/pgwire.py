"""pgwire — the Postgres v3 wire protocol server over the SQL session.

Reference: pkg/sql/pgwire/server.go:854 accepts conns, conn.go:343 reads
the startup message and serves the message loop; CockroachDB speaks v3 so
every Postgres driver works unchanged. This is the same surface, reduced
to the simple-query flow every driver's autocommit path uses:

  StartupMessage -> AuthenticationOk + ParameterStatus* + BackendKeyData
                    + ReadyForQuery
  'Q' (simple query) -> RowDescription / DataRow* / CommandComplete
                        (or ErrorResponse) -> ReadyForQuery
  SSLRequest -> 'N' (no TLS here); CancelRequest -> ignored; 'X' ends.

ReadyForQuery carries the session's REAL transaction status ('I' idle,
'T' in block, 'E' aborted block) — BEGIN/COMMIT/ROLLBACK flow through the
session FSM, so drivers' transaction handling works. Results travel in
text format (the universally-supported encoding); the extended protocol
(Parse/Bind/Execute) is the next increment.

Each connection gets its OWN Session over the shared catalog/DB — the
reference's conn-executor-per-session model.
"""

from __future__ import annotations

import re
import socket
import struct
import threading

import numpy as np

from ..sql import Session

_SSL_REQUEST = 80877103
_CANCEL_REQUEST = 80877102
_STARTUP_V3 = 196608

# type OIDs (pg_catalog.pg_type)
_OID_BOOL = 16
_OID_INT8 = 20
_OID_FLOAT8 = 701
_OID_TEXT = 25
_OID_DATE = 1082
_OID_NUMERIC = 1700


def _oid_for_dtype(dtype) -> int:
    """Column OID from the RESULT ARRAY's dtype — never from row values
    (a NULL in row 0 must not retype the whole column as TEXT)."""
    if dtype == np.bool_:
        return _OID_BOOL
    if np.issubdtype(dtype, np.integer):
        return _OID_INT8
    if np.issubdtype(dtype, np.floating):
        return _OID_FLOAT8
    return _OID_TEXT  # object arrays: strings or mixed/NULL-bearing


def _render(v) -> bytes | None:
    if v is None:
        return None
    if isinstance(v, (bool, np.bool_)):
        return b"t" if v else b"f"
    if isinstance(v, (float, np.floating)):
        return repr(float(v)).encode()
    return str(v).encode()


class _Conn:
    def __init__(self, sock: socket.socket, session: Session):
        self.sock = sock
        self.session = session
        self._ext_failed = False  # error sent; discarding until Sync
        self._stmts: dict[bytes, str] = {}  # prepared statements
        self._portals: dict[bytes, str] = {}  # bound portals (params inlined)

    # -- framing -------------------------------------------------------------

    def _recv_exact(self, n: int) -> bytes:
        buf = bytearray()
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client closed")
            buf.extend(chunk)
        return bytes(buf)

    def _send(self, tag: bytes, payload: bytes = b"") -> None:
        self.sock.sendall(tag + struct.pack("!I", len(payload) + 4) + payload)

    # -- startup -------------------------------------------------------------

    def startup(self) -> bool:
        while True:
            n = struct.unpack("!I", self._recv_exact(4))[0]
            body = self._recv_exact(n - 4)
            code = struct.unpack("!I", body[:4])[0]
            if code == _SSL_REQUEST:
                self.sock.sendall(b"N")  # no TLS; client retries plaintext
                continue
            if code == _CANCEL_REQUEST:
                return False
            if code != _STARTUP_V3:
                raise ConnectionError(f"unsupported protocol {code}")
            break
        self._send(b"R", struct.pack("!I", 0))  # AuthenticationOk (trust)
        for k, v in (
            (b"server_version", b"13.0 cockroach_tpu"),
            (b"client_encoding", b"UTF8"),
            (b"DateStyle", b"ISO"),
        ):
            self._send(b"S", k + b"\x00" + v + b"\x00")
        self._send(b"K", struct.pack("!II", 0, 0))  # BackendKeyData
        self._ready()
        return True

    def _txn_status(self) -> bytes:
        if getattr(self.session, "_txn_aborted", False):
            return b"E"
        return b"T" if getattr(self.session, "_txn", None) is not None \
            else b"I"

    def _ready(self) -> None:
        self._send(b"Z", self._txn_status())

    # -- query flow ----------------------------------------------------------

    def _error(self, msg: str, code: str = "XX000") -> None:
        fields = (b"SERROR\x00" + b"C" + code.encode() + b"\x00"
                  + b"M" + msg.encode("utf-8", "replace") + b"\x00\x00")
        self._send(b"E", fields)

    def _row_description(self, names, dtypes) -> None:
        out = [struct.pack("!H", len(names))]
        for name, dt in zip(names, dtypes):
            out.append(
                name.encode() + b"\x00"
                + struct.pack("!IHIhih", 0, 0, _oid_for_dtype(dt), -1, -1, 0)
            )
        self._send(b"T", b"".join(out))

    def _data_row(self, row) -> None:
        out = [struct.pack("!H", len(row))]
        for v in row:
            r = _render(v)
            if r is None:
                out.append(struct.pack("!i", -1))
            else:
                out.append(struct.pack("!i", len(r)) + r)
        self._send(b"D", b"".join(out))

    def _run_query(self, sql_text: str, send_row_desc: bool = True) -> None:
        res = self.session.execute(sql_text)
        if isinstance(res, dict) and res and all(
            isinstance(v, np.ndarray) for v in res.values()
        ):
            names = list(res.keys())
            nrows = len(res[names[0]]) if names else 0
            if send_row_desc:  # extended Execute relies on Describe's
                self._row_description(names, [res[n].dtype for n in names])
            for i in range(nrows):
                self._data_row([res[n][i] for n in names])
            self._send(b"C", b"SELECT %d\x00" % nrows)
            return
        # DML / DDL / txn control results
        if isinstance(res, dict):
            if "rows_affected" in res:
                n = res["rows_affected"]
                low = sql_text.strip().lower()
                if low.startswith("insert"):
                    tag = b"INSERT 0 %d" % n
                elif low.startswith("update"):
                    tag = b"UPDATE %d" % n
                elif low.startswith("delete"):
                    tag = b"DELETE %d" % n
                else:
                    tag = b"OK"
            elif "begin" in res:
                tag = b"BEGIN"
            elif "commit" in res:
                tag = b"COMMIT"
            elif "rollback" in res:
                tag = b"ROLLBACK"
            elif "created" in res:
                tag = b"CREATE TABLE"
            elif "analyzed" in res:
                tag = b"ANALYZE"
            else:
                tag = b"OK"
        else:
            tag = b"OK"
        self._send(b"C", tag + b"\x00")

    def serve(self) -> None:
        if not self.startup():
            return
        while True:
            tag = self._recv_exact(1)
            n = struct.unpack("!I", self._recv_exact(4))[0]
            body = self._recv_exact(n - 4)
            if tag == b"X":  # Terminate
                return
            if self._ext_failed and tag != b"S":
                # error-recovery rule: after the batch's ErrorResponse,
                # discard EVERYTHING (including stray Query/unknown tags)
                # until Sync — any extra response would desync the client
                continue
            if tag == b"Q":
                sql_text = body.rstrip(b"\x00").decode("utf-8", "replace")
                try:
                    if sql_text.strip():
                        self._run_query(sql_text)
                    else:
                        self._send(b"I", b"")  # EmptyQueryResponse
                except Exception as e:  # crlint: allow-broad-except(query error becomes an ErrorResponse to the client)
                    self._error(f"{type(e).__name__}: {e}",
                                code=_sqlstate_for(e))
                self._ready()
            elif tag in (b"P", b"B", b"D", b"E", b"C"):
                # extended protocol (Parse/Bind/Describe/Execute/Close):
                # on ANY failure send ONE ErrorResponse then discard until
                # Sync (the error-recovery rule — a second error before
                # Sync would desync pipeline-mode clients' result queues)
                try:
                    self._extended(tag, body)
                except Exception as e:  # crlint: allow-broad-except(extended-protocol error becomes ONE ErrorResponse then discard-until-Sync)
                    self._ext_failed = True
                    self._error(f"{type(e).__name__}: {e}",
                                code=_sqlstate_for(e))
            elif tag == b"F":
                if not self._ext_failed:
                    self._ext_failed = True
                    self._error("FunctionCall is not supported",
                                code="0A000")
            elif tag == b"H":  # Flush: nothing buffered, nothing to do
                pass
            elif tag == b"S":  # Sync ends the extended batch
                self._ext_failed = False
                self._ready()
            else:
                self._error(f"unknown message {tag!r}")
                self._ready()

    # -- extended protocol ---------------------------------------------------

    @staticmethod
    def _cstr(body: bytes, off: int) -> tuple[str, int]:
        end = body.index(b"\x00", off)
        return body[off:end].decode("utf-8", "replace"), end + 1

    def _extended(self, tag: bytes, body: bytes) -> None:
        if tag == b"P":  # Parse: name, query, param-type oids
            name, off = self._cstr(body, 0)
            query, off = self._cstr(body, off)
            self._stmts[name.encode()] = query
            self._send(b"1", b"")  # ParseComplete
        elif tag == b"B":  # Bind: portal, stmt, formats, params
            portal, off = self._cstr(body, 0)
            stmt, off = self._cstr(body, off)
            nfmt = struct.unpack_from("!H", body, off)[0]
            fmts = struct.unpack_from("!%dH" % nfmt, body, off + 2)
            off += 2 + 2 * nfmt
            nparams = struct.unpack_from("!H", body, off)[0]
            off += 2
            params: list[str | None] = []
            for i in range(nparams):
                plen = struct.unpack_from("!i", body, off)[0]
                off += 4
                if plen < 0:
                    params.append(None)
                    continue
                fmt = fmts[i] if i < len(fmts) else (
                    fmts[0] if len(fmts) == 1 else 0)
                if fmt != 0:
                    raise ValueError(
                        "binary parameter format is not supported "
                        "(send text format)"
                    )
                params.append(body[off:off + plen].decode("utf-8"))
                off += plen
            # trailing result-format codes: binary results are not
            # implemented — reject loudly rather than sending text bytes
            # a binary-mode client would decode as garbage
            if off + 2 <= len(body):
                nrf = struct.unpack_from("!H", body, off)[0]
                rfmts = struct.unpack_from("!%dH" % nrf, body, off + 2)
                if any(f != 0 for f in rfmts):
                    raise ValueError(
                        "binary result format is not supported "
                        "(request text format)"
                    )
            sql = self._stmts.get(stmt.encode())
            if sql is None:
                raise ValueError(f"unknown prepared statement {stmt!r}")
            self._portals[portal.encode()] = _inline_params(sql, params)
            self._send(b"2", b"")  # BindComplete
        elif tag == b"D":  # Describe 'S'|'P' + name
            kind, name = body[:1], body[1:].rstrip(b"\x00")
            sql = (self._stmts.get(name) if kind == b"S"
                   else self._portals.get(name))
            if sql is None:
                raise ValueError(f"unknown {kind!r} to describe: {name!r}")
            nparams = _count_placeholders(sql)
            if kind == b"S":
                # ParameterDescription is mandatory for statement
                # describes; oid 0 = unspecified (clients send text)
                self._send(b"t", struct.pack("!H", nparams)
                           + struct.pack("!I", 0) * nparams)
                # plan the schema with placeholders as NULLs
                sql = _inline_params(sql, [None] * nparams)
            schema = self._plan_schema(sql)
            if schema is None:
                self._send(b"n", b"")  # NoData (DML/DDL)
            else:
                names, dtypes = schema
                self._row_description(names, dtypes)
        elif tag == b"E":  # Execute: portal, row limit (ignored: full)
            portal, off = self._cstr(body, 0)
            sql = self._portals.get(portal.encode())
            if sql is None:
                raise ValueError(f"unknown portal {portal!r}")
            # extended-protocol Execute sends DataRows WITHOUT a
            # RowDescription (clients got it from Describe). The inlined
            # text reaches Session.execute, where sql/plancache.py
            # re-parameterizes it — so Parse-once/Bind-many clients hit
            # the prepared-plan cache on every rebind: no re-plan, no new
            # XLA compiles (the inlined literals rebind as jit arguments).
            self._run_query(sql, send_row_desc=False)
        elif tag == b"C":  # Close 'S'|'P' + name
            kind, name = body[:1], body[1:].rstrip(b"\x00")
            (self._stmts if kind == b"S" else self._portals).pop(name, None)
            self._send(b"3", b"")  # CloseComplete

    def _plan_schema(self, sql: str):
        """(names, dtypes) for a SELECT by BINDING (not running) it —
        Describe must answer before Execute. Non-SELECTs: None (NoData)."""
        from ..coldata.types import Family as F
        from ..sql import parser as P
        from ..sql.binder import Binder

        try:
            stmt = P.parse_statement(sql)
        except Exception:  # crlint: allow-broad-except(describe-time parse failure means no row description, not an error)
            return None
        if not isinstance(stmt, P.Select):
            return None
        rel = Binder(self.session.catalog).bind(stmt)
        dtypes = []
        for t in rel.schema.types:
            if t.family is F.BOOL:
                dtypes.append(np.dtype(np.bool_))
            elif t.family in (F.INT, F.DATE):
                dtypes.append(np.dtype(np.int64))
            elif t.family in (F.FLOAT, F.DECIMAL):
                dtypes.append(np.dtype(np.float64))
            else:
                dtypes.append(np.dtype(object))
        return list(rel.schema.names), dtypes


_NUMERIC_PARAM = re.compile(r"^-?\d+(\.\d+)?$")
_PLACEHOLDER = re.compile(r"\$(\d+)")
_SQL_LITERAL = re.compile(r"'(?:[^']|'')*'")


def _outside_literals(sql: str):
    """Yield (is_literal, segment) pairs — $n inside a quoted SQL string
    is literal text, never a placeholder."""
    last = 0
    for m in _SQL_LITERAL.finditer(sql):
        yield False, sql[last:m.start()]
        yield True, m.group(0)
        last = m.end()
    yield False, sql[last:]


def _count_placeholders(sql: str) -> int:
    return max(
        (int(m.group(1))
         for lit, seg in _outside_literals(sql) if not lit
         for m in _PLACEHOLDER.finditer(seg)),
        default=0,
    )


def _inline_params(sql: str, params: list) -> str:
    """Substitute $1..$n with SQL literals (text-format params): numeric-
    looking values inline bare (placeholder type inference by value
    shape — the reference infers from context; divergence documented),
    strings quote with '' escaping, None becomes NULL. ONE regex pass
    over the NON-LITERAL segments only — sequential replacement would
    re-substitute placeholders appearing inside parameter values, and a
    '$n' inside a quoted literal is just text."""
    def lit(m: re.Match) -> str:
        i = int(m.group(1))
        if not 1 <= i <= len(params):
            raise ValueError(f"no parameter bound for ${i}")
        v = params[i - 1]
        if v is None:
            return "null"
        if _NUMERIC_PARAM.match(v):
            return v
        if v.lower() in ("true", "false"):
            return v.lower()
        return "'" + v.replace("'", "''") + "'"

    return "".join(
        seg if is_lit else _PLACEHOLDER.sub(lit, seg)
        for is_lit, seg in _outside_literals(sql)
    )


def _sqlstate_for(e: Exception) -> str:
    from ..kv.txn import TransactionRetryError
    from ..storage.lsm import WriteIntentError
    from ..utils.errors import AdmissionRejectedError, QueryError

    if isinstance(e, QueryError) and e.__cause__ is not None:
        return _sqlstate_for(e.__cause__)
    if isinstance(e, (TransactionRetryError, WriteIntentError)):
        return "40001"  # serialization_failure: clients retry
    if isinstance(e, AdmissionRejectedError):
        # insufficient_resources class: the node is shedding load (queue
        # full / rate limit / overload). The message carries the
        # retry-after hint; clients back off instead of hammering
        return "53300"
    return "XX000"


class PgServer:
    """Accept loop: one thread + one Session per connection."""

    def __init__(self, catalog=None, db=None, host: str = "127.0.0.1",
                 port: int = 0, session_factory=None):
        if session_factory is None:
            if db is not None:
                # bootstrap the shared catalog ONCE; per-connection
                # sessions reuse it without re-scanning descriptors
                boot = Session(catalog=catalog, db=db)
                catalog, db = boot.catalog, boot.db
            self._factory = lambda: Session(catalog=catalog, db=db,
                                            bootstrap=False)
        else:
            self._factory = session_factory
        self._srv = socket.create_server((host, port))
        self.addr = self._srv.getsockname()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def serve_background(self) -> "PgServer":
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()
        return self

    def _serve(self) -> None:
        from ..utils import log, metric

        self._srv.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return

            def run(c=conn):
                try:
                    _Conn(c, self._factory()).serve()
                except (ConnectionError, OSError):
                    pass  # client went away: its problem, not the server's
                except Exception as e:  # crlint: allow-broad-except(connection thread: failure logged, socket closed in finally)
                    log.warning(log.OPS, "pgwire connection failed",
                                error=f"{type(e).__name__}: {e}")
                finally:
                    c.close()

            metric.PG_CONNS.inc()
            threading.Thread(target=run, daemon=True).start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self._srv.close()
