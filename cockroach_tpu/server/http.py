"""HTTP admin API — the pkg/server status/admin endpoint reduction.

Reference: pkg/server serves the db-console's data plane over HTTP —
`/_status/vars` (prometheus text exposition), `/health`, `/_status/nodes`
(node liveness + metadata, api_v2*.go), `/_status/jobs`, and timeseries
queries (pkg/ts/server.go). The TypeScript console itself is out of scope
(SURVEY §2.7: "keep HTTP JSON APIs first"); this module is those APIs over
the Node's subsystems, so an operator can curl the same surfaces.

Endpoints (all GET):
  /health             -> {"nodeId": N, "isLive": bool}  (healthz alias too)
  /_status/vars       -> prometheus text (utils/metric Registry.scrape)
  /_status/nodes      -> {"nodes": [liveness records + epoch + liveness]}
  /_status/jobs       -> {"jobs": [job records]}
  /_status/settings   -> {"settings": {name: value}}
  /ts/query?name=&start=&end= -> {"datapoints": [[ts_ms, value], ...]}

Built on http.server (stdlib) with a daemon thread per server; the Node
owns start/stop. One handler class per Node instance via a closure so two
nodes in one process (tests) never share state.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..utils import log, metric, settings

# minimal db-console (the TypeScript console stays out of scope; this
# single self-contained page renders the SAME status APIs an operator
# would curl, so the HTTP surface is demonstrably sufficient for a UI)
_CONSOLE_HTML = b"""<!doctype html><html><head>
<meta charset="utf-8"><title>cockroach_tpu console</title>
<style>
 body{font:14px ui-monospace,monospace;margin:2em;background:#fafafa}
 h1{font-size:18px} h2{font-size:15px;margin-top:1.4em}
 table{border-collapse:collapse} td,th{border:1px solid #ccc;
 padding:3px 9px;text-align:left} .ok{color:#06792e}.bad{color:#b00020}
 pre{background:#f0f0f0;padding:8px;max-height:300px;overflow:auto}
</style></head><body>
<h1>cockroach_tpu node console</h1>
<div id="health"></div>
<h2>nodes</h2><table id="nodes"></table>
<h2>jobs</h2><table id="jobs"></table>
<h2>statements</h2><table id="stmts"></table>
<h2>contention</h2><table id="cont"></table>
<h2>memory / load</h2><table id="load"></table>
<h2>metrics (/_status/vars)</h2><pre id="vars"></pre>
<script>
async function j(p){return (await fetch(p)).json()}
function mvar(text,name){
 const m=text.match(new RegExp('^'+name+' ([0-9.eE+-]+)$','m'));
 return m?Number(m[1]):0;
}
function mib(n){return (n/1048576).toFixed(1)+' MiB'}
async function refresh(){
 const h=await j('/health');
 document.getElementById('health').innerHTML=
  `node ${h.nodeId}: <b class="${h.isLive?'ok':'bad'}">`+
  `${h.isLive?'LIVE':'NOT LIVE'}</b>`+
  (h.diskSlow!==undefined?` | disk p99 ${h.diskWriteP99Ms}ms`+
   (h.diskSlow?' <b class="bad">SLOW</b>':''):'');
 const ns=(await j('/_status/nodes')).nodes;
 document.getElementById('nodes').innerHTML=
  '<tr><th>id</th><th>epoch</th><th>live</th></tr>'+ns.map(n=>
  `<tr><td>${n.nodeId}</td><td>${n.epoch}</td><td>${n.isLive}</td></tr>`
  ).join('');
 const js=(await j('/_status/jobs')).jobs;
 document.getElementById('jobs').innerHTML=
  '<tr><th>id</th><th>type</th><th>state</th><th>node</th></tr>'+
  js.map(x=>`<tr><td>${x.id}</td><td>${x.type}</td>`+
  `<td>${x.state}</td><td>${x.claimNode}</td></tr>`).join('');
 const ss=(await j('/_status/statements')).statements.slice(0,15);
 document.getElementById('stmts').innerHTML=
  '<tr><th>fingerprint</th><th>count</th><th>mean ms</th>'+
  '<th>rows</th><th>errors</th></tr>'+ss.map(s=>
  `<tr><td>${s.fingerprint.slice(0,70)}</td><td>${s.count}</td>`+
  `<td>${s.meanMs}</td><td>${s.rows}</td><td>${s.errors}</td></tr>`
  ).join('');
 const ce=(await j('/_status/contention')).events.slice(0,10);
 document.getElementById('cont').innerHTML=
  '<tr><th>key</th><th>count</th><th>waiters</th></tr>'+ce.map(e=>
  `<tr><td>${e.key}</td><td>${e.count}</td>`+
  `<td>${e.numWaiters}</td></tr>`).join('');
 const vt=await (await fetch('/_status/vars')).text();
 document.getElementById('load').innerHTML=
  '<tr><th>sql mem current</th><th>sql mem max</th>'+
  '<th>admission slots in use</th><th>queue depth</th></tr>'+
  `<tr><td>${mib(mvar(vt,'sql_mem_current'))}</td>`+
  `<td>${mib(mvar(vt,'sql_mem_max'))}</td>`+
  `<td>${mvar(vt,'admission_sql_slots_in_use')}`+
  ` / ${mvar(vt,'admission_sql_slots')}</td>`+
  `<td>${mvar(vt,'admission_sql_queue_depth')}</td></tr>`;
 document.getElementById('vars').textContent=vt;
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""


from ..utils.errors import retry_past_intents as _status_read  # noqa: E402


def load_payload(node=None) -> dict:
    """The /_status/load body: the node's resource plane in one JSON —
    memory-monitor tree, physical device stats, admission queue state and
    live session/query counts. Module-level (not an AdminServer method) so
    debug zip can capture it without a running server."""
    from ..flow import memory
    from ..sql import activity
    from ..utils import admission

    q = admission.sql_queue()
    out = {
        "memory": {
            "currentBytes": memory.ROOT.used,
            "peakBytes": memory.ROOT.high_water,
            "rootBudgetBytes": memory.root_budget(),
            "pressure": round(memory.mem_pressure(), 4),
            "queryLeaks": memory.drain_failure_count(),
            "monitors": memory.monitor_rows(),
        },
        "device": memory.device_memory_stats(),
        "admission": {
            "slots": q.slots,
            "slotsInUse": q.in_use,
            "queueDepth": q.queue_depth,
            "maxQueueDepth": q.max_queue_depth,
            "admitted": q.admitted,
            "waited": q.waited,
            "timeouts": q.timeouts,
            "rejected": q.rejected,
            "rejectionsByReason": dict(q.rejections_by_reason),
            "laneQueueDepth": q.lane_depths(),
            "shedFloor": admission.shed_floor(),
            "tenants": q.tenant_rows(),
        },
        "activity": {
            "sessions": len(activity.sessions()),
            "activeQueries": len(activity.queries()),
        },
    }
    if node is not None:
        out["nodeId"] = node.node_id
    return out


class AdminServer:
    """HTTP admin endpoint bound to one Node. serve_background() returns
    after bind so the caller knows the port; close() joins the thread."""

    def __init__(self, node, host: str = "127.0.0.1", port: int = 0):
        self.node = node
        self.host = host
        self.port = port
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    # -- endpoint payloads (plain methods: unit-testable without sockets) ----

    def health(self) -> dict:
        n = self.node
        try:
            live = n.liveness.is_live(n.node_id)
        except Exception:  # crlint: allow-broad-except(liveness probe failure IS the not-live answer)
            live = False
        out = {"nodeId": n.node_id, "isLive": bool(live)}
        disk = getattr(n, "disk", None)
        if disk is not None:
            out["diskSlow"] = disk.is_slow()
            out["diskWriteP99Ms"] = round(disk.p99_ms(), 2)
        return out

    def nodes(self) -> dict:
        now = self.node.db.clock.now()
        out = []
        # liveness computed from the records just read — no per-node
        # re-read (each would retake the engine mutex)
        for rec in self.node.liveness.livenesses():
            out.append({
                "nodeId": rec.node_id,
                "epoch": rec.epoch,
                "expiration": rec.expiration,
                "isLive": rec.live_at(now),
            })
        return {"nodes": out}

    def jobs(self) -> dict:
        out = []
        for j in _status_read(self.node.jobs.jobs):
            out.append({
                "id": j.job_id,
                "type": j.job_type,
                "state": j.state,
                "claimNode": j.claim_node,
                "claimEpoch": j.claim_epoch,
            })
        return {"jobs": out}

    def statements(self) -> dict:
        from ..sql import sqlstats

        return {"statements": sqlstats.DEFAULT.rows_payload()}

    def vars(self) -> str:
        """Prometheus text exposition (/_status/vars body)."""
        return metric.DEFAULT.scrape()

    def contention(self) -> dict:
        from ..kv.contention import DEFAULT as _cont

        return {"events": _cont.rows_payload()}

    def diagnostics(self) -> dict:
        """Statement diagnostics ring listing (newest first)."""
        from ..sql import diagnostics as diag

        return {"bundles": diag.bundles()}

    def diagnostics_bundle(self, bundle_id: int) -> dict | None:
        from ..sql import diagnostics as diag

        return diag.get(bundle_id)

    def spans(self) -> dict:
        """In-flight trace spans (crdb_internal.node_inflight_trace_spans
        over HTTP): everything started but not yet finished, oldest first."""
        from ..utils import tracing

        return {"spans": [
            {"traceId": s.trace_id, "spanId": s.span_id,
             "parentSpanId": s.parent_id, "operation": s.name,
             "startWallMs": int(s.start_wall * 1e3)}
            for s in tracing.inflight()
        ]}

    def settings_payload(self) -> dict:
        return {"settings": {
            name: s.get() for name, s in settings.all_settings().items()
        }}

    def hot_ranges(self) -> dict:
        """Range lifecycle report (the /_status/hotranges role): every
        range with decayed QPS, write-bytes rate, authoritative size and
        leaseholder, hottest first. Without a running RangeLifecycle the
        payload degrades to the bare descriptor table."""
        ranger = getattr(self.node, "ranger", None)
        if ranger is not None:
            return ranger.hot_ranges()
        eng = self.node.db.engine
        meta = getattr(eng, "meta", None)
        if meta is None:
            return {"hotRanges": []}
        return {"hotRanges": [
            {"rangeId": d.range_id,
             "startKey": d.start_key.decode(errors="replace"),
             "endKey": (d.end_key.decode(errors="replace")
                        if d.end_key is not None else None),
             "storeId": d.store_id, "qps": 0.0, "writeBytesRate": 0.0,
             "sizeBytes": None, "leaseholder": None}
            for d in meta.snapshot()
        ]}

    def load(self) -> dict:
        """Resource/serving-load snapshot (/_status/load)."""
        return load_payload(self.node)

    def changefeeds(self) -> dict:
        """Fan-out plane snapshot (/_status/changefeeds): one row per
        rangefeed subscriber — span, frontier, buffered bytes, ladder
        counters — plus the node-wide changefeed staging account."""
        from ..flow import memory as flowmem
        from ..kv import fanout

        mon = flowmem.staging_monitor("changefeed")
        return {
            "subscribers": fanout.subscriber_rows(),
            "buffer_bytes": int(mon.used),
            "buffer_high_water": int(mon.high_water),
        }

    def ts_query(self, name: str, start_ms: int, end_ms: int) -> dict:
        pts = self.node.tsdb.query(name, start_ms=start_ms, end_ms=end_ms)
        return {"name": name,
                "datapoints": [[int(t), float(v)] for t, v in pts]}

    # -- plumbing ------------------------------------------------------------

    def _make_handler(self):
        admin = self

        class Handler(BaseHTTPRequestHandler):
            # StreamRequestHandler.setup() applies this as the socket
            # timeout for every request read: a client that connects
            # and never sends a request line (or stalls mid-headers)
            # releases its handler thread instead of parking it in
            # recv forever. BaseHTTPRequestHandler maps the timeout to
            # close_connection, so the slot is reclaimed cleanly.
            timeout = 30.0

            # quiet: requests land in the structured log, not stderr
            def log_message(self, fmt, *args):  # noqa: N802
                log.debug(log.OPS, "http " + fmt % args)

            def _reply(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _json(self, obj, code: int = 200) -> None:
                self._reply(code, json.dumps(obj).encode(),
                            "application/json")

            def do_GET(self):  # noqa: N802
                try:
                    u = urlparse(self.path)
                    if u.path in ("/", "/index.html", "/_status/ui"):
                        self._reply(200, _CONSOLE_HTML,
                                    "text/html; charset=utf-8")
                    elif u.path in ("/health", "/healthz"):
                        self._json(admin.health())
                    elif u.path == "/_status/vars":
                        self._reply(200, admin.vars().encode(),
                                    "text/plain; version=0.0.4")
                    elif u.path == "/_status/nodes":
                        self._json(admin.nodes())
                    elif u.path == "/_status/jobs":
                        self._json(admin.jobs())
                    elif u.path == "/_status/settings":
                        self._json(admin.settings_payload())
                    elif u.path == "/_status/statements":
                        self._json(admin.statements())
                    elif u.path in ("/hot_ranges", "/_status/hot_ranges"):
                        self._json(admin.hot_ranges())
                    elif u.path == "/_status/contention":
                        self._json(admin.contention())
                    elif u.path == "/_status/diagnostics":
                        q = parse_qs(u.query)
                        bid = (q.get("id") or [""])[0]
                        if bid:
                            full = admin.diagnostics_bundle(int(bid))
                            if full is None:
                                self._json({"error": f"no bundle {bid}"},
                                           404)
                            else:
                                self._json(full)
                        else:
                            self._json(admin.diagnostics())
                    elif u.path == "/_status/spans":
                        self._json(admin.spans())
                    elif u.path == "/_status/load":
                        self._json(admin.load())
                    elif u.path == "/_status/changefeeds":
                        self._json(admin.changefeeds())
                    elif u.path == "/ts/query":
                        q = parse_qs(u.query)
                        name = (q.get("name") or [""])[0]
                        if not name:
                            self._json({"error": "name required"}, 400)
                            return
                        start = int((q.get("start") or ["0"])[0])
                        end = int((q.get("end") or [str(1 << 62)])[0])
                        self._json(admin.ts_query(name, start, end))
                    else:
                        self._json({"error": f"unknown path {u.path}"}, 404)
                except BrokenPipeError:
                    pass  # client went away mid-reply
                except Exception as e:  # crlint: allow-broad-except(one bad request never kills serving; error is reported to the client)
                    try:
                        self._json({"error": f"{type(e).__name__}: {e}"}, 500)
                    except OSError:
                        pass  # client also gone mid-error-reply

        return Handler

    def serve_background(self) -> "AdminServer":
        self._httpd = ThreadingHTTPServer(
            (self.host, self.port), self._make_handler()
        )
        self._httpd.daemon_threads = True
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"admin-http-n{self.node.node_id}", daemon=True,
        )
        self._thread.start()
        log.info(log.OPS, "admin http serving", port=self.port)
        return self

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
