"""Table statistics — the pkg/sql/stats reduction.

Reference: CREATE STATISTICS / the automatic stats collector sample tables
into TableStatistic protos (row count, distinct count, null count, and
histograms per column, pkg/sql/stats/new_stat.go); the optimizer's
statistics builder consumes them for cardinality estimates
(pkg/sql/opt/memo/statistics_builder.go). Here ANALYZE computes exact
single-pass statistics (the tables are columnar and resident — sampling
buys nothing at this scale) and three planner consumers read them:

- join ordering starts from the largest estimated source
  (sql/binder.py Source.base_rows);
- the distribute planner's broadcast-join threshold compares estimated
  rows (plan/distribute.py estimated_rows);
- exact packed join keys derive bit widths from (lo, hi) bounds
  (ops/join.plan_exact_key via Table.col_stats).

Statistics are DELIBERATELY stale-able: they snapshot at ANALYZE time and
perturbing them changes plans without changing data — exactly the
reference's contract (and what the stats tests assert).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field

import numpy as np


HIST_BUCKETS = 32


@dataclass
class ColumnStat:
    lo: int | None = None  # min over non-NULL rows (int-represented cols)
    hi: int | None = None
    ndv: int = 0  # distinct non-NULL values
    null_count: int = 0
    # equi-depth histogram (statistics_builder.go's histogram role):
    # hist_bounds[i] is the UPPER bound (inclusive) of bucket i, ascending;
    # hist_counts[i] is that bucket's row count
    hist_bounds: list | None = None
    hist_counts: list | None = None

    def frac_le(self, v: int) -> float:
        """Estimated fraction of non-NULL rows with value <= v."""
        if self.lo is None or self.hi is None:
            return 0.5
        if v < self.lo:
            return 0.0
        if v >= self.hi:
            return 1.0
        if self.hist_bounds:
            total = sum(self.hist_counts)
            acc = 0.0
            prev_hi = self.lo - 1
            for b, c in zip(self.hist_bounds, self.hist_counts):
                if v >= b:
                    acc += c
                    prev_hi = b
                else:
                    # linear interpolation inside the bucket
                    width = max(1, b - prev_hi)
                    acc += c * min(1.0, max(0.0, (v - prev_hi) / width))
                    break
            return min(1.0, acc / max(1, total))
        return (v - self.lo + 1) / max(1, self.hi - self.lo + 1)

    def cmp_fraction(self, op: str, v: int) -> float:
        """Estimated selected fraction for `col <op> v` (eq lt le gt ge),
        over non-NULL rows — the statistics_builder selectivity role."""
        if op == "eq":
            if self.lo is not None and not self.lo <= v <= self.hi:
                return 0.0
            return 1.0 / max(1, self.ndv)
        if op == "le":
            return self.frac_le(v)
        if op == "lt":
            return self.frac_le(v - 1)
        if op == "ge":
            return 1.0 - self.frac_le(v - 1)
        if op == "gt":
            return 1.0 - self.frac_le(v)
        return 1.0


@dataclass
class TableStats:
    row_count: int
    cols: dict[str, ColumnStat] = field(default_factory=dict)
    created_unix: float = 0.0

    def to_json(self) -> str:
        return json.dumps({
            "row_count": self.row_count,
            "created_unix": self.created_unix,
            "cols": {
                n: [c.lo, c.hi, c.ndv, c.null_count]
                for n, c in self.cols.items()
            },
            "hists": {
                n: [c.hist_bounds, c.hist_counts]
                for n, c in self.cols.items() if c.hist_bounds
            },
        }, separators=(",", ":"))

    @staticmethod
    def from_json(s: str) -> "TableStats":
        d = json.loads(s)
        st = TableStats(
            row_count=d["row_count"],
            created_unix=d.get("created_unix", 0.0),
            cols={
                n: ColumnStat(lo, hi, ndv, nc)
                for n, (lo, hi, ndv, nc) in d["cols"].items()
            },
        )
        for n, (bounds, counts) in d.get("hists", {}).items():
            st.cols[n].hist_bounds = bounds
            st.cols[n].hist_counts = counts
        return st


def _equi_depth_hist(live: np.ndarray) -> tuple[list, list]:
    """Equi-depth histogram over sorted int values: ~HIST_BUCKETS buckets,
    each holding ~n/HIST_BUCKETS rows; bounds are inclusive upper edges."""
    v = np.sort(live.astype(np.int64))
    n = len(v)
    per = max(1, n // HIST_BUCKETS)
    bounds: list[int] = []
    counts: list[int] = []
    start = 0
    while start < n:
        end = min(n, start + per)
        b = int(v[end - 1])
        # a bucket must end at a value boundary or equal values straddle
        # buckets and frac_le double-counts
        while end < n and int(v[end]) == b:
            end += 1
        bounds.append(b)
        counts.append(end - start)
        start = end
    return bounds, counts


def analyze_table(table) -> TableStats:
    """One exact pass over host columns -> TableStats. Works for both host
    Tables and KVTables (duck-typed on .schema/.columns/.valids)."""
    from ..coldata.types import Family

    n = table.num_rows
    st = TableStats(row_count=int(n), created_unix=time.time())
    if hasattr(table, "columns") and isinstance(table.columns, dict):
        columns = {k: np.asarray(v) for k, v in table.columns.items()}
        valids = {
            k: np.asarray(v) for k, v in table.valids.items()
        } if table.valids else {}
    else:
        # KVTable: statistics live in the RAW storage domain (scaled
        # DECIMALs, dictionary codes) — the same domain col_stats feeds to
        # exact-key planning — so read the columnar batch, not to_host
        b = table.device_batch()
        mask = np.asarray(b.mask)
        columns = {
            name: np.asarray(col.data)[mask]
            for name, col in zip(table.schema.names, b.cols)
        }
        valids = {
            name: np.asarray(col.valid)[mask]
            for name, col in zip(table.schema.names, b.cols)
        }
    for name, t in zip(table.schema.names, table.schema.types):
        a = columns[name]
        cs = ColumnStat()
        v = valids.get(name)
        if v is not None:
            cs.null_count = int((~v).sum())
            live = a[v]
        elif a.dtype == object:
            isnull = np.array([x is None for x in a])
            cs.null_count = int(isnull.sum())
            live = a[~isnull]
        else:
            live = a
        if len(live):
            if live.dtype == object:
                cs.ndv = int(len(set(live.tolist())))
            else:
                cs.ndv = int(len(np.unique(live)))
            # STRING columns keep dictionary-CODE bounds (the pre-ANALYZE
            # catalog stats include them and exact-key/sort packing relies
            # on them; dropping bounds here would make ANALYZE degrade
            # string-key plans)
            if (t.family not in (Family.BYTES, Family.JSON,
                                 Family.FLOAT, Family.BOOL)
                    and live.dtype != object
                    and np.issubdtype(live.dtype, np.integer)):
                cs.lo = int(live.min())
                cs.hi = int(live.max())
                if cs.ndv > 1:
                    cs.hist_bounds, cs.hist_counts = _equi_depth_hist(live)
        st.cols[name] = cs
    return st


# -- persistence for KV-backed tables (system keyspace) ----------------------
# system.table_statistics role: JSON chunked across rows so statistics fit
# any engine value width (the descriptor-chunking discipline)

_STATS_PREFIX = b"\x01stat"


def _stats_key(table_id: int, chunk: int) -> bytes:
    return _STATS_PREFIX + b"%06d.%04d" % (table_id, chunk)


def save_kv_stats(db, table_id: int, st: TableStats) -> None:
    from ..kv.chunked import chunk_blob

    blob = st.to_json().encode("utf-8")
    step = max(16, db.engine.val_width - 1)
    # length-headered chunks (kv/chunked.py): stale tail chunks from a
    # longer previous version are ignored on read — no delete pass needed
    for ci, piece in enumerate(chunk_blob(blob, step)):
        db.put(_stats_key(table_id, ci), piece)


def load_kv_stats(db, table_id: int) -> TableStats | None:
    from ..kv.chunked import unchunk

    rows = db.scan(_stats_key(table_id, 0), _stats_key(table_id, 9999))
    if not rows:
        return None
    return TableStats.from_json(unchunk([v for _, v in rows]).decode("utf-8"))
