"""Table statistics — the pkg/sql/stats reduction.

Reference: CREATE STATISTICS / the automatic stats collector sample tables
into TableStatistic protos (row count, distinct count, null count, and
histograms per column, pkg/sql/stats/new_stat.go); the optimizer's
statistics builder consumes them for cardinality estimates
(pkg/sql/opt/memo/statistics_builder.go). Here ANALYZE computes exact
single-pass statistics (the tables are columnar and resident — sampling
buys nothing at this scale) and three planner consumers read them:

- join ordering starts from the largest estimated source
  (sql/binder.py Source.base_rows);
- the distribute planner's broadcast-join threshold compares estimated
  rows (plan/distribute.py estimated_rows);
- exact packed join keys derive bit widths from (lo, hi) bounds
  (ops/join.plan_exact_key via Table.col_stats).

Statistics are DELIBERATELY stale-able: they snapshot at ANALYZE time and
perturbing them changes plans without changing data — exactly the
reference's contract (and what the stats tests assert).
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field

import numpy as np


@dataclass
class ColumnStat:
    lo: int | None = None  # min over non-NULL rows (int-represented cols)
    hi: int | None = None
    ndv: int = 0  # distinct non-NULL values
    null_count: int = 0


@dataclass
class TableStats:
    row_count: int
    cols: dict[str, ColumnStat] = field(default_factory=dict)
    created_unix: float = 0.0

    def to_json(self) -> str:
        return json.dumps({
            "row_count": self.row_count,
            "created_unix": self.created_unix,
            "cols": {
                n: [c.lo, c.hi, c.ndv, c.null_count]
                for n, c in self.cols.items()
            },
        }, separators=(",", ":"))

    @staticmethod
    def from_json(s: str) -> "TableStats":
        d = json.loads(s)
        return TableStats(
            row_count=d["row_count"],
            created_unix=d.get("created_unix", 0.0),
            cols={
                n: ColumnStat(lo, hi, ndv, nc)
                for n, (lo, hi, ndv, nc) in d["cols"].items()
            },
        )


def analyze_table(table) -> TableStats:
    """One exact pass over host columns -> TableStats. Works for both host
    Tables and KVTables (duck-typed on .schema/.columns/.valids)."""
    from ..coldata.types import Family

    n = table.num_rows
    st = TableStats(row_count=int(n), created_unix=time.time())
    if hasattr(table, "columns") and isinstance(table.columns, dict):
        columns = {k: np.asarray(v) for k, v in table.columns.items()}
        valids = {
            k: np.asarray(v) for k, v in table.valids.items()
        } if table.valids else {}
    else:
        # KVTable: statistics live in the RAW storage domain (scaled
        # DECIMALs, dictionary codes) — the same domain col_stats feeds to
        # exact-key planning — so read the columnar batch, not to_host
        b = table.device_batch()
        mask = np.asarray(b.mask)
        columns = {
            name: np.asarray(col.data)[mask]
            for name, col in zip(table.schema.names, b.cols)
        }
        valids = {
            name: np.asarray(col.valid)[mask]
            for name, col in zip(table.schema.names, b.cols)
        }
    for name, t in zip(table.schema.names, table.schema.types):
        a = columns[name]
        cs = ColumnStat()
        v = valids.get(name)
        if v is not None:
            cs.null_count = int((~v).sum())
            live = a[v]
        elif a.dtype == object:
            isnull = np.array([x is None for x in a])
            cs.null_count = int(isnull.sum())
            live = a[~isnull]
        else:
            live = a
        if len(live):
            if live.dtype == object:
                cs.ndv = int(len(set(live.tolist())))
            else:
                cs.ndv = int(len(np.unique(live)))
            # STRING columns keep dictionary-CODE bounds (the pre-ANALYZE
            # catalog stats include them and exact-key/sort packing relies
            # on them; dropping bounds here would make ANALYZE degrade
            # string-key plans)
            if (t.family not in (Family.BYTES, Family.JSON,
                                 Family.FLOAT, Family.BOOL)
                    and live.dtype != object
                    and np.issubdtype(live.dtype, np.integer)):
                cs.lo = int(live.min())
                cs.hi = int(live.max())
        st.cols[name] = cs
    return st


# -- persistence for KV-backed tables (system keyspace) ----------------------
# system.table_statistics role: JSON chunked across rows so statistics fit
# any engine value width (the descriptor-chunking discipline)

_STATS_PREFIX = b"\x01stat"


def _stats_key(table_id: int, chunk: int) -> bytes:
    return _STATS_PREFIX + b"%06d.%04d" % (table_id, chunk)


def save_kv_stats(db, table_id: int, st: TableStats) -> None:
    from ..kv.chunked import chunk_blob

    blob = st.to_json().encode("utf-8")
    step = max(16, db.engine.val_width - 1)
    # length-headered chunks (kv/chunked.py): stale tail chunks from a
    # longer previous version are ignored on read — no delete pass needed
    for ci, piece in enumerate(chunk_blob(blob, step)):
        db.put(_stats_key(table_id, ci), piece)


def load_kv_stats(db, table_id: int) -> TableStats | None:
    from ..kv.chunked import unchunk

    rows = db.scan(_stats_key(table_id, 0), _stats_key(table_id, 9999))
    if not rows:
        return None
    return TableStats.from_json(unchunk([v for _, v in rows]).decode("utf-8"))
