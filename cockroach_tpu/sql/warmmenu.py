"""Ahead-of-time kernel menu — kill the cold wall before readiness.

Reference: a fresh CockroachDB node serves its first query at full speed
because the execution engine is interpreted; a TPU-native engine instead
pays 3-10s of XLA compilation per query SHAPE the first time it is seen.
PR 6's cache hierarchy made repeats free (process-global kernel cache,
plan cache, on-disk XLA cache); this module moves the remaining
first-ever cost off the serving path entirely: at server start, BEFORE
the node advertises readiness (server/node.py calls :func:`warm_node`
ahead of its "node started" line), a bounded background pool compiles an
ahead-of-time *menu* of kernels into the same process-global
``flow/dispatch.jit`` cache the serving path reads.

The menu has three courses, warmed in value order:

1. **explicit** — statements handed in by the operator/test harness;
2. **hot** — sqlstats-ranked statement texts from the plan cache's
   fingerprint->text store (``PlanCache.hot_texts``): what THIS node's
   workload actually runs, learned across restarts via sqlstats;
3. **ladder** — synthesized per-table statements covering the canonical
   shape ladder (``catalog.SHAPE_BUCKETS``) times the fused-pipeline
   operator templates from ``flow/fuse.py`` (filter/project chain,
   scalar aggregate, grouped aggregate, top-k): because every table pads
   to a ladder rung and kernels key on (template, rung), warming one
   table per shape warms every future query of that shape.

Each item executes twice on a private background session — the first
run compiles, the second settles adaptive capacities — exactly the
discipline scripts/check_recompiles.py holds the serving path to, so a
post-menu first execution of a menu-shaped query compiles 0 new kernels.

Bounded: ``sql.warmup.menu.budget_s`` caps wall time and
``sql.warmup.menu.max_kernels`` caps minted compilations; items past
either bound are recorded as ``skipped``. Best-effort: a failed item
(chaos site ``sql.warmup.compile``) is recorded as ``failed`` and the
kernel compiles on first use instead — the menu never blocks readiness
beyond its budget and never fails startup.

Accounting surfaces: ``sql_warmup_kernels_compiled`` /
``sql_warmup_menu_hits`` metrics and the
``crdb_internal.node_warmup_menu`` vtable (one row per menu item with
status, kernels, seconds, and serving-path hits).
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

from ..coldata.types import Family
from ..utils import faults, locks, log, metric, settings

__all__ = ["build_menu", "warm_node", "menu_rows", "note_serving_hit",
           "reset", "MenuRun"]

# bounded background pool: enough to overlap XLA compiles, small enough
# that startup never starves the machine the node is about to serve on
_POOL_SIZE = 2

# fused-pipeline operator templates (flow/fuse.py _CHAIN/_CONSUMERS
# shapes): scan->filter->project, scalar-aggregate spool, grouped
# aggregate, and the top-k consumer — the chains every ladder-shaped
# query decomposes into. {t}/{c} bind per table below.
_TEMPLATES = (
    ("filter", "select {c} from {t} where {c} >= 0"),
    ("scalar_agg", "select sum({c}) from {t}"),
    ("group_agg", "select {c}, sum({c}) from {t} group by {c}"),
    ("topk", "select {c} from {t} order by {c} limit 16"),
)

# menu registry (vtable + hit accounting): fingerprint -> row dict.
# Guarded by a named control-plane lock; the serving path touches it
# once per plan-cache hit (note_serving_hit).
_mu = locks.lock("sql.warmmenu")
_MENU: dict[str, dict] = {}


@dataclass
class _Item:
    text: str
    source: str  # 'explicit' | 'hot' | 'ladder'


class MenuRun:
    """Handle on one menu build: join it, or stop it early (node
    shutdown racing a budget-bound warmup)."""

    def __init__(self):
        self.stop = threading.Event()
        self.threads: list[threading.Thread] = []

    def join(self, timeout: float | None = None) -> None:
        for t in self.threads:
            if t is not threading.current_thread():
                t.join(timeout)

    def stop_join(self, timeout: float = 5.0) -> None:
        self.stop.set()
        self.join(timeout)


def reset() -> None:
    """Drop menu state (test isolation)."""
    with _mu:
        _MENU.clear()


def menu_rows() -> list[dict]:
    """Snapshot of the menu registry for crdb_internal.node_warmup_menu
    (insertion order = warm order)."""
    with _mu:
        return [dict(r) for r in _MENU.values()]


def warmed_fingerprints() -> set[str]:
    with _mu:
        return {fp for fp, r in _MENU.items() if r["status"] == "compiled"}


def note_serving_hit(fingerprint: str) -> None:
    """Called by the plan cache on a serving-path hit: if the menu
    compiled this fingerprint, the cold wall was paid at startup — count
    it. Warmup threads' own executions never count."""
    if threading.current_thread().name.startswith(
            ("warm-menu", "plan-warmup")):
        return
    with _mu:
        row = _MENU.get(fingerprint)
        if row is None or row["status"] != "compiled":
            return
        row["hits"] += 1
    metric.SQL_WARMUP_MENU_HITS.inc()


def _record(item: _Item, status: str, kernels: int, seconds: float) -> None:
    from . import sqlstats

    fp = sqlstats.fingerprint(item.text)
    with _mu:
        row = _MENU.get(fp)
        if row is None:
            _MENU[fp] = {
                "fingerprint": fp, "source": item.source, "status": status,
                "kernels": int(kernels), "seconds": float(seconds),
                "hits": 0,
            }
        elif status == "compiled" and row["status"] != "compiled":
            # a retry/duplicate that compiled upgrades the row
            row.update(status=status, kernels=int(kernels),
                       seconds=float(seconds))


def _ladder_statements(catalog) -> list[str]:
    """One table per ladder rung x every operator template. Kernels key
    on (template, rung), so warming the first table padded to a rung
    warms every same-rung table; skipping the rest keeps the menu
    O(|SHAPE_BUCKETS| x |templates|) no matter how wide the catalog is."""
    from ..catalog import _bucket_cap

    out: list[str] = []
    rung_done: set[int] = set()
    for name in sorted(catalog.tables):
        if name.startswith("__") or name.startswith("crdb_internal."):
            continue
        t = catalog.tables[name]
        try:
            rows = t.num_rows
        except (StopIteration, KeyError, ValueError):
            continue  # descriptor-only / torn table: nothing to warm
        rung = _bucket_cap(rows)
        if rung in rung_done:
            continue
        ints = [c for c, ty in zip(t.schema.names, t.schema.types)
                if ty.family is Family.INT]
        if not ints:
            continue
        rung_done.add(rung)
        c = ints[0]
        for _, tmpl in _TEMPLATES:
            out.append(tmpl.format(t=name, c=c))
    return out


def build_menu(catalog, db, statements=None, block: bool = True
               ) -> MenuRun | None:
    """Compile the AOT kernel menu for ``catalog``/``db`` on a bounded
    background pool. Returns the :class:`MenuRun` handle (already joined
    when ``block``, the server-start mode) or None when disabled or the
    menu is empty. Never raises: warmup is best-effort by contract."""
    if not settings.get("sql.warmup.menu.enabled"):
        return None
    from . import plancache
    from .session import Session

    items: list[_Item] = []
    seen: set[str] = set()

    def add(text: str, source: str) -> None:
        if text and text not in seen:
            seen.add(text)
            items.append(_Item(text, source))

    for t in (statements or ()):
        add(t, "explicit")
    for t in plancache.cache_for(catalog).hot_texts():
        add(t, "hot")
    for t in _ladder_statements(catalog):
        add(t, "ladder")
    if not items:
        return None

    budget_s = settings.get("sql.warmup.menu.budget_s")
    max_kernels = settings.get("sql.warmup.menu.max_kernels")
    deadline = (time.monotonic() + budget_s) if budget_s > 0 else None
    run = MenuRun()
    pending = list(items)
    plock = locks.lock("sql.warmmenu.pending")
    from ..flow import dispatch

    k0 = dispatch.compiles()
    t_start = time.monotonic()

    def _worker(sess) -> None:
        try:
            while not run.stop.is_set():
                with plock:
                    if not pending:
                        return
                    item = pending.pop(0)
                over_budget = (
                    (deadline is not None and time.monotonic() >= deadline)
                    or dispatch.compiles() - k0 >= max_kernels)
                if over_budget:
                    _record(item, "skipped", 0, 0.0)
                    continue
                c0 = dispatch.compiles()
                t0 = time.perf_counter()
                try:
                    # chaos site: an AOT compile failing at startup must
                    # degrade to compile-on-first-use, never block
                    # readiness (see utils/faults.py SITES)
                    faults.fire("sql.warmup.compile")
                    # twice, like plancache.start_warmup: run 1 compiles,
                    # run 2 settles adaptive capacities so the serving
                    # repeat is pure dispatch
                    sess.execute(item.text)
                    if run.stop.is_set():
                        _record(item, "skipped", dispatch.compiles() - c0,
                                time.perf_counter() - t0)
                        return
                    sess.execute(item.text)
                except Exception:  # noqa: BLE001  # crlint: allow-broad-except(warmup is best-effort: a failed menu item is recorded and served cold on first use)
                    _record(item, "failed", dispatch.compiles() - c0,
                            time.perf_counter() - t0)
                    continue
                kn = dispatch.compiles() - c0
                if kn > 0:
                    metric.SQL_WARMUP_KERNELS_COMPILED.inc(kn)
                _record(item, "compiled", kn, time.perf_counter() - t0)
        finally:
            sess.close()

    n = min(_POOL_SIZE, len(items))
    for i in range(n):
        # PRIVATE per-worker sessions over the shared catalog/store,
        # constructed HERE (not in the thread): session bootstrap touches
        # engine state that only the spawning thread may initialize
        sess = Session(catalog=catalog, db=db, bootstrap=False)
        th = threading.Thread(target=_worker, args=(sess,),
                              name=f"warm-menu-{i}", daemon=True)
        run.threads.append(th)
        th.start()
    if block:
        # readiness gate: wait out the budget (plus a statement-boundary
        # grace), then tell stragglers to stop at their next boundary
        remain = (None if deadline is None
                  else max(0.0, deadline - time.monotonic()) + 5.0)
        run.join(remain)
        run.stop.set()
        rows = menu_rows()
        compiled = sum(1 for r in rows if r["status"] == "compiled")
        log.info(log.SQL_EXEC, "warm menu built",
                 items=len(rows), compiled=compiled,
                 kernels=dispatch.compiles() - k0,
                 seconds=round(time.monotonic() - t_start, 3))
    return run


def warm_node(node) -> MenuRun | None:
    """Server-start entry (server/node.py): warm the node's SQL catalog
    over its store before the node advertises readiness. The returned
    handle is stashed on the node so shutdown can stop a budget-bound
    straggler at its next statement boundary."""
    catalog = getattr(node, "_sql_catalog", None)
    if catalog is None:
        return None
    run = build_menu(catalog, node.db, block=True)
    node._warmmenu_run = run
    return run
