"""Prepared-plan cache — the zero-recompile serving path (L2 of the cache
hierarchy; see README "Compile-avoidance cache hierarchy").

Reference shape: pkg/sql's query cache (plan_opt.go / querycache) keys
memoized plans on statement + placeholder types + catalog descriptor
versions, so the conn executor skips optbuild on repeat statements. Here
the expensive phase is not optimization but the build->fuse->XLA-compile
pipeline, so the cache holds the BUILT operator tree:

- ``parameterize`` rewrites numeric literals in Filter predicates into
  ``ex.Param`` slots, so a repeat statement with different literals maps
  to the same structural plan; the values are rebound per execution as
  jit ARGUMENTS (ops/expr.param_scope), never retraced.
- ``plan_key`` derives a stable structural key from the parameterized
  plan (frozen dataclasses all the way down). Anything it cannot key
  byte-stably (runtime-filled dictionaries, unknown objects) raises
  ``_Unkeyable`` and the statement simply is not cached — conservative
  misses, never wrong hits.
- Entries are LRU-bounded (``sql.plan_cache.size``) and keyed on the
  catalog schema version + the settings signature, so DDL (CREATE/DROP
  INDEX, ALTER) and tuning changes can never serve a stale plan; the
  session's DDL handlers additionally sweep dead-version entries out
  eagerly (``invalidate``).
- A per-entry lock serializes concurrent sessions through one entry:
  operator trees hold mutable pull state, so two sessions never drive
  the same tree at once (they queue; distinct statements run in
  parallel).

Execution-stats collection (EXPLAIN ANALYZE / the cluster setting)
bypasses the cache: stats need a fresh per-operator tree, and cached
trees deliberately skip the instrumented path.
"""

from __future__ import annotations

import dataclasses
import enum
import threading
from collections import OrderedDict

import numpy as np

from ..coldata.batch import Dictionary
from ..coldata.types import Family
from ..ops import expr as ex
from ..plan import builder as plan_builder
from ..plan import spec as S
from ..utils import metric, settings, tracing

# literal families rewritten into Param slots: everything whose device
# representation is a plain numeric scalar. STRING stays literal (string
# predicates lower to host-built CodeLookup tables — content-keyed), BOOL
# stays literal (structural TRUE/FALSE branches), NULL stays literal (its
# valid-mask shape differs from any bound value)
_PARAM_FAMILIES = (Family.INT, Family.FLOAT, Family.DECIMAL, Family.DATE,
                   Family.TIMESTAMP, Family.INTERVAL)


class _Unkeyable(Exception):
    """The plan holds an object with no stable structural key; the
    statement runs uncached (conservative — a miss is always correct)."""


class ParamStore:
    """Positional parameter values for one cached plan, shared by every
    operator the plan's builder created with ``params=``.

    ``args()`` is re-read at each run's ``stream_parts`` fetch, so
    rebinding values between runs flows into the jitted kernels as fresh
    arguments — dtypes are pinned per slot at parameterize time, so no
    value change can force a retrace."""

    def __init__(self, types):
        self._types = tuple(types)
        self._values: tuple | None = None

    def set_values(self, values) -> None:
        if len(values) != len(self._types):
            raise ValueError(
                f"expected {len(self._types)} parameter values, "
                f"got {len(values)}")
        out = []
        for v, t in zip(values, self._types):
            if t.family is Family.DECIMAL:
                # the same host-side fixed-point scaling Const evaluation
                # applies (ops/expr.py) — device kernels see scaled ints
                v = int(round(float(v) * 10 ** t.scale))
            out.append(np.asarray(v, dtype=t.dtype))
        self._values = tuple(out)

    def args(self) -> tuple:
        if self._values is None:
            raise RuntimeError("ParamStore.args() before set_values()")
        return self._values


def parameterize(plan):
    """Rewrite numeric Filter-predicate literals into Param slots.

    Returns ``(pplan, values, types)``: the parameterized plan (shared
    across every statement with the same shape), the extracted literal
    values in slot order, and their SQL types. Runs AFTER index
    selection (plan/indexopt.py), so IndexScan lo/hi bounds stay
    literal — different index bounds are different plans by design."""
    values: list = []
    types: list = []

    def walk_expr(e):
        if isinstance(e, ex.Const):
            if (e.value is not None
                    and e.type.family in _PARAM_FAMILIES
                    and not isinstance(e.value, (tuple, list, np.ndarray))):
                p = ex.Param(len(values), e.type)
                values.append(e.value)
                types.append(e.type)
                return p
            return e
        if isinstance(e, ex.CodeLookup) or not isinstance(e, ex.Expr):
            return e
        if isinstance(e, ex.Func2) and e.func == "round2":
            # round2's digit count is read with .value at trace time
            # ("binder guarantees a literal") — it must stay a Const
            left = walk_expr(e.left)
            return (e if left is e.left
                    else dataclasses.replace(e, left=left))
        changes = {}
        for f in dataclasses.fields(e):
            v = getattr(e, f.name)
            nv = walk_field(v)
            if nv is not v:
                changes[f.name] = nv
        return dataclasses.replace(e, **changes) if changes else e

    def walk_field(v):
        if isinstance(v, ex.Expr):
            return walk_expr(v)
        if isinstance(v, tuple):
            nv = tuple(walk_field(i) for i in v)
            return nv if any(a is not b for a, b in zip(nv, v)) else v
        return v

    def walk_plan(n):
        if not dataclasses.is_dataclass(n):
            return n
        changes = {}
        for f in dataclasses.fields(n):
            v = getattr(n, f.name)
            if isinstance(n, S.Filter) and f.name == "predicate":
                nv = walk_expr(v)
            elif isinstance(v, S.PlanNode):
                nv = walk_plan(v)
            elif (isinstance(v, tuple) and v
                    and isinstance(v[0], S.PlanNode)):
                nv = tuple(walk_plan(i) for i in v)
                if not any(a is not b for a, b in zip(nv, v)):
                    nv = v
            else:
                nv = v
            if nv is not v:
                changes[f.name] = nv
        return dataclasses.replace(n, **changes) if changes else n

    return walk_plan(plan), tuple(values), tuple(types)


def plan_key(pplan):
    """Stable structural key of a (parameterized) plan tree. Raises
    ``_Unkeyable`` for objects without byte-stable content."""
    return _key_of(pplan)


def _key_of(x):
    if x is None or isinstance(x, (bool, int, float, str, bytes)):
        return x
    if isinstance(x, enum.Enum):
        return ("enum", type(x).__name__, x.name)
    if isinstance(x, np.generic):
        return ("np", str(x.dtype), x.item())
    if isinstance(x, np.ndarray):
        return ("nd", str(x.dtype), x.shape, x.tobytes())
    if isinstance(x, ex.CodeLookup):
        # eq=False dataclass (identity semantics for jit keys); the plan
        # key compares the host table's CONTENT so two binds of the same
        # string predicate share an entry
        t = np.asarray(x.table)
        return ("codelookup", x.col, _key_of(x.out_type), str(t.dtype),
                t.shape, t.tobytes())
    if isinstance(x, Dictionary):
        if getattr(x, "_runtime", False):
            raise _Unkeyable("runtime-filled dictionary")
        return ("dict", tuple(str(v) for v in x.values))
    if isinstance(x, (tuple, list)):
        return ("seq", tuple(_key_of(i) for i in x))
    if dataclasses.is_dataclass(x):
        return ((type(x).__name__,)
                + tuple(_key_of(getattr(x, f.name))
                        for f in dataclasses.fields(x)))
    raise _Unkeyable(type(x).__name__)


def _table_names(plan) -> list[str]:
    names: set[str] = set()

    def walk(n):
        if isinstance(n, (S.TableScan, S.IndexScan)):
            names.add(n.table)
        for f in ("input", "probe", "build"):
            c = getattr(n, f, None)
            if c is not None:
                walk(c)
        for c in getattr(n, "inputs", ()) or ():
            walk(c)

    walk(plan)
    return sorted(names)


def _dict_gen(catalog, plan) -> tuple:
    """Per-table string-dictionary generations (column -> value count).
    Built operators capture dictionary SNAPSHOTS (flow/operators.py
    _wire_source_metadata), so an INSERT that mints a new string value
    must re-key the plan — decoding through the stale snapshot would
    mislabel the new codes. Row-count changes alone keep hitting."""
    return _dict_gen_for(catalog, _table_names(plan))


def _dict_gen_for(catalog, names) -> tuple:
    out = []
    for name in names:
        t = catalog.tables.get(name)
        if t is None:
            continue
        d = t.dictionaries  # KVTable property returns fresh snapshots
        out.append((name, tuple(sorted(
            (c, len(dd.values)) for c, dd in d.items()))))
    return tuple(out)


def _settings_sig() -> tuple:
    """Current values of every registered setting. Conservative: ANY
    settings change re-keys the cache (a stale tile size or fusion mode
    must never serve), at the cost of misses on unrelated toggles."""
    reg = settings.all_settings()
    return tuple((n, str(reg[n].get())) for n in sorted(reg))


class _Entry:
    __slots__ = ("root", "store", "version", "fingerprint", "lock", "hits")

    def __init__(self, root, store, version, fingerprint):
        self.root = root
        self.store = store
        self.version = version
        self.fingerprint = fingerprint
        self.lock = threading.Lock()
        self.hits = 0


class PlanCache:
    """Size-capped LRU of built plans, one per Catalog (``cache_for``).
    ``hits``/``misses`` counters are per-cache (tests); the process
    metrics (sql_plan_cache_*) aggregate across catalogs."""

    def __init__(self):
        self._entries: OrderedDict = OrderedDict()
        self._lock = threading.Lock()
        self._texts: OrderedDict = OrderedDict()  # fingerprint -> last text
        self._memo: OrderedDict = OrderedDict()   # exact text -> (key, values)
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def lookup(self, key):
        with self._lock:
            e = self._entries.get(key)
            if e is None:
                self.misses += 1
                metric.PLAN_CACHE_MISSES.inc()
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            e.hits += 1
            metric.PLAN_CACHE_HITS.inc()
            return e

    def peek(self, key):
        with self._lock:
            return self._entries.get(key)

    def insert(self, key, entry) -> "_Entry":
        cap = int(settings.get("sql.plan_cache.size"))
        with self._lock:
            cur = self._entries.get(key)
            if cur is not None:
                return cur  # concurrent first executions: first wins
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > cap:
                self._entries.popitem(last=False)
                self.evictions += 1
                metric.PLAN_CACHE_EVICTIONS.inc()
            return entry

    def invalidate(self, version: int) -> int:
        """Eagerly drop entries built against a dead catalog version
        (DDL). Version is part of the key, so stale entries could never
        HIT again — this sweep just frees them immediately."""
        with self._lock:
            dead = [k for k, e in self._entries.items()
                    if e.version != version]
            for k in dead:
                del self._entries[k]
                self.evictions += 1
                metric.PLAN_CACHE_EVICTIONS.inc()
            self._memo.clear()
            return len(dead)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._memo.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -- exact-text memo (skips parse/bind/optimize on verbatim repeats) --

    _MEMO_CAP = 512

    def memo_get(self, text):
        with self._lock:
            v = self._memo.get(text)
            if v is not None:
                self._memo.move_to_end(text)
            return v

    def memo_put(self, text, key, values, tables) -> None:
        with self._lock:
            self._memo[text] = (key, values, tables)
            self._memo.move_to_end(text)
            while len(self._memo) > self._MEMO_CAP:
                self._memo.popitem(last=False)

    # -- warmup bookkeeping ----------------------------------------------

    _TEXT_CAP = 256

    def note_text(self, fingerprint: str, text: str) -> None:
        with self._lock:
            self._texts[fingerprint] = text
            self._texts.move_to_end(fingerprint)
            while len(self._texts) > self._TEXT_CAP:
                self._texts.popitem(last=False)

    def hot_texts(self, limit: int = 32) -> list[str]:
        """Recorded statement texts for the hottest fingerprints, by the
        sqlstats execution counts (sql/sqlstats.py)."""
        from . import sqlstats

        with self._lock:
            texts = dict(self._texts)
        counts = {s.fingerprint: s.count for s in sqlstats.DEFAULT.all()}
        order = sorted(texts, key=lambda fp: -counts.get(fp, 0))
        return [texts[fp] for fp in order[:limit]]


def cache_for(catalog) -> PlanCache:
    pc = getattr(catalog, "_plan_cache", None)
    if pc is None:
        pc = catalog._plan_cache = PlanCache()
    return pc


# -- the serving path --------------------------------------------------------


def _cacheable() -> bool:
    return (settings.get("sql.plan_cache.enabled")
            and not settings.get("sql.stats.collect_execution_stats"))


_VOLATILE = ("now(", "current_date", "current_timestamp")


def _is_virtual_plan(plan) -> bool:
    from . import crdb_internal

    return any(crdb_internal.is_virtual(n) for n in _table_names(plan))


def run_cached(rel, text: str | None = None):
    """Execute a bound Rel through the plan cache; see
    :func:`run_cached_ex` (this keeps the original 2-tuple shape)."""
    res, status, _ = run_cached_ex(rel, text)
    return res, status


def run_cached_ex(rel, text: str | None = None):
    """Execute a bound Rel through the plan cache.

    Returns ``(results, status, fingerprint)`` with status one of ``hit``
    (literals rebound into a cached tree, zero new builds), ``miss``
    (built fresh and cached), ``uncacheable`` (no stable key), ``bypass``
    (cache off, stats collection on, or crdb_internal virtual tables —
    those materialize fresh per statement, so a cached plan would freeze
    a snapshot). ``fingerprint`` is the serving entry's structural
    fingerprint (the first text that built it — sqlstats uses it so
    literal variants collapse to one row), or '' when no entry served."""
    from ..flow import runtime

    if not _cacheable():
        return rel.run(), "bypass", ""
    maybe_enable_compile_cache()
    cache = cache_for(rel.catalog)
    plan = rel.optimized_plan()
    if _is_virtual_plan(plan):
        return runtime.run_plan(plan, rel.catalog), "bypass", ""
    try:
        with tracing.leaf_span("sql.plancache.lookup"):
            pplan, values, types = parameterize(plan)
            key = (plan_key(pplan), rel.catalog.version, _settings_sig(),
                   _dict_gen(rel.catalog, pplan))
            entry = cache.lookup(key)
    except _Unkeyable:
        return runtime.run_plan(plan, rel.catalog), "uncacheable", ""
    status = "hit"
    if entry is None:
        status = "miss"
        store = ParamStore(types)
        store.set_values(values)
        root = plan_builder.build(pplan, rel.catalog, params=store)
        entry = _Entry(root, store, rel.catalog.version, _fingerprint(text))
        # run BEFORE publishing: a plan whose first execution fails never
        # enters the cache (concurrent first executions may both build;
        # insert keeps whichever published first)
        with entry.lock:
            entry.store.set_values(values)
            with tracing.leaf_span("query", cache="miss"):
                res = runtime.run_operator(entry.root)
        entry = cache.insert(key, entry)
    else:
        with entry.lock:
            entry.store.set_values(values)
            with tracing.leaf_span("query", cache="hit"):
                res = runtime.run_operator(entry.root)
        if entry.fingerprint:
            # warm-menu hit accounting: a serving-path hit on a statement
            # the AOT menu compiled means the cold wall was paid at start
            from . import warmmenu

            warmmenu.note_serving_hit(entry.fingerprint)
    if text is not None:
        if entry.fingerprint:
            cache.note_text(entry.fingerprint, text)
        low = text.lower()
        if not any(tok in low for tok in _VOLATILE):
            # verbatim repeats can skip parse/bind next time; statements
            # with per-bind folded volatiles (now()) must re-bind
            cache.memo_put(text, key, values, tuple(_table_names(pplan)))
    return res, status, entry.fingerprint


def run_memoized(catalog, text: str):
    """Exact-text fast path; see :func:`run_memoized_ex` (this keeps the
    original results-or-None shape)."""
    m = run_memoized_ex(catalog, text)
    return None if m is None else m[0]


def run_memoized_ex(catalog, text: str):
    """Exact-text fast path: if this verbatim statement ran before and
    its entry is still live (same catalog version + settings), execute it
    without parsing or binding. Returns (results, entry fingerprint) or
    None (fall through to the normal path)."""
    from ..flow import runtime

    if not _cacheable():
        return None
    cache = cache_for(catalog)
    m = cache.memo_get(text)
    if m is None:
        return None
    key, values, tables = m
    # key embeds (version, settings sig, dict gens); ALL must still hold
    # — the entry itself may still live under the old key, so a stale
    # dictionary generation has to be rejected here, not left to lookup
    if (key[1] != catalog.version or key[2] != _settings_sig()
            or key[3] != _dict_gen_for(catalog, tables)):
        return None
    entry = cache.lookup(key)
    if entry is None:
        return None
    if entry.fingerprint:
        # the memo path is still a plan-cache hit — warm-menu accounting
        # must see it, or menu-compiled statements that repeat verbatim
        # (the common serving shape) would never count as menu hits
        from . import warmmenu

        warmmenu.note_serving_hit(entry.fingerprint)
    with entry.lock:
        entry.store.set_values(values)
        with tracing.leaf_span("query", cache="memo"):
            return runtime.run_operator(entry.root), entry.fingerprint


def probe(rel) -> str:
    """Cache status a statement WOULD see, without executing — the
    EXPLAIN ANALYZE "plan cache:" line (stats collection itself always
    runs the instrumented fresh tree)."""
    if not settings.get("sql.plan_cache.enabled"):
        return "disabled"
    if _is_virtual_plan(rel.optimized_plan()):
        return "uncacheable"
    try:
        pplan, _, _ = parameterize(rel.optimized_plan())
        key = (plan_key(pplan), rel.catalog.version, _settings_sig(),
               _dict_gen(rel.catalog, pplan))
    except _Unkeyable:
        return "uncacheable"
    hit = cache_for(rel.catalog).peek(key) is not None
    return "hit" if hit else "miss"


def _fingerprint(text: str | None) -> str:
    if text is None:
        return ""
    from . import sqlstats

    return sqlstats.fingerprint(text)


# -- L3: on-disk XLA compilation cache ---------------------------------------

_compile_cache_on = False


def maybe_enable_compile_cache() -> None:
    """Idempotently turn on JAX's persistent compilation cache when
    ``sql.compile_cache.enabled`` is set — process restarts then reload
    executables from disk instead of recompiling the kernel fleet."""
    global _compile_cache_on
    if _compile_cache_on or not settings.get("sql.compile_cache.enabled"):
        return
    from ..utils.backend import enable_compile_cache

    enable_compile_cache(settings.get("sql.compile_cache.dir") or None)
    _compile_cache_on = True


# -- background pre-warming --------------------------------------------------


def start_warmup(session, statements=None) -> threading.Thread | None:
    """Re-execute hot statements on a background session so their plans
    and kernel specializations are compiled OFF the serving path (after
    process start or a DDL invalidation). Gated on
    ``sql.plan_cache.warmup.enabled``; returns the daemon thread (join it
    in tests) or None when disabled / nothing to warm.

    Replaying the hottest recorded statement texts warms every level at
    once: the plan cache entry, each kernel at its current canonical
    tile shape (catalog.SHAPE_BUCKETS keeps that menu small), and — when
    enabled — the on-disk XLA cache.

    Lifecycle: the thread checks a stop event between statements and the
    owning session joins it in ``close()`` (via :func:`stop_warmup`), so
    a warmup racing server shutdown stops at the next statement boundary
    instead of executing against a torn-down store — the no-leak census
    asserts no ``plan-warmup`` thread survives teardown. Re-invalidation
    (back-to-back DDL) stops the previous warmup before starting the
    next, so at most one warmup thread exists per session."""
    if not settings.get("sql.plan_cache.warmup.enabled"):
        return None
    texts = (list(statements) if statements is not None
             else cache_for(session.catalog).hot_texts())
    if not texts:
        return None
    from .session import Session

    # one warmup per session: a DDL burst must not stack threads
    stop_warmup(session)
    # a PRIVATE session over the shared catalog/store: the warmup thread
    # must never touch the serving session's transaction state
    bg = Session(catalog=session.catalog, db=session.db, bootstrap=False)
    stop = threading.Event()

    def _run():
        try:
            for t in texts:
                if stop.is_set():
                    return
                try:
                    # twice: the first execution compiles; the second
                    # settles adaptive capacities (join emission caps learn
                    # from run 1 and re-specialize once), so the SERVING
                    # repeat is pure dispatch — scripts/check_recompiles.py
                    # holds it to zero
                    bg.execute(t)
                    if stop.is_set():
                        return
                    bg.execute(t)
                except Exception:  # noqa: BLE001 — warmup is best-effort
                    continue
        finally:
            bg.close()

    th = threading.Thread(target=_run, name="plan-warmup", daemon=True)
    session._warmup_stop = stop
    session._warmup_thread = th
    th.start()
    return th


def stop_warmup(session, timeout: float = 5.0) -> None:
    """Signal and join the session's warmup thread (idempotent; no-op
    when none is running). Called from Session.close() and before a new
    warmup replaces a running one."""
    th = getattr(session, "_warmup_thread", None)
    if th is None:
        return
    stop = getattr(session, "_warmup_stop", None)
    if stop is not None:
        stop.set()
    if th is not threading.current_thread():
        th.join(timeout=timeout)
    session._warmup_thread = None
    session._warmup_stop = None
