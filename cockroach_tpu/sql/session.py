"""SQL session — DDL/DML execution over the KV layer (the conn-executor
analog, reduced to statement dispatch).

Reference shape: pkg/sql/conn_executor.go:2323 runs statements through the
planner; INSERT/UPDATE/DELETE encode rows and write through kv.Txn
(pkg/sql/insert.go, kv/txn.go), DDL creates descriptors. Here:

- CREATE TABLE registers a KVTable (storage/rowcodec row encoding, engine-
  backed, MVCC reads) in the catalog;
- INSERT VALUES / INSERT ... SELECT encode rows and put them inside ONE
  kv transaction (atomic: every row or none, write intents + commit);
- UPDATE/DELETE plan their WHERE through the same binder/engine as SELECT
  (a columnar scan computes the affected rows), then write the new
  versions / tombstones transactionally;
- SELECT returns columns through the standard bind/execute path.

Divergences (documented): no schema changes after creation, single-node
descriptors (table ids allocated locally), and writes materialize the
affected rows on the host before re-encoding (no vectorized write path
yet — the reference's colenc).
"""

from __future__ import annotations

import numpy as np

from ..catalog import Catalog
from ..coldata import types as T
from ..kv import DB, Clock
from ..kv.table import KVTable, create_kv_table
from ..kv.txn import TransactionRetryError
from ..storage.lsm import WriteIntentError
from ..storage import rowcodec
from ..storage.lsm import Engine
from . import parser as P
from .binder import BindError, Binder, ExprLowerer
from .rel import Rel

_TYPE_MAP = {
    "int": T.INT64, "integer": T.INT64, "bigint": T.INT64,
    "int8": T.INT64, "int4": T.INT32, "smallint": T.INT16,
    "float": T.FLOAT64, "double": T.FLOAT64, "real": T.FLOAT64,
    "float8": T.FLOAT64, "date": T.DATE, "timestamp": T.TIMESTAMP,
    "interval": T.INTERVAL, "bool": T.BOOL, "boolean": T.BOOL,
}


class NotALiteral(BindError):
    """The expression is not a constant (it references columns)."""


def _col_type(c: P.ColumnDef) -> T.SQLType:
    tn = c.type_name
    if tn in ("decimal", "numeric"):
        return T.DECIMAL(c.precision or 19,
                         c.scale if c.scale is not None else 2)
    if tn in ("string", "text", "varchar", "char"):
        return T.STRING
    t = _TYPE_MAP.get(tn)
    if t is None:
        raise BindError(f"unknown column type {tn!r}")
    return t


class Session:
    """One SQL session over one KV store. execute() returns:
    - SELECT: dict[str, np.ndarray] of result columns
    - INSERT/UPDATE/DELETE: {"rows_affected": n}
    - CREATE TABLE: {"created": name}
    """

    def __init__(self, catalog: Catalog | None = None, db: DB | None = None,
                 val_width: int = 128, key_width: int = 24,
                 bootstrap: bool = True, tenant: str | None = None):
        """bootstrap=False skips the catalog rediscovery scan — for servers
        (pgwire) that bootstrap the shared catalog ONCE and hand every
        connection's session the prebuilt one (re-running the descriptor
        scan per connection would replace live KVTable objects under
        concurrently executing sessions).

        tenant: run this session AS the named tenant over the shared KV
        store (kv/tenant.py) — catalog discovery and table creation are
        confined to the tenant's table-id range, and capability checks
        gate CREATE TABLE / BACKUP. None = the unscoped legacy session
        (system-tenant powers, no restrictions)."""
        self.catalog = catalog if catalog is not None else Catalog()
        # key_width must fit the WIDEST key family the session can write:
        # secondary-index entries are 21 bytes (kv/index.ENTRY_BYTES),
        # so the default is 24 (next multiple of 8), not the 16 a bare
        # primary-key session would need
        self.db = db if db is not None else DB(
            Engine(key_width=key_width, val_width=val_width,
                   memtable_size=4096),
            Clock(),
        )
        self.tenant = None
        if tenant is not None:
            from ..kv.tenant import TenantRegistry

            reg = TenantRegistry(self.db)
            reg.bootstrap()
            self.tenant = reg.get(tenant)
            # admission capabilities bind here: a tenant carrying
            # admission_rate / admission_burst / admission_weight caps
            # gets its token bucket / fair-share weight configured past
            # the cluster defaults (tenant rate-limiter shape)
            caps = self.tenant.caps
            if any(k in caps for k in ("admission_rate", "admission_burst",
                                       "admission_weight")):
                from ..utils import admission as _adm

                _adm.sql_queue().configure_tenant(
                    self.tenant.tenant_id,
                    rate=caps.get("admission_rate"),
                    burst=caps.get("admission_burst"),
                    weight=caps.get("admission_weight"))
        if db is not None and bootstrap:
            # opening over an existing store: rediscover persisted tables
            # from their descriptors (the catalog bootstrap path), plus any
            # persisted ANALYZE statistics (system.table_statistics role)
            from ..kv.table import load_catalog_from_engine

            load_catalog_from_engine(
                self.catalog, self.db,
                id_range=(None if self.tenant is None
                          else (self.tenant.id_lo, self.tenant.id_hi)),
            )
            from . import stats as stats_mod

            for tbl in self.catalog.tables.values():
                if isinstance(tbl, KVTable):
                    st = stats_mod.load_kv_stats(self.db, tbl.table_id)
                    if st is not None:
                        tbl.set_stats(st)
        # explicit-transaction state machine: NoTxn (_txn None) / Open /
        # Aborted (_txn_aborted — only ROLLBACK/COMMIT leave it)
        self._txn = None
        self._txn_aborted = False
        # observability plumbing: the live-session registry entry, plus
        # the handles crdb_internal builders reach through the catalog
        from . import activity

        self._session_id = activity.register_session()
        self._active_qid = None
        self._last_fp = None
        self.catalog._crdb_db = self.db
        # this session's node in the memory-monitor tree: statements open
        # query monitors under it, so the session's used/peak aggregate
        # every statement's operator accounts (mon.BytesMonitor session
        # tier)
        from ..flow import memory as flowmem

        self._mem_mon = flowmem.session_monitor(
            f"session-{self._session_id}")

    def close(self) -> None:
        """Drop this session from the live registry (idempotent; a session
        that is never closed falls off the registry's bounded end). Joins
        the background plan-warmup thread first: a warmup racing teardown
        must stop at its next statement boundary, not execute against a
        closed store."""
        from . import activity, plancache

        plancache.stop_warmup(self)
        activity.deregister_session(self._session_id)
        self._mem_mon.close()

    def _set_phase(self, phase: str) -> None:
        if self._active_qid is not None:
            from . import activity

            activity.set_phase(self._active_qid, phase)

    # -- dispatch ------------------------------------------------------------

    def execute(self, text: str):
        handled = self._maybe_txn_stmt(text)
        if handled is not None:
            return handled
        if self._txn_aborted:
            raise BindError(
                "current transaction is aborted, commands ignored until "
                "end of transaction block (issue ROLLBACK)"
            )
        import time as _time

        from . import activity, sqlstats
        from ..flow import memory as flowmem
        from ..utils import admission, tracing

        t0 = _time.perf_counter()
        self._active_qid = activity.begin_query(self._session_id, text)
        self._last_fp = None
        err = False
        sp = None
        qmon = None
        try:
            # admission first (queue-wait is NOT query memory or trace
            # time), then the statement's query monitor under this
            # session's tier, then the root span of the statement's trace:
            # everything below — parse/bind, plan-cache lookup, flow pull,
            # KV batches, WAL appends — nests under them via contextvars
            # the slot request carries this session's tenant, the
            # statement's lane (analytical sheds first under overload),
            # and the statement deadline — queue-wait counts against
            # statement_timeout, so a full queue is a fast typed 53300
            # instead of a silent stall
            with admission.sql_slot(
                    admission.classify_statement(text),
                    tenant_id=(None if self.tenant is None
                               else self.tenant.tenant_id),
                    deadline=self._statement_deadline()), \
                    flowmem.query_scope(self._mem_mon) as qmon, \
                    tracing.span("sql.execute",
                                 stmt=text.strip()[:120]) as sp:
                out = self._dispatch(text)
        except BaseException:
            # ANY failure inside an explicit block aborts it (postgres /
            # CRDB: subsequent statements are rejected until ROLLBACK)
            err = True
            if self._txn is not None:
                self._txn_aborted = True
            raise
        finally:
            activity.end_query(self._active_qid)
            self._active_qid = None
            elapsed = _time.perf_counter() - t0
            # peak/spills survive the monitor's close (read them off the
            # closed query monitor — the scope exited above)
            mem_peak = getattr(qmon, "high_water", 0)
            mem_spills = getattr(qmon, "spills", 0)
            if err:
                sqlstats.DEFAULT.record(text, elapsed, 0, error=True,
                                        fp=self._last_fp,
                                        mem_bytes=mem_peak,
                                        spills=mem_spills)
                self._maybe_slow_query(text, elapsed, sp, error=True)
        nrows = 0
        if isinstance(out, dict) and out:
            if "rows_affected" in out:  # DML verbs report affected rows
                nrows = int(out["rows_affected"])
            else:
                first = next(iter(out.values()))
                if hasattr(first, "__len__") and not isinstance(first, str):
                    nrows = len(first)
        sqlstats.DEFAULT.record(text, elapsed, nrows, fp=self._last_fp,
                                mem_bytes=mem_peak, spills=mem_spills)
        self._maybe_slow_query(text, elapsed, sp)
        return out

    def _statement_deadline(self) -> float | None:
        """time.monotonic() deadline from the statement_timeout session
        var (milliseconds, postgres convention; 0/unset = none). Handed
        to admission so queue-wait spends the same budget as execution —
        a statement must not wait out its whole timeout in the queue and
        then start running."""
        sv = getattr(self, "_session_vars", None)
        if not sv:
            return None
        try:
            ms = float(sv.get("statement_timeout", 0) or 0)
        except (TypeError, ValueError):
            return None
        if ms <= 0:
            return None
        import time as _time

        return _time.monotonic() + ms / 1e3

    def _maybe_slow_query(self, text: str, elapsed_s: float, span,
                          error: bool = False) -> None:
        """The slow-query log (sql.log.slow_query.latency_threshold, 0 =
        off): past the threshold, log AND capture a diagnostics bundle so
        the slow execution's trace is inspectable after the fact."""
        from ..utils import settings

        thresh = settings.get("sql.log.slow_query.latency_threshold")
        if not thresh or elapsed_s < float(thresh):
            return
        from ..utils import log
        from . import diagnostics

        bundle = diagnostics.capture(
            self, text, elapsed_s=elapsed_s, span=span,
            trigger="slow_query", error=error)
        log.warning(log.SQL_EXEC, "slow query",
                    elapsed_ms=round(elapsed_s * 1e3, 1),
                    bundle=bundle.get("id"), stmt=text.strip()[:120])

    def _dispatch(self, text: str):
        from .binder import begin_statement

        begin_statement()  # now()/current_date fold per statement
        handled = self._maybe_settings_stmt(text)
        if handled is None:
            handled = self._maybe_admin_stmt(text)
        if handled is None:
            handled = self._maybe_session_var_stmt(text)
        if handled is not None:
            return handled
        from . import matview

        handled = matview.maybe_matview_stmt(self, text)
        if handled is not None:
            return handled
        if self._txn is None:
            # standing views refresh BEFORE the plan-cache fast path: a
            # memoized statement over a view must still see the frontier
            # as of statement start (refresh bumps the catalog version,
            # which re-keys any plan the refresh staled)
            matview.refresh_for_text(self.catalog, text)
        if self._txn is None:
            # exact-text fast path: a verbatim repeat SELECT skips even
            # parse/bind and runs its cached prepared plan directly
            from . import plancache

            self._set_phase("executing")
            m = plancache.run_memoized_ex(self.catalog, text)
            if m is not None:
                res, fp = m
                self._last_fp = fp or None
                return res
        self._set_phase("parsing")
        from ..utils import tracing

        with tracing.leaf_span("sql.parse"):
            stmt = P.parse_statement(text)
        if isinstance(stmt, P.Select):
            return self._select(stmt, text)
        if isinstance(stmt, (P.CreateTable, P.AlterTable, P.CreateIndex,
                             P.DropIndex)) and self._txn is not None:
            raise BindError(
                "DDL inside an explicit transaction is not supported"
            )
        if isinstance(stmt, P.CreateTable):
            return self._create_table(stmt)
        if isinstance(stmt, P.AlterTable):
            return self._alter_table(stmt)
        if isinstance(stmt, P.CreateIndex):
            return self._create_index(stmt)
        if isinstance(stmt, P.DropIndex):
            return self._drop_index(stmt)
        if isinstance(stmt, P.Insert):
            return self._insert(stmt)
        if isinstance(stmt, P.Update):
            return self._update(stmt)
        if isinstance(stmt, P.Delete):
            return self._delete(stmt)
        raise BindError(f"unsupported statement {type(stmt).__name__}")

    # session variables (sessiondata vars.go role): drivers SET these at
    # connect time (extra_float_digits, application_name, ...); SET stores
    # any name tolerantly so every driver's startup script succeeds, SHOW
    # answers known vars and stored ones
    _SESSION_VAR_DEFAULTS = {
        "application_name": "",
        "client_encoding": "UTF8",
        "extra_float_digits": "3",
        "search_path": "public",
        "statement_timeout": "0",
        "timezone": "UTC",
        "datestyle": "ISO",
        "vectorize": "on",
        "distsql": "auto",
    }

    def _maybe_session_var_stmt(self, text: str):
        import re as _re

        import numpy as _np

        t = text.strip().rstrip(";")
        m = _re.match(
            r"(?is)^set\s+(?:session\s+)?([a-z_][a-z0-9_]*)\s*"
            r"(?:=|\s+to\s+)\s*(.+)$", t)
        if m and m.group(1).lower() not in ("cluster",):
            name = m.group(1).lower()
            raw = m.group(2).strip().strip("'\"")
            if not hasattr(self, "_session_vars"):
                self._session_vars = {}
            self._session_vars[name] = raw
            if name == "application_name":
                from . import activity

                activity.set_application_name(self._session_id, raw)
            return {"set": name}
        m = _re.match(r"(?is)^show\s+([a-z_][a-z0-9_]*)$", t)
        if m:
            name = m.group(1).lower()
            vars_ = getattr(self, "_session_vars", {})
            if (name not in vars_
                    and name not in self._SESSION_VAR_DEFAULTS):
                raise BindError(f"unrecognized configuration parameter "
                                f"{name!r}")
            val = vars_.get(name, self._SESSION_VAR_DEFAULTS.get(name, ""))
            return {name: _np.array([val], dtype=object)}
        return None

    # -- explicit transactions (the conn_executor txn state machine,
    # reference: pkg/sql/conn_executor.go:2323 + conn_fsm.go, reduced to
    # NoTxn / Open / Aborted) ------------------------------------------------

    def _maybe_txn_stmt(self, text: str):
        import re as _re

        t = text.strip().rstrip(";").lower()
        if _re.match(r"^(begin|start)(\s+transaction)?$", t):
            if self._txn is not None:
                raise BindError("there is already a transaction in progress")
            self._txn = self.db.new_txn()
            self._txn_aborted = False
            return {"begin": True}
        if _re.match(r"^(commit|end)(\s+transaction)?$", t):
            if self._txn is None:
                return {"warning": "there is no transaction in progress"}
            txn, self._txn = self._txn, None
            if self._txn_aborted:
                # COMMIT of an aborted txn rolls back (postgres semantics)
                self._txn_aborted = False
                txn.rollback()
                return {"rollback": True}
            # a commit-time refresh failure rolls back inside commit() and
            # raises the retryable error (CRDB 40001 shape): the client
            # must restart the whole block
            txn.commit()
            return {"commit": True}
        if _re.match(r"^(rollback|abort)(\s+transaction)?$", t):
            if self._txn is None:
                return {"warning": "there is no transaction in progress"}
            txn, self._txn = self._txn, None
            self._txn_aborted = False
            txn.rollback()
            return {"rollback": True}
        return None

    def _run_write(self, op):
        """Run a DML closure: auto-commit via DB.txn retries outside an
        explicit transaction; inside one, run against the session txn with
        NO implicit retry — a retryable conflict surfaces to the client as
        a restart-the-block error and the txn enters the Aborted state
        (the reference cannot replay client-driven statements either).

        The closure's columnar scans (``_affected``) surface foreign
        intents as WriteIntentError; that is the same retryable conflict
        Txn.get/scan convert, so convert it here too — otherwise the
        40001 retry loop every client wraps around blocks never fires."""

        def guarded(txn):
            try:
                return op(txn)
            except WriteIntentError as e:
                raise TransactionRetryError(
                    f"conflicting intent on {e.keys}"
                ) from e

        if self._txn is None:
            return self.db.txn(guarded)
        try:
            return guarded(self._txn)
        except TransactionRetryError:
            self._txn_aborted = True
            raise

    def _read_as(self, txn):
        """Context: KV-backed columnar scans read AT txn's snapshot AS txn
        (own intents visible, foreign intents conflict)."""
        from contextlib import contextmanager

        kv_tables = [t for t in self.catalog.tables.values()
                     if isinstance(t, KVTable)]

        @contextmanager
        def ctx():
            try:
                for t in kv_tables:
                    t.read_ts = txn.read_ts
                    t.reader_txn = txn.txn_id
                yield
            finally:
                for t in kv_tables:
                    t.read_ts = None
                    t.reader_txn = 0

        return ctx()

    def _select(self, stmt: P.Select, text: str | None = None):
        if self._txn is None:
            # the prepared-plan cache path: repeat statements (identical
            # structure, any numeric literals — the pgwire extended
            # protocol's Parse/Bind/Execute shape after literal inlining)
            # rebind into a cached operator tree with zero new compiles
            from ..utils import tracing
            from . import plancache

            self._set_phase("binding")
            with tracing.leaf_span("sql.bind"):
                rel = Binder(self.catalog).bind(stmt)
            # a plan matching a standing view's shape + literals serves
            # from the view's state (autocommit only: an explicit txn
            # reads at ITS snapshot, not the view frontier)
            from . import matview

            rel, _mv = matview.maybe_rewrite(self.catalog, rel)
            self._set_phase("executing")
            res, _, fp = plancache.run_cached_ex(rel, text=text)
            self._last_fp = fp or None
            return res
        # in-txn SELECT: scans read at the txn snapshot, and every scanned
        # table's span lands in the txn's read set for commit-time refresh
        txn = self._txn
        with self._read_as(txn):
            rel = Binder(self.catalog).bind(stmt)
            for t in self._scanned_kv_tables(rel.plan):
                from ..storage import rowcodec as _rc

                start, end = _rc.table_span(t.table_id)
                txn.note_read_span(start, end)
            try:
                return rel.run()
            except WriteIntentError as e:
                self._txn_aborted = True
                raise TransactionRetryError(
                    f"conflicting intent on {e.keys}"
                ) from e

    def _scanned_kv_tables(self, plan):
        """KVTables named by TableScan nodes anywhere in a plan tree."""
        from ..plan import spec as S

        out = []
        if isinstance(plan, S.TableScan):
            t = self.catalog.tables.get(plan.table)
            if isinstance(t, KVTable):
                out.append(t)
        for f in ("input", "probe", "build"):
            child = getattr(plan, f, None)
            if child is not None:
                out.extend(self._scanned_kv_tables(child))
        for child in getattr(plan, "inputs", ()) or ():
            out.extend(self._scanned_kv_tables(child))
        return out

    @staticmethod
    def _maybe_settings_stmt(text: str):
        """SET CLUSTER SETTING name = value / SHOW CLUSTER SETTING[S] — the
        pkg/settings SQL surface (registry.go; settings are SQL-updatable
        in the reference and gossiped; process-local here)."""
        import re as _re

        from ..utils import settings as _settings

        t = text.strip().rstrip(";")
        m = _re.match(
            r"(?is)^set\s+cluster\s+setting\s+([a-z0-9_.]+)\s*=\s*(.+)$", t)
        if m:
            name, raw = m.group(1), m.group(2).strip()
            reg = _settings.all_settings()
            if name not in reg:
                raise BindError(f"unknown cluster setting {name!r}")
            kind = reg[name].kind
            if kind == "bool":
                val = raw.lower() in ("true", "on", "1")
            elif kind == "int":
                val = int(raw)
            elif kind == "float":
                val = float(raw)
            else:
                val = raw.strip("'")
            _settings.set(name, val)
            return {"set": name}
        m = _re.match(r"(?is)^show\s+cluster\s+setting\s+([a-z0-9_.]+)$", t)
        if m:
            name = m.group(1)
            reg = _settings.all_settings()
            if name not in reg:
                raise BindError(f"unknown cluster setting {name!r}")
            import numpy as _np

            return {"variable": _np.array([name], dtype=object),
                    "value": _np.array([str(reg[name].get())], dtype=object)}
        if _re.match(r"(?is)^show\s+cluster\s+settings$", t):
            import numpy as _np

            reg = _settings.all_settings()
            names = sorted(reg)
            return {
                "variable": _np.array(names, dtype=object),
                "value": _np.array([str(reg[n].get()) for n in names],
                                   dtype=object),
            }
        return None

    def _maybe_tenant_stmt(self, t: str):
        """CREATE/DROP/SHOW/ALTER TENANT — the system tenant's DDL surface
        (reference: SQL tenant builtins + tenantcapabilities; reduced to
        the capability grammar the capability set here supports)."""
        import re as _re

        import numpy as _np

        from ..kv.tenant import TenantError, TenantRegistry

        def require_system():
            if self.tenant is not None and self.tenant.name != "system":
                raise TenantError(
                    "tenant DDL requires the system tenant"
                )
            reg = TenantRegistry(self.db)
            reg.bootstrap()
            return reg

        m = _re.match(r"(?is)^create\s+tenant\s+'?([a-z0-9_]+)'?$", t)
        if m:
            rec = require_system().create(m.group(1))
            return {"tenant_id": rec.tenant_id, "name": rec.name}
        m = _re.match(r"(?is)^drop\s+tenant\s+'?([a-z0-9_]+)'?$", t)
        if m:
            require_system().drop(m.group(1))
            return {"dropped": m.group(1)}
        if _re.match(r"(?is)^show\s+tenants$", t):
            recs = require_system().list()
            return {
                "id": _np.array([r.tenant_id for r in recs],
                                dtype=_np.int64),
                "name": _np.array([r.name for r in recs], dtype=object),
                "capabilities": _np.array(
                    [",".join(f"{k}={v}" for k, v in sorted(r.caps.items()))
                     for r in recs], dtype=object),
            }
        m = _re.match(
            r"(?is)^alter\s+tenant\s+'?([a-z0-9_]+)'?\s+"
            r"(grant|revoke)\s+capability\s+([a-z0-9_]+)$", t)
        if m:
            cap = m.group(3).lower()
            if cap not in ("can_create_table", "can_backup"):
                # GRANT/REVOKE writes booleans: numeric caps (max_tables)
                # would silently corrupt
                raise TenantError(f"unknown boolean capability {cap!r}")
            rec = require_system().set_capability(
                m.group(1), cap,
                m.group(2).lower() == "grant",
            )
            return {"tenant": rec.name,
                    m.group(3).lower(): rec.caps[m.group(3).lower()]}
        return None

    def _maybe_admin_stmt(self, text: str):
        """BACKUP TO '<path>' / RESTORE FROM '<path>' / SHOW JOBS — the
        jobs-backed admin surface (BACKUP runs as a job, exactly the
        reference's shape; RESTORE swaps the engine state in from the
        checkpoint and reloads table dictionaries)."""
        import re as _re

        t = text.strip().rstrip(";")
        handled = self._maybe_tenant_stmt(t)
        if handled is not None:
            return handled
        m = _re.match(r"(?is)^backup\s+to\s+'([^']+)'$", t)
        if m:
            if self.tenant is not None:
                from ..kv.tenant import check_capability

                check_capability(self.tenant, "can_backup")
            from ..kv.jobs import Registry, register_builtin_jobs

            reg = self._jobs_registry()
            register_builtin_jobs(reg)
            job = reg.create("backup", {"path": m.group(1)})
            done = reg.adopt_and_resume(job.job_id)
            return {"job_id": done.job_id, "state": done.state}
        m = _re.match(r"(?is)^restore\s+from\s+'([^']+)'$", t)
        if m:
            if self.tenant is not None and self.tenant.name != "system":
                from ..kv.tenant import CapabilityError

                # RESTORE swaps the SHARED engine state — system only
                raise CapabilityError(
                    "RESTORE requires the system tenant (it replaces the "
                    "shared store)"
                )
            from ..storage.lsm import Engine as _Engine
            from ..utils.external_storage import resolve_dir_uri

            eng = _Engine.open_checkpoint(resolve_dir_uri(m.group(1)))
            self.db.engine = eng
            # schemas are data: rebuild the catalog from the restored
            # descriptors (tables created after the backup disappear;
            # tables present in the backup return even into a fresh session)
            from ..kv.table import load_catalog_from_engine

            for name in [n for n, tbl in self.catalog.tables.items()
                         if isinstance(tbl, KVTable)]:
                del self.catalog.tables[name]
            load_catalog_from_engine(self.catalog, self.db)
            self._invalidate_plans()
            return {"restored": m.group(1)}
        if _re.match(r"(?is)^show\s+tables$", t):
            import numpy as _np

            # "__"-prefixed names are engine-internal (the FROM-less
            # SELECT dual relation)
            names = sorted(n for n in self.catalog.tables
                           if not n.startswith("__"))
            return {"table_name": _np.array(names, dtype=object)}
        m = _re.match(r"(?is)^show\s+columns\s+from\s+([a-z0-9_]+)$", t)
        if m:
            import numpy as _np

            tbl = self.catalog.tables.get(m.group(1))
            if tbl is None:
                raise BindError(f"unknown table {m.group(1)!r}")
            return {
                "column_name": _np.array(tbl.schema.names, dtype=object),
                "data_type": _np.array(
                    [str(ty) for ty in tbl.schema.types], dtype=object),
            }
        m = _re.match(
            r"(?is)^(?:analyze|create\s+statistics\s+\w+\s+from)\s+"
            r"([a-z0-9_]+)$", t)
        if m:
            from . import stats as stats_mod

            name = m.group(1)
            tbl = self.catalog.tables.get(name)
            if tbl is None:
                raise BindError(f"unknown table {name!r}")
            st = stats_mod.analyze_table(tbl)
            tbl.set_stats(st)
            if isinstance(tbl, KVTable):
                stats_mod.save_kv_stats(self.db, tbl.table_id, st)
            # cached plans baked the OLD stats into kernel shapes
            # (bit-packed sort keys, broadcast choices) — re-key them
            self._invalidate_plans()
            return {"analyzed": name, "rows": st.row_count}
        m = _re.match(r"(?is)^show\s+statistics\s+for\s+table\s+"
                      r"([a-z0-9_]+)$", t)
        if m:
            import numpy as _np

            tbl = self.catalog.tables.get(m.group(1))
            if tbl is None:
                raise BindError(f"unknown table {m.group(1)!r}")
            st = getattr(tbl, "table_stats", None)
            if st is None:
                return {"column_name": _np.array([], dtype=object)}
            names = list(st.cols)
            return {
                "column_name": _np.array(names, dtype=object),
                "row_count": _np.full(len(names), st.row_count),
                "distinct_count": _np.array(
                    [st.cols[n].ndv for n in names]),
                "null_count": _np.array(
                    [st.cols[n].null_count for n in names]),
            }
        if _re.match(r"(?is)^show\s+ranges$", t):
            import numpy as _np

            descs = []
            meta = getattr(self.db.engine, "meta", None)
            if meta is not None:  # DistSender-backed: real descriptors
                descs = meta.snapshot()
            if descs:
                return {
                    "range_id": _np.array([d.range_id for d in descs]),
                    "start_key": _np.array(
                        [d.start_key.decode("utf-8", "replace")
                         for d in descs], dtype=object),
                    "end_key": _np.array(
                        [(d.end_key.decode("utf-8", "replace")
                          if d.end_key is not None else "") for d in descs],
                        dtype=object),
                    "store_id": _np.array([d.store_id for d in descs]),
                }
            # single-store DB: one whole-keyspace range (store 1)
            return {
                "range_id": _np.array([1]),
                "start_key": _np.array([""], dtype=object),
                "end_key": _np.array([""], dtype=object),
                "store_id": _np.array([1]),
            }
        if _re.match(r"(?is)^show\s+statements$", t):
            import numpy as _np

            from . import sqlstats

            rows = sqlstats.DEFAULT.rows_payload()  # one consistent snapshot
            return {
                "fingerprint": _np.array(
                    [r["fingerprint"] for r in rows], dtype=object),
                "count": _np.array([r["count"] for r in rows]),
                "mean_ms": _np.array([r["meanMs"] for r in rows]),
                "max_ms": _np.array([r["maxMs"] for r in rows]),
                "rows": _np.array([r["rows"] for r in rows]),
                "errors": _np.array([r["errors"] for r in rows]),
            }
        if _re.match(r"(?is)^show\s+contention$", t):
            import numpy as _np

            from ..kv.contention import DEFAULT as _cont

            rows = _cont.rows_payload()
            return {
                "key": _np.array([r["key"] for r in rows], dtype=object),
                "count": _np.array([r["count"] for r in rows]),
                "last_holder_txn": _np.array(
                    [r["lastHolderTxn"] for r in rows]),
                "num_waiters": _np.array([r["numWaiters"] for r in rows]),
            }
        if _re.match(r"(?is)^show\s+jobs$", t):
            import numpy as _np

            reg = self._jobs_registry()
            jobs = reg.jobs()
            return {
                "job_id": _np.array([j.job_id for j in jobs]),
                "job_type": _np.array([j.job_type for j in jobs],
                                      dtype=object),
                "state": _np.array([j.state for j in jobs], dtype=object),
            }
        return None

    def _jobs_registry(self):
        from ..kv.jobs import Registry

        if getattr(self, "_jobs", None) is None:
            self._jobs = Registry(self.db)
        return self._jobs

    # -- DDL -----------------------------------------------------------------

    def _invalidate_plans(self) -> None:
        """Schema-change barrier: bump the catalog version (re-keying every
        cached plan), eagerly sweep the dead entries, and — when
        ``sql.plan_cache.warmup.enabled`` — kick the background warmup
        thread so hot statements recompile off the serving path."""
        from . import plancache

        self.catalog.bump_version()
        plancache.cache_for(self.catalog).invalidate(self.catalog.version)
        plancache.start_warmup(self)

    def _create_table(self, stmt: P.CreateTable):
        if stmt.name.startswith("__"):
            raise BindError(
                "table names starting with '__' are reserved"
            )
        if stmt.name in self.catalog.tables:
            raise BindError(f"table {stmt.name!r} already exists")
        names = tuple(c.name for c in stmt.columns)
        types = tuple(_col_type(c) for c in stmt.columns)
        pks = [c.name for c in stmt.columns if c.primary_key]
        if len(pks) != 1:
            raise BindError("exactly one PRIMARY KEY column is required")
        schema = T.Schema(names, types)
        need = rowcodec.value_width(schema)
        if self.db.engine.val_width < need:
            raise BindError(
                f"row width {need} exceeds engine value width "
                f"{self.db.engine.val_width}; open the Session with "
                f"val_width>={need}"
            )
        id_range = None
        if self.tenant is not None:
            from ..kv.tenant import check_capability

            check_capability(self.tenant, "can_create_table")
            n_tables = sum(1 for t in self.catalog.tables.values()
                           if isinstance(t, KVTable))
            if n_tables >= int(self.tenant.caps.get("max_tables", 1 << 30)):
                from ..kv.tenant import CapabilityError

                raise CapabilityError(
                    f"tenant {self.tenant.name!r} reached its max_tables "
                    f"({self.tenant.caps['max_tables']})"
                )
            id_range = (self.tenant.id_lo, self.tenant.id_hi)
        create_kv_table(self.catalog, self.db, stmt.name, schema,
                        pk=pks[0], id_range=id_range)
        self._invalidate_plans()
        return {"created": stmt.name}

    def _alter_table(self, stmt: P.AlterTable):
        """ALTER TABLE as a schema_change job: validate, create the job,
        run the checkpointed backfill, swap the descriptor (the reference's
        schema changes are jobs for exactly this crash-resume reason)."""
        from .schemachange import plan_alter, register_schema_change_job

        payload = plan_alter(self.catalog, self.db, stmt)
        reg = self._jobs_registry()
        register_schema_change_job(reg, self.catalog)
        job = reg.create("schema_change", payload)
        done = reg.adopt_and_resume(job.job_id)
        if done.state != "succeeded":
            raise BindError(
                f"schema change failed: {done.error or done.state}"
            )
        self._invalidate_plans()
        return {"altered": stmt.name, "job_id": done.job_id}

    def _create_index(self, stmt: P.CreateIndex):
        """CREATE INDEX as a create_index job: chunked checkpointed entry
        backfill, then a fenced descriptor swap (pkg/sql/backfill.go
        discipline, same machinery as ALTER TABLE)."""
        from ..kv.index import plan_create_index, register_create_index_job

        id_range = ((self.tenant.id_lo, self.tenant.id_hi)
                    if self.tenant is not None else None)
        payload = plan_create_index(self.catalog, self.db, stmt,
                                    id_range=id_range)
        reg = self._jobs_registry()
        register_create_index_job(reg, self.catalog)
        job = reg.create("create_index", payload)
        done = reg.adopt_and_resume(job.job_id)
        if done.state != "succeeded":
            raise BindError(
                f"CREATE INDEX failed: {done.error or done.state}"
            )
        self._invalidate_plans()
        return {"created_index": stmt.name, "job_id": done.job_id}

    def _drop_index(self, stmt: P.DropIndex):
        from ..kv.index import drop_index

        t = self._kv_table(stmt.table)
        drop_index(self.catalog, self.db, t.name, stmt.name)
        # a plan cached against the dropped index (IndexScan) must never
        # serve again — the version bump re-keys it out of existence
        self._invalidate_plans()
        return {"dropped_index": stmt.name}

    # -- DML -----------------------------------------------------------------

    def _kv_table(self, name: str) -> KVTable:
        t = self.catalog.tables.get(name)
        if t is None:
            raise BindError(f"unknown table {name!r}")
        if not isinstance(t, KVTable):
            raise BindError(
                f"table {name!r} is a static host table; DML targets "
                "KV-backed tables (CREATE TABLE)"
            )
        return t

    @staticmethod
    def _literal(e: P.Node, t: T.SQLType):
        """Evaluate a literal expression for column type t. Raises
        NotALiteral when the expression references columns (the caller may
        then route it through the engine); genuine validation errors
        (precision overflow, type mismatch) raise BindError and MUST
        propagate — swallowing them would silently reclassify an invalid
        literal as a computed expression."""
        from .binder import _fold

        e = _fold(e)
        # constant arithmetic (incl. unary minus, which parses as 0 - x)
        if isinstance(e, P.Bin) and e.op in ("+", "-", "*", "/"):
            lv = Session._literal(e.left, T.FLOAT64)
            rv = Session._literal(e.right, T.FLOAT64)
            if lv is None or rv is None:
                return None
            v = {"+": lv + rv, "-": lv - rv, "*": lv * rv,
                 "/": lv / rv}[e.op]
            e = P.NumLit(v)
        if isinstance(e, P.NullLit):
            return None
        if isinstance(e, P.NumLit):
            v = e.value
            if t.family is T.Family.DECIMAL:
                scaled = float(v) * (10 ** t.scale)
                if abs(scaled - round(scaled)) > 1e-6:
                    raise BindError(
                        f"literal {v} has more than {t.scale} decimal places"
                    )
                return int(round(scaled))
            if t.family is T.Family.FLOAT:
                return float(v)
            return int(v)
        if isinstance(e, P.DateLit):
            return int((np.datetime64(e.value) -
                        np.datetime64("1970-01-01")).astype(int))
        if isinstance(e, (P.Bin,)):
            raise NotALiteral("expression references columns")
        if isinstance(e, P.StrLit):
            if t.family is T.Family.DATE:
                # postgres coerces 'YYYY-MM-DD' literals to DATE in
                # context. Explicit 'D' unit: an unqualified datetime64
                # infers resolution from the string, so a timestamp-shaped
                # literal would silently store MINUTES as a day count
                try:
                    return int((np.datetime64(e.value, "D") -
                                np.datetime64("1970-01-01", "D")
                                ).astype(int))
                except ValueError as err:
                    raise BindError(
                        f"invalid DATE literal {e.value!r}: {err}"
                    ) from None
            if t.family is not T.Family.STRING:
                raise BindError("string literal for non-STRING column")
            return e.value  # KVTable dictionary-encodes on insert
        raise NotALiteral(f"not a literal: {e}")

    def _insert(self, stmt: P.Insert):
        t = self._kv_table(stmt.table)
        names = stmt.columns or t.schema.names
        for n in names:
            if n not in t.schema.names:
                raise BindError(f"unknown column {n!r}")
        if stmt.select is not None:
            res = Binder(self.catalog).bind(stmt.select).run()
            if len(res) != len(names):
                raise BindError(
                    f"INSERT ... SELECT produces {len(res)} columns, "
                    f"target list has {len(names)}"
                )
            cols = list(res.values())
            nrows = len(cols[0]) if cols else 0
            rows = []
            keys = list(res.keys())
            for i in range(nrows):
                rows.append({
                    names[j]: _from_result(res[keys[j]][i],
                                           t.schema.type_of(names[j]))
                    for j in range(len(names))
                })
        else:
            # columnar VALUES path (colenc discipline: encode columns, not
            # rows — the vectorized write path; sql/colenc in the
            # reference). Literals land in per-column lists and batch-
            # encode through KVTable.insert_rows.
            per_name: dict[str, list] = {n: [] for n in names}
            for vals in stmt.rows:
                if len(vals) != len(names):
                    raise BindError(
                        f"INSERT row has {len(vals)} values, expected "
                        f"{len(names)}"
                    )
                for n, v in zip(names, vals):
                    per_name[n].append(
                        self._literal(v, t.schema.type_of(n))
                    )
            missing = set(t.schema.names) - set(names)
            if missing:
                raise BindError(f"columns {sorted(missing)} need values "
                                "(defaults not supported)")
            nrows = len(stmt.rows)
            cols: dict[str, np.ndarray] = {}
            valids: dict[str, np.ndarray] = {}
            for n in names:
                vals = per_name[n]
                typ = t.schema.type_of(n)
                valid = np.array([v is not None for v in vals], dtype=bool)
                if not valid.all():
                    valids[n] = valid
                if typ.family is T.Family.STRING:
                    cols[n] = np.array(
                        ["" if v is None else v for v in vals],
                        dtype=object,
                    )
                elif typ.family is T.Family.FLOAT:
                    cols[n] = np.array(
                        [0.0 if v is None else float(v) for v in vals],
                        dtype=np.float64,
                    )
                else:
                    cols[n] = np.array(
                        [0 if v is None else int(v) for v in vals],
                        dtype=np.int64,
                    )
            if t.pk in valids:
                raise BindError("NULL primary key")

            def vop(txn):
                t.insert_rows(txn, cols, valids)

            self._run_write(vop)
            return {"rows_affected": nrows}
        missing = set(t.schema.names) - set(names)
        if missing:
            raise BindError(f"columns {sorted(missing)} need values "
                            "(defaults not supported)")

        def op(txn):
            for r in rows:
                t.insert(txn, r)

        self._run_write(op)
        return {"rows_affected": len(rows)}

    def _affected(self, t: KVTable, where: P.Node | None,
                  extra_cols: list[tuple[str, P.Node]] = ()):
        """Plan WHERE + SET expressions through the columnar engine; returns
        host rows of (pk, full current row, computed extras)."""
        rel = Rel.scan(self.catalog, t.name)
        if where is not None:
            binder = Binder(self.catalog)
            folded = binder._replace_scalar_subqueries(where)
            rel = rel.filter(ExprLowerer(rel).lower(folded))
        items = [(n, ExprLowerer(rel).lower(P.Ident(None, n)))
                 for n in t.schema.names]
        for name, e in extra_cols:
            items.append((f"__set_{name}", ExprLowerer(rel).lower(e)))
        rel = rel.project(items)
        return rel.run()

    def _update(self, stmt: P.Update):
        t = self._kv_table(stmt.table)
        # literal SETs (incl. string literals, whose dictionary code may not
        # exist yet) evaluate host-side; column-referencing SETs compute
        # through the columnar engine alongside the WHERE scan
        const_sets: dict[str, object] = {}
        computed_sets: list[tuple[str, P.Node]] = []
        for col, e in stmt.sets:
            if col not in t.schema.names:
                raise BindError(f"unknown column {col!r}")
            if col == t.pk:
                raise BindError("updating the PRIMARY KEY is not supported")
            try:
                const_sets[col] = self._literal(e, t.schema.type_of(col))
            except NotALiteral:
                computed_sets.append((col, e))
        computed = {c for c, _ in computed_sets}
        pk_t = t.schema.type_of(t.pk)

        def op(txn):
            # the affected-row scan runs INSIDE the txn closure at the TXN'S
            # snapshot (own intents visible — statements earlier in an
            # explicit txn are seen), so a retry recomputes it, and each row
            # is re-read through the txn (get_row_txn tracks the read span)
            # — a writer interleaving between scan and commit fails the
            # commit-time refresh and retries instead of being silently
            # overwritten (lost update)
            with self._read_as(txn):
                res = self._affected(t, stmt.where, computed_sets)
            n = len(res[t.pk])
            written = 0
            for i in range(n):
                pk = _from_result(res[t.pk][i], pk_t)
                cur = t.get_row_txn(txn, pk)
                if cur is None:
                    continue  # deleted since the scan; refresh validates
                row = {}
                for cname, typ in zip(t.schema.names, t.schema.types):
                    if cname in computed:
                        row[cname] = _from_result(res[f"__set_{cname}"][i],
                                                  typ)
                    elif cname in const_sets:
                        row[cname] = const_sets[cname]
                    else:
                        # unmodified columns come from the TRACKED read,
                        # not the untracked scan snapshot
                        row[cname] = cur[cname]
                t.insert(txn, row)  # MVCC: a new version at the txn ts
                written += 1
            return written

        n = self._run_write(op)
        return {"rows_affected": n}

    def _delete(self, stmt: P.Delete):
        t = self._kv_table(stmt.table)
        pk_t = t.schema.type_of(t.pk)

        def op(txn):
            with self._read_as(txn):
                res = self._affected(t, stmt.where)
            deleted = 0
            for v in res[t.pk]:
                pk = _from_result(v, pk_t)
                if t.get_row_txn(txn, pk) is None:
                    continue  # already gone; the tracked read validates
                t.delete_pk(txn, pk)
                deleted += 1
            return deleted

        n = self._run_write(op)
        return {"rows_affected": n}


def _from_result(v, t: T.SQLType):
    """Convert a materialized result value back to the row-encoding domain
    (to_host descales DECIMAL to float and decodes STRING dictionaries;
    re-scale / re-encode for storage)."""
    if v is None:
        return None
    if t.family is T.Family.STRING:
        return str(v)  # KVTable dictionary-encodes on insert
    if t.family is T.Family.DECIMAL:
        return int(round(float(v) * (10 ** t.scale)))
    if t.family is T.Family.FLOAT:
        return float(v)
    if t.family is T.Family.BOOL:
        return bool(v)
    return int(v)
