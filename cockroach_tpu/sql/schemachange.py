"""Schema changer — ALTER TABLE ADD/DROP COLUMN as a backfill job.

Reference: pkg/sql/schemachanger runs declarative schema changes as jobs
with checkpointed backfill progress (legacy path: sql/backfill.go +
rowexec backfillers); the new column becomes visible only when the
backfill completes and the descriptor version swaps.

Reduction: one job type ("schema_change") that rewrites every row of the
target table from the old value layout to the new one in pk-ordered
chunks, checkpointing {last_pk} in the job record after each chunk — a
crash mid-backfill resumes at the checkpoint, and already-rewritten rows
are recognized by their value WIDTH (add/drop always changes the fixed
row width), so re-running a chunk is idempotent. The catalog descriptor
swaps only after the backfill finishes. Concurrent DML during the change
is out of scope (single-session discipline; the reference's online
delete-only/write-only states are the non-reduced version of this).
"""

from __future__ import annotations


from ..coldata import types as T
from ..storage import rowcodec

CHUNK_ROWS = 512


def _type_of(cdef) -> T.SQLType:
    """ColumnDef -> SQLType (the session's _col_type, importable here)."""
    from .session import _col_type

    return _col_type(cdef)


def plan_alter(catalog, db, stmt) -> dict:
    """Validate an AlterTable statement and build the job payload."""
    from .binder import BindError
    from .session import _col_type

    tbl = catalog.tables.get(stmt.name)
    if tbl is None:
        raise BindError(f"unknown table {stmt.name!r}")
    from ..kv.table import KVTable

    if not isinstance(tbl, KVTable):
        raise BindError("ALTER TABLE targets KV-backed tables")
    if stmt.action == "add":
        c = stmt.column
        if c.name in tbl.schema.names:
            raise BindError(f"column {c.name!r} already exists")
        t = _col_type(c)
        new_names = tbl.schema.names + (c.name,)
        new_types = tbl.schema.types + (t,)
        default = None
        if stmt.default is not None:
            from .session import Session

            default = Session._literal(stmt.default, t)
            if hasattr(default, "item"):
                default = default.item()
        elif c.not_null:
            raise BindError(
                "ADD COLUMN NOT NULL requires a DEFAULT (existing rows "
                "must get a value)"
            )
        payload = {
            "table": stmt.name, "action": "add", "col": c.name,
            "type": str(t), "default": default,
            "coldef": {"name": c.name, "type_name": c.type_name,
                       "precision": c.precision, "scale": c.scale,
                       "not_null": c.not_null},
        }
        if t.family is T.Family.STRING:
            # the companion dictionary id is allocated NOW and carried in
            # the payload (a crash-resume must land entries in the same
            # span the final descriptor will name)
            dict_id = tbl.dict_table_id
            if dict_id is None:
                used = set()
                for other in catalog.tables.values():
                    if isinstance(other, KVTable):
                        used.add(other.table_id)
                        if other.dict_table_id is not None:
                            used.add(other.dict_table_id)
                dict_id = max(used, default=0) + 1
            payload["dict_table_id"] = dict_id
            if default is not None:
                # the default string becomes dictionary code 0 for the
                # new column; backfilled rows store the code
                payload["string_default"] = str(default)
                payload["default"] = 0
    else:
        if stmt.drop_name == tbl.pk:
            raise BindError("cannot drop the PRIMARY KEY column")
        if stmt.drop_name not in tbl.schema.names:
            raise BindError(f"unknown column {stmt.drop_name!r}")
        new_names = tuple(n for n in tbl.schema.names if n != stmt.drop_name)
        new_types = tuple(
            t for n, t in zip(tbl.schema.names, tbl.schema.types)
            if n != stmt.drop_name
        )
        payload = {"table": stmt.name, "action": "drop",
                   "col": stmt.drop_name}
    new_schema = T.Schema(new_names, new_types)
    need = rowcodec.value_width(new_schema)
    if db.engine.val_width < need:
        raise BindError(
            f"new row width {need} exceeds engine value width "
            f"{db.engine.val_width}"
        )
    return payload


def _schemas_for(catalog, payload):
    """(old_schema, new_schema, kvtable) from the payload + the catalog's
    CURRENT (pre-swap) descriptor — stable across crash-resume because the
    descriptor only swaps at completion."""
    from .parser import ColumnDef

    tbl = catalog.tables[payload["table"]]
    old = tbl.schema
    if payload["action"] == "add":
        cd = payload["coldef"]
        c = ColumnDef(cd["name"], cd["type_name"], cd["precision"],
                      cd["scale"], False, cd["not_null"])
        new = T.Schema(old.names + (c.name,), old.types + (_type_of(c),))
    else:
        keep = [i for i, n in enumerate(old.names) if n != payload["col"]]
        new = T.Schema(tuple(old.names[i] for i in keep),
                       tuple(old.types[i] for i in keep))
    return old, new, tbl


def backfill(reg, job, catalog) -> None:
    """The schema_change resumer: chunked rewrite + checkpoint + swap.

    Crash-idempotence: a resume AFTER the descriptor already swapped must
    not derive schemas from the post-swap descriptor (it would re-apply
    the change on top of itself). Completion is a DURABLE progress flag
    committed in the same txn as the descriptor swap — never inferred
    from the catalog's column set, which a later user ALTER could have
    changed back."""
    payload = job.payload
    durable = reg.load(job.job_id)
    if durable is not None and durable.progress.get("swapped"):
        job.progress.update(durable.progress)
        return
    if durable is not None:
        job.progress.update(durable.progress)  # fresh resume state
    old, new, tbl = _schemas_for(catalog, payload)
    old_w = rowcodec.value_width(old)
    db = reg.db
    start, end = rowcodec.table_span(tbl.table_id)
    last_pk = job.progress.get("last_pk")
    default = payload.get("default")
    colname = payload.get("col")
    sdef = payload.get("string_default")
    if sdef is not None:
        # persist the default as dictionary code 0 of the NEW column's
        # position (idempotent put: resume re-writes the same entry)
        new_pos = len(new.names) - 1
        enc = sdef.encode("utf-8")
        db.put(
            rowcodec.encode_pk(payload["dict_table_id"],
                               (new_pos << 40) | 0),
            len(enc).to_bytes(2, "little") + enc,
        )
    while True:
        lo = (rowcodec.encode_pk(tbl.table_id, last_pk + 1)
              if last_pk is not None else start)
        rows = db.scan(lo, end, max_keys=CHUNK_ROWS)
        if not rows:
            break

        def rewrite(t, rows=rows):
            done_pk = None
            for k, v in rows:
                pk = rowcodec.decode_pk(k)
                done_pk = pk
                if len(v) != old_w:
                    continue  # already the new layout (resumed chunk)
                row = rowcodec.decode_row(old, v)
                if payload["action"] == "add":
                    row[colname] = default
                else:
                    row.pop(colname, None)
                t.put(k, rowcodec.encode_row(new, row))
            return done_pk

        last_pk = db.txn(rewrite)
        job.progress["last_pk"] = int(last_pk)
        reg.checkpoint(job)
    _swap_descriptor(catalog, db, tbl, new, payload, reg=reg, job=job)


def _remap_dict_span(db, tbl, new_schema, reg=None, job=None) -> None:
    """The persistent string dictionaries key on COLUMN POSITION
    ((col << 40) | code, kv/table.py): a drop that shifts later STRING
    columns left must rewrite their entries to the new positions, and a
    dropped STRING column's entries are deleted.

    NOT re-runnable (a second pass would treat already-moved entries as
    the dropped column's and delete them), so the job's remapped flag
    commits IN THE SAME TXN as the moves — and that txn re-reads the
    DURABLE job record (not the caller's in-memory copy) plus the
    claimant's liveness epoch, so a fenced-out stale node that wakes
    after its replacement finished cannot run the moves again (the
    Registry.checkpoint fencing discipline)."""
    if tbl.dict_table_id is None:
        return
    if job is not None:
        # durable fast path: a resume after the remap committed skips the
        # full dict-span scan (the in-txn fenced re-check below stays the
        # correctness gate)
        durable = reg.load(job.job_id)
        if durable is not None and durable.progress.get("dict_remapped"):
            job.progress.setdefault("dict_remapped", True)
            return
    old_pos = {n: i for i, n in enumerate(tbl.schema.names)}
    new_pos = {n: i for i, n in enumerate(new_schema.names)}
    moves: dict[int, int | None] = {}
    for n, i in old_pos.items():
        if tbl.schema.types[i].family is not T.Family.STRING:
            continue
        moves[i] = new_pos.get(n)  # None: column dropped
    if all(src == dst for src, dst in moves.items()):
        return
    start, end = rowcodec.table_span(tbl.dict_table_id)
    rows = db.scan(start, end)

    def rewrite(t):
        if job is not None:
            cur = _fenced_job_read(reg, job, t)
            if cur.progress.get("dict_remapped"):
                return
        for k, v in rows:
            pk = rowcodec.decode_pk(k)
            col, code = pk >> 40, pk & ((1 << 40) - 1)
            if col not in moves or moves[col] == col:
                continue
            t.delete(k)
            dst = moves[col]
            if dst is not None:
                t.put(rowcodec.encode_pk(tbl.dict_table_id,
                                         (dst << 40) | code), v)
        if job is not None:
            job.progress["dict_remapped"] = True
            reg._write(t, job)

    db.txn(rewrite)


def _fenced_job_read(reg, job, t):
    """Read the DURABLE job record inside txn `t`, verifying this node
    still owns the claim at its believed epoch (Registry.checkpoint's
    fence, shared by every non-re-runnable schema-change txn)."""
    from ..kv.jobs import _PREFIX

    rows = t.scan(reg._chunk_key(job.job_id, 0),
                  _PREFIX + b"%08d.\xff" % job.job_id)
    cur = (reg._from_chunks(job.job_id, rows) if rows else job)
    if (cur.claim_node, cur.claim_epoch) != (job.claim_node,
                                             job.claim_epoch):
        raise RuntimeError(
            f"job {job.job_id} was re-adopted by node {cur.claim_node} "
            f"(epoch {cur.claim_epoch}); this claimant is stale"
        )
    if reg.liveness is not None and job.claim_node == reg.node_id:
        rec = reg.liveness._read(reg.node_id, t)
        if rec is not None and rec.epoch != job.claim_epoch:
            from ..kv.liveness import EpochFencedError

            raise EpochFencedError(
                f"node {reg.node_id} epoch {rec.epoch} != claim epoch "
                f"{job.claim_epoch}"
            )
    return cur


def _swap_descriptor(catalog, db, tbl, new_schema, payload,
                     reg=None, job=None) -> None:
    """Install the new schema: fresh KVTable over the same spans, persist
    the descriptor, replace the catalog entry (descriptor-version bump)."""
    from ..kv.table import KVTable, write_descriptor

    _remap_dict_span(db, tbl, new_schema, reg=reg, job=job)
    # an added STRING column's dict id was allocated at plan time (the
    # backfill already wrote entries into that span)
    dict_id = payload.get("dict_table_id", tbl.dict_table_id)
    nt = KVTable(db, tbl.name, new_schema, pk=tbl.pk,
                 table_id=tbl.table_id, dict_table_id=dict_id)

    def swap(t):
        if job is not None:
            _fenced_job_read(reg, job, t)
        # descriptor chunks + durable completion marker in ONE txn: a
        # crash leaves either the old schema with no marker (resume
        # re-runs safely) or the new schema with the marker (resume
        # finishes immediately) — never the corrupting in-between
        write_descriptor(db, nt, writer=t)
        if job is not None:
            job.progress["swapped"] = True
            reg._write(t, job)

    db.txn(swap)
    catalog.tables[tbl.name] = nt


def register_schema_change_job(registry, catalog) -> None:
    def resume(reg, job):
        backfill(reg, job, catalog)

    registry.register("schema_change", resume)
