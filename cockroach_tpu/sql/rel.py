"""Relational plan builder — the optbuilder analog.

Reference: pkg/sql/opt/optbuilder turns ASTs into a typed relational tree,
resolving names against the catalog. Here ``Rel`` is a fluent builder over the
plan IR that tracks output schema and string dictionaries as the plan grows,
so string literals resolve to dictionary codes and string predicates become
host-prepared CodeLookup tables at plan time (TPC-H queries in
bench/queries.py are written against this API; it is also the user-facing
"dataframe" surface of the framework)."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable

import numpy as np

from ..catalog import Catalog
from ..coldata.batch import Dictionary
from ..coldata.types import Schema, SQLType, Family
from ..flow.runtime import run_plan
from ..ops import aggregation as agg_ops
from ..ops import expr as ex
from ..ops import join as join_ops
from ..ops import sort as sort_ops
from ..plan import spec as S


@dataclass
class Rel:
    catalog: Catalog
    plan: S.PlanNode
    schema: Schema
    dicts: dict[int, Dictionary] = field(default_factory=dict)

    # -- name resolution ----------------------------------------------------

    def idx(self, name: str) -> int:
        return self.schema.index(name)

    def c(self, name: str) -> ex.ColRef:
        return ex.ColRef(self.idx(name))

    def type_of(self, name: str) -> SQLType:
        return self.schema.type_of(name)

    def str_lit(self, col: str, value: str) -> ex.Const:
        """Literal of a dictionary-coded string column -> its code."""
        i = self.idx(col)
        code = self.dicts[i].code_of(value)
        from ..coldata.types import INT32

        return ex.Const(code, INT32)

    def str_eq(self, col: str, value: str) -> ex.Expr:
        return ex.Cmp("eq", self.c(col), self.str_lit(col, value))

    def str_in(self, col: str, values: list[str]) -> ex.Expr:
        i = self.idx(col)
        d = self.dicts[i]
        table = np.zeros(max(1, len(d)), dtype=bool)
        for v in values:
            code = d.code_of(v)
            if code >= 0:
                table[code] = True
        return ex.CodeLookup(col=i, table=table)

    def str_pred(self, col: str, fn: Callable[[str], bool]) -> ex.Expr:
        """Arbitrary string predicate (LIKE etc.) evaluated per dictionary
        entry on the host, becoming a device gather."""
        i = self.idx(col)
        d = self.dicts[i]
        table = np.array([bool(fn(str(v))) for v in d.values])
        if len(table) == 0:
            table = np.zeros(1, dtype=bool)
        return ex.CodeLookup(col=i, table=table)

    def str_cmp(self, col: str, op: str, value: str) -> ex.Expr:
        """Range comparison on strings via the dictionary's rank table."""
        import operator

        fns = {"lt": operator.lt, "le": operator.le, "gt": operator.gt,
               "ge": operator.ge}
        return self.str_pred(col, lambda s: fns[op](s, value))

    def str_transform(self, col: str,
                      fn: Callable[[str], str]) -> tuple[ex.Expr, Dictionary]:
        """String-valued function of a STRING column (SUBSTRING etc.),
        evaluated per dictionary entry on the host: returns a STRING
        expression (a code-remap gather on device) plus the transformed
        values' Dictionary — attach it when projecting (see with_dict)."""
        from ..coldata.types import STRING

        i = self.idx(col)
        d = self.dicts[i]
        mapped = np.array([fn(str(v)) for v in d.values], dtype=object)
        uvals, codes = (np.unique(mapped.astype(str), return_inverse=True)
                        if len(mapped) else (np.array([], dtype=object),
                                             np.zeros(0, np.int32)))
        table = codes.astype(np.int32) if len(codes) else np.zeros(1, np.int32)
        return (ex.CodeLookup(col=i, table=table, out_type=STRING),
                Dictionary(uvals.astype(object)))

    def with_dict(self, col: str, d: Dictionary) -> "Rel":
        """Attach a dictionary to a STRING output column (for columns whose
        dictionary the projection machinery cannot infer, e.g. outputs of
        str_transform). Must directly follow a project(); the override is
        recorded on the Project plan node so the operator layer sees it."""
        i = self.idx(col)
        if not isinstance(self.plan, S.Project):
            raise TypeError("with_dict must follow a project()")
        plan = S.Project(self.plan.input, self.plan.exprs, self.plan.names,
                         self.plan.dict_overrides + ((i, d),))
        out = Rel(self.catalog, plan, self.schema, dict(self.dicts))
        out.dicts[i] = d
        return out

    # -- relational operators ----------------------------------------------

    @staticmethod
    def scan(catalog: Catalog, table: str,
             cols: tuple[str, ...] | None = None) -> "Rel":
        t = catalog.get(table)
        names = cols or t.schema.names
        idxs = tuple(t.schema.index(n) for n in names)
        schema = t.schema.select(idxs)
        full = t.dict_by_index()
        dicts = {i: full[ci] for i, ci in enumerate(idxs) if ci in full}
        return Rel(catalog, S.TableScan(table, tuple(names)), schema, dicts)

    def filter(self, pred: ex.Expr) -> "Rel":
        return Rel(self.catalog, S.Filter(self.plan, pred), self.schema,
                   dict(self.dicts))

    def project(self, items: list[tuple[str, ex.Expr]]) -> "Rel":
        names = tuple(n for n, _ in items)
        exprs = tuple(e for _, e in items)
        types = tuple(ex.expr_type(e, self.schema) for e in exprs)
        dicts = {
            i: self.dicts[e.idx]
            for i, (_, e) in enumerate(items)
            if isinstance(e, ex.ColRef) and e.idx in self.dicts
        }
        return Rel(self.catalog, S.Project(self.plan, exprs, names),
                   Schema(names, types), dicts)

    def select(self, *names: str) -> "Rel":
        return self.project([(n, self.c(n)) for n in names])

    def groupby(self, by: list[str],
                aggs: list[tuple]) -> "Rel":
        """aggs: (output name, func, input col name or None) — string_agg
        takes a 4th element, the separator."""
        gcols = tuple(self.idx(n) for n in by)
        specs = tuple(
            agg_ops.AggSpec(
                a[1], None if a[2] is None else self.idx(a[2]), a[0],
                *((a[3],) if len(a) > 3 else ()),
            )
            for a in aggs
        )
        # dense-state path: all keys dictionary-coded with small product
        from ..utils import settings as _settings

        key_sizes = None
        if (gcols and all(i in self.dicts for i in gcols)
                and _settings.get("sql.distsql.dense_agg.enabled")):
            sizes = tuple(len(self.dicts[i]) for i in gcols)
            prod = 1
            for s in sizes:
                prod *= s + 1  # +1 NULL code per column
            # the one-hot dense path does O(rows*G) work: only worth it for
            # genuinely small G (sort path is O(rows log rows) otherwise)
            if 0 < prod <= 256 and all(
                sp.func in ("sum", "count", "count_rows", "min", "max",
                            "avg", "any_not_null")
                for sp in specs
            ):
                key_sizes = sizes
        node = S.Aggregate(self.plan, gcols, specs, key_sizes=key_sizes)
        names = tuple([self.schema.names[i] for i in gcols] +
                      [s[0] for s in aggs])
        types = []
        for i in gcols:
            types.append(self.schema.types[i])
        for a in aggs:
            name, f, cn = a[0], a[1], a[2]
            spec = agg_ops.AggSpec(f, None if cn is None else self.idx(cn), name)
            if f == "avg":
                from ..coldata.types import FLOAT64

                types.append(FLOAT64)
            else:
                types.append(agg_ops.agg_output_type(spec, self.schema))
        dicts = {
            by.index(self.schema.names[i]): self.dicts[i]
            for i in gcols
            if i in self.dicts
        }
        return Rel(self.catalog, node, Schema(names, tuple(types)), dicts)

    def scalar_agg(self, aggs: list[tuple[str, str, str | None]]) -> "Rel":
        specs = tuple(
            agg_ops.AggSpec(f, None if cn is None else self.idx(cn), name)
            for name, f, cn in aggs
        )
        node = S.ScalarAggregate(self.plan, specs)
        names, types = [], []
        for name, f, cn in aggs:
            names.append(name)
            if f == "avg":
                from ..coldata.types import FLOAT64

                types.append(FLOAT64)
            else:
                spec = agg_ops.AggSpec(f, None if cn is None else self.idx(cn), name)
                types.append(agg_ops.agg_output_type(spec, self.schema))
        return Rel(self.catalog, node, Schema(tuple(names), tuple(types)), {})

    def sort(self, keys: list[tuple[str, bool]]) -> "Rel":
        sk = tuple(sort_ops.SortKey(self.idx(n), desc=d) for n, d in keys)
        return Rel(self.catalog, S.Sort(self.plan, sk), self.schema,
                   dict(self.dicts))

    def limit(self, n: int, offset: int = 0) -> "Rel":
        return Rel(self.catalog, S.Limit(self.plan, n, offset), self.schema,
                   dict(self.dicts))

    def distinct(self, cols: list[str] | None = None) -> "Rel":
        idxs = (tuple(self.idx(n) for n in cols)
                if cols else tuple(range(len(self.schema))))
        schema = self.schema.select(idxs)
        dicts = {
            idxs.index(i): d for i, d in self.dicts.items() if i in idxs
        }
        return Rel(self.catalog, S.Distinct(self.plan, idxs), schema, dicts)

    def window(self, partition_by: list[str], order_by: list[tuple[str, bool]],
               funcs: list[tuple[str, str, str | None]],
               running: bool = False, frame: tuple | None = None,
               frame_kind: str = "rows",
               exclude: str = "no_others") -> "Rel":
        """funcs: (output name, window func, input col name or None).
        running=True selects the cumulative frame for aggregates; `frame`
        is the general ROWS BETWEEN spec as (preceding, following) row
        counts with None meaning UNBOUNDED — e.g. frame=(2, 0) is ROWS
        BETWEEN 2 PRECEDING AND CURRENT ROW. frame_kind='range' reads the
        bounds as ORDER-BY-VALUE offsets instead (RANGE BETWEEN)."""
        from ..ops import sort as sort_ops
        from ..ops import window as win_ops

        pcols = tuple(self.idx(n) for n in partition_by)
        okeys = tuple(sort_ops.SortKey(self.idx(n), desc=d)
                      for n, d in order_by)
        specs = tuple(
            win_ops.WindowSpec(
                a[1], None if a[2] is None else self.idx(a[2]), a[0],
                running=running, frame=frame, frame_kind=frame_kind,
                exclude=exclude,
                **({"offset": a[3]} if len(a) > 3 else {}),
            )
            for a in funcs
        )
        node = S.Window(self.plan, pcols, okeys, specs)
        schema = win_ops.window_output_schema(self.schema, specs)
        dicts = dict(self.dicts)
        base = len(self.schema)
        for i, sp in enumerate(specs):  # string-valued window outputs
            if (sp.col is not None and sp.col in self.dicts
                    and sp.func in ("lag", "lead", "min", "max",
                                    "first_value", "last_value")):
                dicts[base + i] = self.dicts[sp.col]
        return Rel(self.catalog, node, schema, dicts)

    def merge_join(self, build: "Rel", on,
                   how: str = "inner") -> "Rel":
        """Merge join (sorted-key binary search, no hashing). `on` is one
        (probe_col, build_col) pair or a list of pairs (composite key,
        compared lexicographically)."""
        from ..ops import join as join_ops

        pairs = [on] if isinstance(on[0], str) else list(on)
        pk = tuple(self.idx(p) for p, _ in pairs)
        bk = tuple(build.idx(b) for _, b in pairs)
        if len(pairs) == 1:
            pk, bk = pk[0], bk[0]
        spec = join_ops.JoinSpec(how, build_unique=False)
        node = S.MergeJoin(self.plan, build.plan, pk, bk, spec)
        if how in ("semi", "anti"):
            schema, dicts = self.schema, dict(self.dicts)
        else:
            schema = self.schema.concat(build.schema)
            dicts = dict(self.dicts)
            off = len(self.schema)
            for i, d in build.dicts.items():
                dicts[off + i] = d
        return Rel(self.catalog, node, schema, dicts)

    def join(self, build: "Rel", on: list[tuple[str | int, str | int]],
             how: str = "inner", build_unique: bool = True) -> "Rel":
        """inner | left | right | full | semi | anti. `on` pairs accept
        column names or POSITIONS (positions are the only sound reference
        once self-joins duplicate names). Right and full outer
        compose from the primitive kernels the way the reference's hash
        joiner emits unmatched build rows after the probe stream
        (hashjoiner.go emitUnmatched): the matched part (inner for right,
        left-outer for full) UNION ALL the build-side anti join against the
        probe, null-extended over the probe columns."""
        def _pk(r: "Rel", c) -> int:
            return c if isinstance(c, int) else r.idx(c)

        if how in ("right", "full"):
            matched = self.join(build, on,
                                how="inner" if how == "right" else "left",
                                build_unique=build_unique)
            rev = [(b, p) for (p, b) in on]
            unmatched = build.join(self, on=rev, how="anti",
                                   build_unique=False)
            exprs = tuple(ex.Const(None, t) for t in self.schema.types)
            exprs = exprs + tuple(ex.ColRef(i)
                                  for i in range(len(build.schema)))
            names = self.schema.names + build.schema.names
            off = len(self.schema)
            overrides = tuple((off + i, d) for i, d in build.dicts.items())
            node = S.Project(unmatched.plan, exprs, names, overrides)
            ne = Rel(self.catalog, node, matched.schema,
                     {off + i: d for i, d in build.dicts.items()})
            return matched.union_all(ne)
        pkeys = tuple(_pk(self, l) for l, _ in on)
        bkeys = tuple(_pk(build, r) for _, r in on)
        spec = join_ops.JoinSpec(how, build_unique)
        node = S.HashJoin(self.plan, build.plan, pkeys, bkeys, spec)
        if how in ("semi", "anti"):
            schema, dicts = self.schema, dict(self.dicts)
        else:
            schema = self.schema.concat(build.schema)
            dicts = dict(self.dicts)
            off = len(self.schema)
            for i, d in build.dicts.items():
                dicts[off + i] = d
        return Rel(self.catalog, node, schema, dicts)

    def union_all(self, other: "Rel") -> "Rel":
        """UNION ALL (bag semantics, like the reference's unordered
        synchronizer over same-schema streams)."""
        if len(self.schema) != len(other.schema):
            raise ValueError("UNION ALL inputs must have equal arity")
        for i, (lt, rt) in enumerate(zip(self.schema.types,
                                         other.schema.types)):
            if lt.family is not rt.family:
                raise ValueError(
                    f"UNION ALL column {i}: {lt} vs {rt} (type families "
                    "must match)"
                )
        for i in set(self.dicts) & set(other.dicts):
            if self.dicts.get(i) is not other.dicts.get(i):
                raise ValueError(
                    "UNION ALL over STRING columns requires a shared "
                    "dictionary (codes are dictionary-relative)"
                )
        # a column with a dictionary on only ONE side is allowed solely for
        # provably all-NULL arms (e.g. outer joins' null-extended side);
        # non-NULL codes from the dict-less side would decode through the
        # wrong/absent dictionary — enforced, not assumed
        def _all_null_col(rel: "Rel", i: int) -> bool:
            p = rel.plan
            return (isinstance(p, S.Project)
                    and isinstance(p.exprs[i], ex.Const)
                    and p.exprs[i].value is None)

        for i in set(self.dicts) ^ set(other.dicts):
            dictless = other if i in self.dicts else self
            if (self.schema.types[i].family is Family.STRING
                    and not _all_null_col(dictless, i)):
                raise ValueError(
                    f"UNION ALL column {i}: one arm is dictionary-coded and "
                    "the other is not provably all-NULL; codes would decode "
                    "through the wrong dictionary"
                )
        node = S.Union((self.plan, other.plan))
        return Rel(self.catalog, node, self.schema, dict(self.dicts))

    def cross_join(self, build: "Rel") -> "Rel":
        """Cross join via a constant join key (every probe row matches the
        single-key build side; the general-duplicate join emits the full
        product — crossJoiner role, sized for small build sides)."""
        lk = self.project(
            [(n, self.c(n)) for n in self.schema.names] + [("__k", ex.lit(1))]
        )
        rk = build.project(
            [(n, build.c(n)) for n in build.schema.names]
            + [("__k", ex.lit(1))]
        )
        j = lk.join(rk, on=[("__k", "__k")], how="inner", build_unique=False)
        np_, nb = len(self.schema), len(build.schema)
        keep = list(range(np_)) + list(range(np_ + 1, np_ + 1 + nb))
        items = [(j.schema.names[i], ex.ColRef(i)) for i in keep]
        return j.project(items)

    # -- execution ----------------------------------------------------------

    def optimized_plan(self) -> S.PlanNode:
        """Plan after local optimization passes (index selection —
        plan/indexopt.py; top-k pushdown — plan/topkopt.py). Distribution
        has its own rewrite."""
        from ..plan.indexopt import use_indexes
        from ..plan.topkopt import push_topk

        return push_topk(use_indexes(self.plan, self.catalog))

    def run(self) -> dict[str, np.ndarray]:
        return run_plan(self.optimized_plan(), self.catalog)

    def run_distributed(self, mesh=None,
                        broadcast_rows: int | None = None
                        ) -> dict[str, np.ndarray]:
        """Execute distributed over the device mesh: the plan is rewritten
        with Exchange/Broadcast/Gather stages (plan/distribute.py) and
        lowered into one SPMD program (parallel/planner.py)."""
        from ..parallel import mesh as mesh_mod
        from ..parallel.planner import DistributedQuery

        if mesh is None:
            mesh = mesh_mod.make_mesh()
        return DistributedQuery(
            self.plan, self.catalog, mesh, broadcast_rows=broadcast_rows
        ).run()

    def explain_distributed(self, broadcast_rows: int | None = None) -> str:
        """EXPLAIN of the distributed plan (Exchange/Broadcast/Gather
        stages visible). Pass the same broadcast_rows as run_distributed
        to see the plan that would actually execute."""
        from ..parallel.planner import _needs_local
        from ..plan.distribute import distribute
        from ..plan.explain import explain_plan

        if _needs_local(self.plan):
            # run_distributed falls back to local execution for this plan
            # (checkSupportForPlanNode discipline) — show that truth
            return ("distribution: local (plan not distributable)\n"
                    + explain_plan(self.plan))
        return explain_plan(
            distribute(self.plan, self.catalog, broadcast_rows)
        )

    def explain(self) -> str:
        from ..plan.explain import explain_plan

        return explain_plan(self.optimized_plan())

    def explain_analyze(self) -> tuple[str, dict[str, np.ndarray]]:
        """Run with ComponentStats collection; returns (rendered tree,
        results) — the EXPLAIN ANALYZE surface."""
        from ..flow.runtime import run_plan_with_stats
        from ..plan.explain import explain_analyze

        plan = self.optimized_plan()
        res, root = run_plan_with_stats(plan, self.catalog)
        return explain_analyze(plan, root), res
